"""Fused whole-tree-on-device leaf-wise learner.

The TPU production path: the entire leaf-wise tree build — histogram
construction, best-split scans, the argmax over leaves, and the data
partition — runs as ONE jitted program per tree, with zero host round-trips.
This is the TPU answer to the reference's CUDA learner
(reference: src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:158-260),
which keeps all state device-resident but still drives each split from the
host: here even the per-split control flow (which leaf to split next) stays
on device, because the host link may be a high-latency tunnel and a single
D2H sync per split would dominate the runtime.

Structure: ``fori_loop`` over the ``num_leaves-1`` splits. Row-sized work
(gathering a leaf's rows for histograms; partitioning the chosen leaf) runs
in inner ``while_loop``s over fixed-width chunks — static shapes, dynamic
trip counts — so device time is proportional to actual rows touched, keeping
the histogram-subtraction trick's O(min(|L|,|R|)) economics
(reference: serial_tree_learner.cpp:408-476) inside a fully-compiled program.
"""
from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import Config
from ..data.dataset import BinnedDataset
from ..ops.histogram import gh_contract
from ..ops.partition import decision_go_left
from ..ops.split import (K_MIN_SCORE, SplitParams, calculate_leaf_output,
                         gather_threshold_split, leaf_gain, per_feature_best)
from .learner import SerialTreeLearner, _next_pow2
from .tree import Tree

HIST_C = 3


class DeviceTree(NamedTuple):
    """One trained tree, resident on device."""
    node_feature: jax.Array      # i32 [NODES] (inner feature index)
    node_threshold: jax.Array    # i32 [NODES]
    node_default_left: jax.Array  # bool [NODES]
    node_is_cat: jax.Array       # bool [NODES]
    node_cat_bits: jax.Array     # u32 [NODES, 8]
    node_left: jax.Array         # i32 [NODES] (>=0 node, <0 ~leaf)
    node_right: jax.Array        # i32 [NODES]
    node_gain: jax.Array         # f32 [NODES]
    node_value: jax.Array        # f32 [NODES] parent output
    node_weight: jax.Array       # f32 [NODES] parent hess sum
    node_count: jax.Array        # f32 [NODES]
    leaf_value: jax.Array        # f32 [L]
    leaf_weight: jax.Array       # f32 [L]
    leaf_count: jax.Array        # f32 [L]
    leaf_depth: jax.Array        # i32 [L]
    leaf_parent_node: jax.Array  # i32 [L]
    num_leaves: jax.Array        # i32 scalar
    row_leaf: jax.Array          # i32 [N] leaf id per training row


class FusedTreeLearner(SerialTreeLearner):
    """Whole-tree-per-dispatch learner. Reuses SerialTreeLearner's dataset
    plumbing (bin meta, split params, feature sampling)."""

    def __init__(self, dataset: BinnedDataset, config: Config) -> None:
        super().__init__(dataset, config)
        if self.residency == "stream":
            # out-of-core mode (docs/performance.md): the binned matrix
            # stays in host shards; _train_tree_stream drives per-tree
            # multi-dispatch builds whose kernels replicate the fused
            # program's math window-for-window. EFB bundling is skipped
            # (its construction needs the full resident matrix) and the
            # options _stream_blockers lists fell back to hbm upstream.
            self.bundled = False
            self.Bb = self.B
            self.chunk = self._pick_chunk()
            self.quant = False
            self.quant_exact = False
            self.forced_seq = None
            self._need_step_keys = False
            self.axis: Optional[str] = None
            self.voting = False
            self.pack32 = False
            self._srows_dummy = jnp.zeros((1, 1), jnp.uint32)
            self.last_row_leaf: Optional[jax.Array] = None
            self._init_stream_jits()
            return
        # EFB: histograms and partitions run over the bundled matrix when
        # the dataset built one; histograms are un-bundled back to feature
        # space before every split scan, and partition decisions decode the
        # chosen feature's bin from its bundle column
        bun = dataset.ensure_bundle(config)
        self.bundled = bun is not None
        if self.bundled:
            hx = bun.cols
            self.Bb = _next_pow2(max(bun.num_bins))
            self.bcol = jnp.asarray(bun.col_of)
            self.boff = jnp.asarray(bun.off_of)
            self.bsingle = jnp.asarray(bun.single)
            from ..data.bundling import unbundle_map
            src, kind = unbundle_map(
                bun, np.asarray(dataset.feature_num_bins, np.int32),
                np.asarray([dataset.mappers[j].default_bin
                            for j in dataset.used_features], np.int32),
                self.B, self.Bb)
            self.ub_src = jnp.asarray(src)
            self.ub_kind = jnp.asarray(kind)
        else:
            hx = dataset.binned
            self.Bb = self.B
        self._place_binned(np.asarray(hx))
        self.chunk = self._pick_chunk()
        # quantized-gradient training (reference: GradientDiscretizer,
        # src/treelearner/gradient_discretizer.hpp): int8 grad/hess levels
        # with stochastic rounding; on TPU the histogram contraction runs
        # as an int8 MXU matmul with exact int32 accumulation
        from ..ops.hist_pallas import MAX_QUANT_BINS, exact_accum_limit
        self.quant = bool(config.use_quantized_grad)
        # int8-level histograms accumulate into int32 only WITHIN one
        # W-row chunk (cross-chunk accumulation is float32, chunk_hist), so
        # the worst in-chunk sum is chunk*MAX_QUANT_BINS — overflow would
        # need a chunk of ~16.9M rows; guard the configurable chunk width,
        # not num_data
        if self.chunk * MAX_QUANT_BINS >= 2**31 - 1:
            from ..utils import log
            log.fatal("tpu_rows_per_block=%d makes the histogram chunk too "
                      "large for int32 accumulation", config.tpu_rows_per_block)
        # exact integer histogram reduction (reference: the 16/32-bit integer
        # reduce paths, src/treelearner/data_parallel_tree_learner.cpp:283-298):
        # accumulate RAW int levels across chunks (int32 under Pallas,
        # integer-valued f32 under the one-hot path) and apply the gradient
        # scales only after the cross-shard psum. Integer sums are
        # order-independent, so the distributed reduction is deterministic
        # for any shard count. Falls back to per-chunk scaled f32 when the
        # worst-case level sum could overflow the accumulator
        # (exact_accum_limit — the same helper config validation queries
        # for the num_grad_quant_bins bound).
        if self.quant:
            qb = config.num_grad_quant_bins   # config-validated int in
            # [2, MAX_QUANT_BINS]; the old silent min(.., 127) cap is gone
            limit = exact_accum_limit(self.hist_impl)
            self.quant_exact = dataset.num_data * qb < limit
            if not self.quant_exact:
                from ..utils import log
                log.warning("quantized histogram level sums may exceed the "
                            "exact accumulator range (%d rows x %d levels); "
                            "using per-chunk scaled float32 accumulation",
                            dataset.num_data, qb)
        else:
            self.quant_exact = False
        if self.quant:
            self._qkey = jax.random.PRNGKey(config.data_random_seed + 7919)
        # forced splits (reference: serial_tree_learner.cpp:624 ForceSplits):
        # the BFS order fixes which leaf id each forced node splits (root=0;
        # the split at step k hands its right child leaf id k+1), so the
        # whole forcing schedule is three static arrays consumed by the
        # fused program's step loop; an invalid forced split flips the
        # carried `forcing` flag off (the abort_last_forced_split analog)
        self.forced_seq = None
        if self.forced_json is not None:
            self.forced_seq = self._build_forced_seq(config.num_leaves - 1)
        self._need_step_keys = (self.extra_on
                                or config.feature_fraction_bynode < 1.0)
        if self._need_step_keys:
            # independent streams, like the host learner's separate RNGs:
            # extra_seed drives random thresholds, feature_fraction_seed
            # drives by-node sampling — changing one never perturbs the other
            self._ekey = jax.random.PRNGKey(config.extra_seed)
            self._bkey = jax.random.PRNGKey(config.feature_fraction_seed + 7)
        # when set (FusedDataParallelTreeLearner), _train_tree_impl runs as
        # the per-shard body of a shard_map over this mesh axis: rows are
        # sharded, histograms are psum-ed over ICI after each chunked local
        # accumulation, and everything derived from histograms (gains, split
        # choices, leaf values) is replicated-by-construction
        self.axis: Optional[str] = None
        # voting mode: keep histograms local, vote top-k features, psum
        # only voted columns (set by FusedVotingParallelTreeLearner)
        self.voting: bool = False
        # u32-lane packing of the gathered row matrix (A/B knob; see the
        # pack32 block in _pack_rows)
        self.pack32 = os.environ.get("LAMBDAGAP_PACK32", "1") != "0"
        # tree_layout=sorted (docs/performance.md): the packed row matrix
        # is (re)built leaf-ordered by a separate jitted pre-pass per tree
        # — dispatched under the layout_apply telemetry span so its cost
        # tiles the iteration wall — and then carried through the fused
        # program, which applies the permutation delta of each split
        # physically to only that leaf's slice. The buffer is donated: it
        # is per-tree scratch and aliasing it in place saves one
        # N*(C+8)-byte copy at loop entry.
        self._srows_dummy = jnp.zeros((1, 1), jnp.uint32)
        self._layout_jit = jax.jit(self._build_sorted_impl,
                                   static_argnames=("has_mask",))
        donate_srows = (self.layout == "sorted"
                        and jax.default_backend() == "tpu")  # CPU/GPU can't
        self._train_jit = jax.jit(
            self._train_tree_impl, static_argnames=("has_mask",),
            donate_argnums=(6,) if donate_srows else ())
        self.last_row_leaf: Optional[jax.Array] = None

    def _build_forced_seq(self, nodes: int):
        """Flatten the forced-split JSON into per-step (leaf, feature, bin)
        arrays in BFS order. Truncates at the first unmappable node."""
        fl, ff, ft = [], [], []
        q = [(self.forced_json, 0)]
        while q and len(fl) < nodes:
            node, leaf = q.pop(0)
            fb = self._forced_bin(node)
            if fb is None:
                break
            k, thr_bin = fb
            step = len(fl)
            fl.append(leaf)
            ff.append(k)
            ft.append(thr_bin)
            for key, child in (("left", leaf), ("right", step + 1)):
                ch = node.get(key)
                if (isinstance(ch, dict) and "feature" in ch
                        and "threshold" in ch):
                    q.append((ch, child))
        if not fl:
            return None
        on = np.zeros(nodes, dtype=bool)
        on[:len(fl)] = True
        pad = nodes - len(fl)
        return (np.asarray(fl + [0] * pad, np.int32),
                np.asarray(ff + [0] * pad, np.int32),
                np.asarray(ft + [0] * pad, np.int32), on)

    # device-layout hooks (overridden by FusedDataParallelTreeLearner) ----
    def _place_binned(self, hx: np.ndarray) -> None:
        """Upload the row-major binned matrix plus a column-major copy for
        cheap feature-column reads while partitioning (the analog of
        CUDAColumnData next to CUDARowData,
        reference: src/io/cuda/cuda_column_data.cpp). Under
        ``tree_layout=sorted`` the partition decodes the split feature from
        the sorted window itself, so the column-major copy would be N*C
        dead bytes of HBM — a tiny placeholder keeps the jit signature."""
        self.hx_rows = jnp.asarray(hx)
        if self.layout == "sorted":
            self.x_cols = jnp.zeros((1, 1), self.hx_rows.dtype)
        else:
            self.x_cols = jnp.asarray(np.ascontiguousarray(hx.T))

    # packed row-matrix layout -------------------------------------------
    def _window(self, N: int) -> int:
        """Chunk window of the while-loop'd row passes (shared by the
        training program and the sorted-layout pre-pass, whose pad row
        count must match)."""
        return min(self.chunk, _next_pow2(N))

    def _packed_meta(self, has_mask: bool):
        """Static column layout of the packed row matrix, in bin-dtype
        columns after the C binned columns: (gh_cols, q_cols, mask_col).

        * non-quant: 2 f32 grad/hess values bitcast to 8 (uint8) / 4
          (uint16) columns; the bagging mask rides one more column.
        * quant + sorted layout: the int8 (g_q, h_q) pair rides 2 uint8 /
          1 uint16 column(s) (+ mask column) so the physically reordered
          buffer carries everything the histogram pass reads.
        * quant + gather layout: nothing extra — gq/hq/mask are gathered
          by row index alongside the bins (the historical layout).
        """
        u8 = self.hx_rows.dtype == jnp.uint8
        if self.quant:
            if self.layout == "sorted":
                return 0, (2 if u8 else 1), bool(has_mask)
            return 0, 0, False
        return (8 if u8 else 4), 0, bool(has_mask)

    def _pack_rows(self, grad, hess, row_mask, x_rows, gq, hq,
                   has_mask: bool):
        """Pack the binned rows plus their per-row channels into ONE
        row-major matrix in the bin dtype, bitcast to u32 lanes (pack32):
        the histogram pass then runs ONE random gather per row window
        instead of two (the 8 B gh gather pays near-full random latency
        despite 3.5x fewer bytes than the row fetch; merging them removed
        it — measured 4.84 -> 4.64 s/iter at full HIGGS size), and one u32
        element carrying 4 binned uint8 columns (2 uint16) cuts the hot
        pass's element count ~4x (2x); lanes decode with one bitcast after
        the fetch (reference analog: cuda_row_data.hpp:32-117 packs rows
        by bit width for the same reason). Costs: one streaming repack
        pass per tree (~19 ms at 10.5M rows) and a second resident copy of
        the binned matrix, ~N*(C+8) bytes — ~380 MB at full HIGGS size
        against the chip's 16 GB."""
        gh_cols, q_cols, mask_col = self._packed_meta(has_mask)
        parts = [x_rows]
        if gh_cols:
            gh2 = jnp.stack([grad, hess], axis=1)           # [N, 2] f32
            if x_rows.dtype == jnp.uint16:
                ghb = lax.bitcast_convert_type(gh2, jnp.uint16)   # [N,2,2]
            else:
                ghb = lax.bitcast_convert_type(gh2, jnp.uint8)    # [N,2,4]
            parts.append(ghb.reshape(ghb.shape[0], -1))
        if q_cols:
            if x_rows.dtype == jnp.uint16:
                parts.append(lax.bitcast_convert_type(
                    jnp.stack([gq, hq], axis=1), jnp.uint16)[:, None])
            else:
                parts.append(jnp.stack(
                    [lax.bitcast_convert_type(gq, jnp.uint8),
                     lax.bitcast_convert_type(hq, jnp.uint8)], axis=1))
        if mask_col:
            parts.append(row_mask.astype(x_rows.dtype)[:, None])
        packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts,
                                                                  axis=1)
        if self.pack32:
            lane_n = 4 if packed.dtype == jnp.uint8 else 2
            P0 = packed.shape[1]
            padc = (-P0) % lane_n
            if padc:
                packed = jnp.concatenate(
                    [packed, jnp.zeros((packed.shape[0], padc),
                                       packed.dtype)], axis=1)
            packed = lax.bitcast_convert_type(
                packed.reshape(packed.shape[0], (P0 + padc) // lane_n,
                               lane_n), jnp.uint32)          # [N, P32]
        return packed

    def _build_sorted_impl(self, grad, hess, row_mask, x_rows, gq, hq, *,
                           has_mask: bool):
        """The ``tree_layout=sorted`` pre-pass: (re)build the physically
        leaf-ordered packed row buffer for one tree. Each tree starts from
        the identity permutation, so this is a pure streaming repack (no
        gather); gradients change every iteration, which is why the buffer
        cannot persist across trees. The W trailing pad rows let every
        window read in the fused program be a clamp-free dynamic slice
        (the same invariant as the permutation buffer's)."""
        packed = self._pack_rows(grad, hess, row_mask, x_rows, gq, hq,
                                 has_mask)
        W = self._window(x_rows.shape[0])
        return jnp.concatenate(
            [packed, jnp.zeros((W, packed.shape[1]), packed.dtype)])

    @staticmethod
    def _chunk_override() -> Optional[int]:
        """Debug/bench knob: LAMBDAGAP_CHUNK forces the window size (used
        for the measured W sweeps in the bench notes). Rounded to a power
        of two; malformed values are ignored loudly."""
        import os
        raw = os.environ.get("LAMBDAGAP_CHUNK")
        if not raw:
            return None
        try:
            return max(_next_pow2(int(raw)), 1 << 10)
        except ValueError:
            from ..utils import log
            log.warning("LAMBDAGAP_CHUNK=%r is not an integer; ignored", raw)
            return None

    def _pick_chunk(self) -> int:
        """Chunk window for the while-loop'd row passes: small enough that a
        deep (small) leaf doesn't pay a huge padded window of gather/scan
        work, large enough that root-sized passes don't drown in per-trip
        overhead.

        Sized off HALF the average leaf population N/num_leaves, not N:
        padding waste across one tree is ~num_leaves * W/2 rows against
        ~N*log2(L) total row-touches, so a window near the deep-leaf size
        keeps waste ~10% where an N-scaled window pays ~40% at the HIGGS
        shape (10.5M rows, 255 leaves; measured 5.21 vs 5.65 s/iter on the
        bench chip). The round-5 sweep under u32-lane packing moved the
        optimum one notch smaller still: W=32768 measured 4.44 s/iter vs
        65536's 4.61 and 131072's 5.05 at full HIGGS shape (replicated;
        one corrupted-window outlier excluded). Inside one compiled
        program extra while-loop trips cost only loop control, not kernel
        launches."""
        forced = self._chunk_override()
        if forced is not None:
            return forced
        cap = max(int(self.config.tpu_rows_per_block) * 16, 1 << 12)
        per_leaf = self.num_data // max(self.config.num_leaves, 8)
        return min(max(_next_pow2(max(per_leaf // 2, 1)), 1 << 12), cap)

    # ------------------------------------------------------------------
    def train_device(self, grad: jax.Array, hess: jax.Array,
                     row_mask: Optional[jax.Array] = None) -> DeviceTree:
        if self.residency == "stream":
            rec = self._train_tree_stream(grad, hess, row_mask)
            self.last_row_leaf = rec.row_leaf
            return rec
        fmask = self._feature_mask()
        mask = row_mask if row_mask is not None else jnp.ones(1, dtype=bool)
        if self.quant:
            from ..ops.hist_pallas import quantize_gradients
            self._qkey, sub = jax.random.split(self._qkey)
            gq, hq, gs, hs = quantize_gradients(
                grad, hess, sub, self.config.num_grad_quant_bins,
                self.config.stochastic_rounding)
        else:
            gq = hq = jnp.zeros(1, jnp.int8)
            gs = hs = jnp.float32(1.0)
        if self._need_step_keys:
            self._ekey, e = jax.random.split(self._ekey)
            self._bkey, b = jax.random.split(self._bkey)
            ekey = jnp.stack([e, b])            # [2, 2]: extra / by-node
        else:
            ekey = jnp.zeros((2, 2), jnp.uint32)
        if self.layout == "sorted":
            # the leaf-ordered packed buffer is rebuilt per tree; the span
            # makes its (streaming-repack) cost tile the iteration wall —
            # the in-program per-split permutation-apply rides the tree
            # span like the rest of the fused program
            with self.telemetry.phase("layout_apply"):
                srows = self._layout_jit(grad, hess, mask, self.hx_rows,
                                         gq, hq,
                                         has_mask=row_mask is not None)
        else:
            srows = self._srows_dummy
        from ..obs import costplane
        rec = costplane.observed_call(
            "train.fused", self._train_jit,
            (grad, hess, mask, fmask, self.hx_rows, self.x_cols, srows,
             gq, hq, gs, hs, ekey),
            dict(has_mask=row_mask is not None),
            bucket=int(grad.shape[0]), phase="tree")
        self.last_row_leaf = rec.row_leaf
        return rec

    def train(self, grad, hess, row_mask=None) -> Tree:
        """Host-Tree interface (used by tests / non-bench paths)."""
        return self.materialize(self.train_device(grad, hess, row_mask))

    # ------------------------------------------------------------------
    def materialize_batch(self, recs) -> list:
        """Fetch MANY DeviceTrees in one transfer: each field is stacked
        across trees on device, so the D2H cost is one buffer per field
        instead of one per (tree, field) — on the tunneled chip that is the
        difference between ~16 and ~16*T round-trips (the round-3 bench's
        20s+ first-predict wall was exactly this)."""
        if not recs:
            return []
        stacked = {k: jnp.stack([getattr(r, k) for r in recs])
                   for k in DeviceTree._fields if k != "row_leaf"}
        h = jax.device_get(stacked)
        return [self._tree_from_host({k: v[i] for k, v in h.items()})
                for i in range(len(recs))]

    def materialize(self, rec: DeviceTree) -> Tree:
        """Fetch a DeviceTree and build the host Tree model (one transfer;
        row_leaf stays on device — it is O(N))."""
        # graftlint: disable=R1 — THE materialization boundary of the fused
        # learner: one compact O(leaves) struct transfer per tree builds
        # the host model; scores already updated on device, so this is the
        # only per-tree D2H of the sync-free path
        h = jax.device_get({k: v for k, v in rec._asdict().items()
                            if k != "row_leaf"})
        return self._tree_from_host(h)

    def _tree_from_host(self, h) -> Tree:
        L = int(h["num_leaves"])
        nodes = max(L - 1, 0)
        tree = Tree(max_leaves=self.config.num_leaves)
        tree.num_leaves = max(L, 1)
        mt_codes = {"None": 0, "Zero": 1, "NaN": 2}
        for k in range(nodes):
            fi = int(h["node_feature"][k])
            j = self.dataset.used_features[fi]
            mapper = self.dataset.mappers[j]
            tree.split_feature.append(j)
            tree.split_feature_inner.append(fi)
            thr_bin = int(h["node_threshold"][k])
            tree.threshold_bin.append(thr_bin)
            tree.threshold_real.append(mapper.bin_to_value(thr_bin))
            tree.default_left.append(bool(h["node_default_left"][k]))
            tree.missing_type.append(mt_codes[mapper.missing_type])
            tree.left_child.append(int(h["node_left"][k]))
            tree.right_child.append(int(h["node_right"][k]))
            tree.split_gain.append(float(h["node_gain"][k]))
            is_cat = bool(h["node_is_cat"][k])
            tree.is_categorical.append(is_cat)
            bits = np.asarray(h["node_cat_bits"][k], dtype=np.uint32)
            tree.cat_bitset.append(bits)
            tree.cat_bitset_real.append(
                self._cat_bitset_real(fi, bits) if is_cat
                else np.zeros(8, np.uint32))
            tree.internal_value.append(float(h["node_value"][k]))
            tree.internal_weight.append(float(h["node_weight"][k]))
            tree.internal_count.append(int(h["node_count"][k]))
        Lb = tree.max_leaves
        tree.leaf_value[:Lb] = h["leaf_value"][:Lb]
        tree.leaf_weight[:Lb] = h["leaf_weight"][:Lb]
        tree.leaf_count[:Lb] = h["leaf_count"][:Lb].astype(np.int64)
        tree.leaf_depth[:Lb] = h["leaf_depth"][:Lb]
        tree.leaf_parent[:Lb] = h["leaf_parent_node"][:Lb]
        return tree

    # ------------------------------------------------------------------
    # the fused program
    # ------------------------------------------------------------------
    def _train_tree_impl(self, grad, hess, row_mask, fmask, x_rows, x_cols,
                         srows, gq, hq, gs, hs, ekey, *, has_mask: bool):
        """One whole tree as a single XLA program.

        Design notes for the ``fori_loop`` body (the per-split step):

        * No ``lax.cond``: an un-splittable step is expressed by masking —
          the partition/histogram loops get a zero row count (zero trips)
          and every state write lands on a dump row (index ``L`` / ``NODES``)
          instead of branching. This keeps the loop body straight-line and
          lets XLA alias the large carried buffers in place (a cond joining
          two 20+ MB states forced copies).
        * Per-leaf and per-node bookkeeping lives in a few consolidated
          matrices (``leaf_f``/``leaf_i``/``node_f``/``node_i``) so one split
          costs a handful of dynamic-update-slices instead of ~30 one-column
          kernels — per-split fixed cost is mostly kernel-launch count.
        * Both children's best-split scans run in one vmapped call.
        """
        cfg = self.config
        N = x_rows.shape[0]       # LOCAL rows (== num_data unless sharded)
        F = self.num_features
        B = self.B
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        W = min(self.chunk, _next_pow2(N))
        p = self.params
        max_depth = cfg.max_depth
        # x_rows [N, C] (bundled when EFB active) / x_cols [C, N] arrive as
        # jit ARGUMENTS: a closed-over matrix would be inlined into the HLO
        # as a dense constant, and at HIGGS size that 300+ MB payload
        # overflows the remote-compile transport (round 2: HTTP 413)
        C = x_rows.shape[1]
        Bb = self.Bb                    # bins per stored column
        bundled = self.bundled
        num_bins = self.num_bins_arr
        default_bins = self.default_bins_arr
        missing_types = self.missing_types_arr
        is_cat_arr = self.is_categorical_arr
        has_cat = self.has_categorical
        mono_on = self.mono_on
        mono_arr = self.mono_arr
        # monotone 'intermediate' runs IN-PROGRAM: sibling-output child
        # bounds + the cross-leaf constraint propagation as a vectorized
        # per-split state update over the leaf_f bounds columns, with eager
        # re-scans of tightened leaves (reference:
        # monotone_constraints.hpp:560-850 IntermediateLeafConstraints)
        inter = mono_on and self.mono_method == "intermediate"
        NPW_N = (NODES + 31) // 32 if inter else 1
        lane = jnp.arange(W, dtype=jnp.int32)
        bin_iota = jnp.arange(Bb, dtype=x_rows.dtype)
        quant = self.quant
        qexact = self.quant_exact
        # physical row layout (docs/performance.md). gather: grad+hess (and
        # the bagging mask) are PACKED INTO the binned row matrix and the
        # histogram pass gathers one packed row per visit (_pack_rows has
        # the full story + measured history). sorted: the packed matrix
        # arrives PRE-BUILT and leaf-ordered in ``srows`` (the layout_apply
        # pre-pass) and is carried through the split loop, which applies
        # each split's permutation delta physically to only that leaf's
        # slice — the histogram pass then reads contiguous streams at
        # stream bandwidth instead of issuing row gathers.
        layout_sorted = self.layout == "sorted"
        gh_cols, q_cols, mask_col = self._packed_meta(has_mask)
        pack32 = self.pack32
        if layout_sorted:
            packed_rows = None          # rows live in the carried srows
            SW = srows.shape[1]
        else:
            packed_rows = self._pack_rows(grad, hess, row_mask, x_rows,
                                          gq, hq, has_mask)

        def unpack(prow):
            """u32 lanes -> bin-dtype columns (no-op when pack32 is off)."""
            if pack32:
                return lax.bitcast_convert_type(
                    prow, x_rows.dtype).reshape(prow.shape[0], -1)
            return prow

        def srow_slice(buf, start):
            """Contiguous W-row window of the (N+W padded) sorted payload
            — a dynamic-slice DMA, the sorted layout's whole point."""
            # same pad invariant as perm_slice: starts stay <= N
            assert buf.shape[0] == N + W
            return lax.dynamic_slice(buf, (start, 0), (W, SW))

        def perm_slice(perm, start):
            """Contiguous W-row window of the (N+W padded) permutation —
            a dynamic-slice DMA, not a gather."""
            # every start is <= N and the buffer carries one full window of
            # padding, so the dynamic_slice clamp can never fire
            assert perm.shape[0] == N + W
            return lax.dynamic_slice(perm, (start,), (W,))

        def chunk_hist(perm, srows_c, begin, count, acc, c):
            """Histogram of the leaf rows at positions
            begin+cW : begin+(c+1)W — a permutation gather under the
            gather layout, a contiguous window DMA under sorted."""
            if layout_sorted:
                rows = None
                prow = unpack(srow_slice(srows_c, begin + c * W))
            else:
                rows = perm_slice(perm, begin + c * W)
                prow = unpack(packed_rows[rows])    # [W, C(+gh+mask)]
            valid = (c * W + lane) < count
            bins = prow[:, :C]
            if quant:
                if layout_sorted:
                    # int8 levels decoded out of the sorted payload
                    if x_rows.dtype == jnp.uint16:
                        qw = lax.bitcast_convert_type(prow[:, C], jnp.int8)
                        gq_w, hq_w = qw[:, 0], qw[:, 1]
                    else:
                        gq_w = lax.bitcast_convert_type(prow[:, C],
                                                        jnp.int8)
                        hq_w = lax.bitcast_convert_type(prow[:, C + 1],
                                                        jnp.int8)
                    if mask_col:
                        valid = valid & (prow[:, C + q_cols] > 0)
                else:
                    gq_w, hq_w = gq[rows], hq[rows]
                    if has_mask:
                        valid = valid & row_mask[rows]
                qscale = jnp.stack([gs, hs, jnp.float32(1.0)])
                if self.hist_impl == "pallas":
                    from ..ops.hist_pallas import hist_pallas_q, pack_ghq8
                    live = jnp.clip(count - c * W, 0, W)
                    ghq = pack_ghq8(gq_w, hq_w, valid)
                    hist_i = hist_pallas_q(bins, ghq, Bb, live)
                    if qexact:          # raw level sums; scaled post-psum
                        return acc + hist_i
                    return acc + hist_i.astype(jnp.float32) * qscale
                gsc = jnp.float32(1.0) if qexact else gs
                hsc = jnp.float32(1.0) if qexact else hs
                g = jnp.where(valid, gq_w.astype(jnp.float32) * gsc, 0.0)
                h = jnp.where(valid, hq_w.astype(jnp.float32) * hsc, 0.0)
                gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
                onehot = (bins[:, :, None] == bin_iota).astype(jnp.bfloat16)
                part = gh_contract(gh, onehot.reshape(W, C * Bb),
                                   self.hist_precision)
                return acc + part.reshape(HIST_C, C, Bb).transpose(1, 2, 0)
            if has_mask:
                valid = valid & (prow[:, C + gh_cols] > 0)
            ghr = lax.bitcast_convert_type(
                prow[:, C:C + gh_cols].reshape(W, 2, gh_cols // 2),
                jnp.float32)                            # [W, 2]
            if self.hist_impl == "pallas":
                from ..ops.hist_pallas import hist_pallas, pack_gh8
                live = jnp.clip(count - c * W, 0, W)
                gh8 = pack_gh8(ghr[:, 0], ghr[:, 1], valid)
                return acc + hist_pallas(bins, gh8, Bb, live)
            g = jnp.where(valid, ghr[:, 0], 0.0)
            h = jnp.where(valid, ghr[:, 1], 0.0)
            gh = jnp.stack([g, h, valid.astype(jnp.float32)], axis=1)
            onehot = (bins[:, :, None] == bin_iota).astype(jnp.bfloat16)
            part = gh_contract(gh, onehot.reshape(W, C * Bb),
                               self.hist_precision)
            return acc + part.reshape(HIST_C, C, Bb).transpose(1, 2, 0)

        def leaf_hist(perm, srows_c, begin, count):
            # jax.named_scope labels below tag the traced ops so profiler
            # windows (obs/profile.py) show the same histogram/partition/
            # split phase structure the host-side telemetry reports
            nch = (count + W - 1) // W

            def body(st):
                c, acc = st
                return c + 1, chunk_hist(perm, srows_c, begin, count, acc, c)

            acc_dtype = (jnp.int32 if qexact and self.hist_impl == "pallas"
                         else jnp.float32)
            with jax.named_scope("histogram"):
                _, hist = lax.while_loop(
                    lambda st: st[0] < nch, body,
                    (jnp.int32(0), jnp.zeros((C, Bb, HIST_C), acc_dtype)))
            if self.axis is not None and not self.voting:
                # the one collective per split: local chunk loops may run
                # different trip counts per shard (local leaf sizes differ),
                # but every shard reaches this psum exactly once per step.
                # In quant_exact mode the reduction is over raw integer level
                # sums — order-independent, hence deterministic for any shard
                # count (reference: the 16/32-bit integer ReduceScatter at
                # data_parallel_tree_learner.cpp:283-298).
                # Voting mode keeps histograms LOCAL: the collective moves
                # into best_of as a top-k vote + psum of only the voted
                # columns (reference: voting_parallel_tree_learner.cpp).
                hist = lax.psum(hist, self.axis)
            if qexact and not self.voting:
                hist = hist.astype(jnp.float32) * jnp.stack(
                    [gs, hs, jnp.float32(1.0)])
            # voting + quant_exact: keep RAW level sums — the exact integer
            # reduction happens per voted column inside best_of, scales after
            return hist

        extra_on = self.extra_on
        contri = self.contri_arr
        nb_m1 = self.nb_minus1_arr
        # interaction constraints, in-program (reference: col_sampler.hpp
        # interaction sets): each leaf carries a bitmask of features used on
        # its path; a feature is allowed iff some group contains path+{f}
        ic_on = self.ic_groups is not None
        if ic_on:
            PW = (F + 31) // 32
            gb = np.zeros((len(self.ic_groups), PW), np.uint32)
            gm = np.zeros((len(self.ic_groups), F), bool)
            for gi, g in enumerate(self.ic_groups):
                for f in g:
                    gb[gi, f // 32] |= np.uint32(1) << np.uint32(f % 32)
                    gm[gi, f] = True
            group_bits = jnp.asarray(gb)
            group_member = jnp.asarray(gm)
        else:
            PW = 1
        bynode_frac = float(cfg.feature_fraction_bynode)
        bynode_on = bynode_frac < 1.0

        def node_fmask(path_bits, rkey):
            """Per-leaf effective feature mask: interaction-set filtering +
            by-node sampling (reference: col_sampler.hpp GetByNode)."""
            m = fmask
            if ic_on:
                subset = jnp.all((path_bits[None, :] & ~group_bits) == 0,
                                 axis=1)                       # [G]
                # union of the groups containing the path; the empty path is
                # a subset of every group, so the root gets the union of ALL
                # groups — features outside every group are never usable
                # (matches the host learner's _node_fmask)
                m = m & jnp.any(subset[:, None] & group_member, axis=0)
            if bynode_on:
                r = jax.random.uniform(rkey, (F,))
                r = jnp.where(m, r, -jnp.inf)
                avail = jnp.sum(m.astype(jnp.int32))
                k = jnp.maximum(jnp.ceil(bynode_frac * avail), 1.0)
                rank = jnp.argsort(jnp.argsort(-r))
                m = m & (rank < k.astype(jnp.int32))
            return m

        voting = self.voting
        vote_k = int(getattr(self, "vote_k", 0)) if voting else 0
        # feature-parallel mode: rows replicated, COLUMNS sharded over this
        # axis; histograms need no collective at all — the per-split
        # traffic is one all_gather of per-shard best-split tuples (the
        # SyncUpGlobalBestSplit analog) plus a psum broadcast of the
        # winning feature's column for the partition
        # (reference: src/treelearner/feature_parallel_tree_learner.cpp)
        fax = getattr(self, "feat_axis", None)

        def best_of_feat(hist, pg, ph, pc, pout, lo, hi, depth, rkey, fm):
            """Feature-sharded best split: local scan over this shard's
            column block, then an all_gather of the D local winners and a
            replicated argmax. Tie-break matches the serial argmax exactly
            (first max in global feature order)."""
            C_loc = hist.shape[0]
            off = lax.axis_index(fax) * C_loc

            def sl(arr):
                # shards tile the padded feature axis exactly, so the
                # per-shard slice start can never clamp
                assert arr.shape[0] % C_loc == 0
                return lax.dynamic_slice_in_dim(arr, off, C_loc, axis=0)

            mono_l = sl(mono_arr)
            cons = (mono_l, lo, hi) if mono_on else None
            rand_t = None
            if extra_on:
                # replicated draw over the GLOBAL feature axis, sliced
                # locally. Drawn at the REAL feature count, then padded:
                # F here is the shard-padded program width, and a
                # (padded,)-shaped draw is a DIFFERENT prng stream than
                # the serial learner's (real,)-shaped one — the splits
                # would be legitimate but never comparable to serial
                # (pre-existing divergence unmasked by the ISSUE-8 combo
                # test rework). Pad columns get threshold 0: their fmask
                # is False and nb_minus1 is 1, so they can never win.
                rF = getattr(self, "_real_F", F)
                draw = jax.random.randint(rkey, (rF,), 0, 1 << 30)
                if rF != F:
                    draw = jnp.concatenate(
                        [draw, jnp.zeros(F - rF, draw.dtype)])
                rand_t = sl(draw % nb_m1)
            gain, thr, dl, lg, lh, lc, bits = per_feature_best(
                hist, pg, ph, pc, pout, sl(num_bins), sl(default_bins),
                sl(missing_types), sl(is_cat_arr), sl(fm), p, has_cat,
                constraints=cons, rand_thresholds=rand_t)
            parent_gain = leaf_gain(pg, ph, p, pc, pout)
            shift = parent_gain + p.min_gain_to_split
            mult = sl(contri) if contri is not None else None
            if mono_on and self.mono_penalty > 0:
                from ..ops.split import monotone_split_penalty
                mp = jnp.where(mono_l != 0,
                               monotone_split_penalty(depth,
                                                      self.mono_penalty),
                               1.0)
                mult = mp if mult is None else mult * mp
            if mult is not None:
                gain = jnp.where(jnp.isfinite(gain),
                                 (gain - shift) * mult + shift, gain)
            fl = jnp.argmax(gain, axis=0).astype(jnp.int32)
            lout_l = calculate_leaf_output(lg[fl], lh[fl], p, lc[fl], pout)
            rout_l = calculate_leaf_output(pg - lg[fl], ph - lh[fl], p,
                                           pc - lc[fl], pout)
            if mono_on:
                lout_l = jnp.clip(lout_l, lo, hi)
                rout_l = jnp.clip(rout_l, lo, hi)
            fields = (gain[fl], off + fl, thr[fl],
                      dl[fl].astype(jnp.int32),
                      sl(is_cat_arr)[fl].astype(jnp.int32), bits[fl],
                      lg[fl], lh[fl], lc[fl], lout_l, rout_l)
            gathered = [lax.all_gather(x, fax) for x in fields]   # [D, ...]
            win = jnp.argmax(gathered[0], axis=0).astype(jnp.int32)
            gw = gathered[0][win]
            g = gw - shift
            ok = jnp.isfinite(gw) & (g > 0.0)
            if max_depth > 0:
                ok = ok & (depth < max_depth)
            return (jnp.where(ok, g, K_MIN_SCORE), gathered[1][win],
                    gathered[2][win], gathered[3][win].astype(bool),
                    gathered[4][win].astype(bool), gathered[5][win],
                    gathered[6][win], gathered[7][win], gathered[8][win],
                    gathered[9][win], gathered[10][win])

        def best_of(hist, pg, ph, pc, pout, lo, hi, depth, rkey, fm):
            """Best split for one leaf, with the max_depth guard.
            Returns (gain, feat, thr, dl, cat, bits, lg, lh, lc, lout, rout).

            Voting mode (reference:
            src/treelearner/voting_parallel_tree_learner.cpp:151-184
            GlobalVoting + CopyLocalHistogram): ``hist`` is this shard's
            LOCAL histogram; each shard scans it against its local parent
            sums, proposes its top-k features, the votes all_gather, and
            only the voted columns psum — O(D·k·B) bytes on the wire per
            split instead of O(F·B) — before one global scan whose results
            scatter back into full-F arrays so the downstream argmax/
            penalty/monotone code is identical in all modes."""
            if fax is not None:
                return best_of_feat(hist, pg, ph, pc, pout, lo, hi, depth,
                                    rkey, fm)
            cons = (mono_arr, lo, hi) if mono_on else None
            rand_t = None
            if extra_on:
                # rkey is replicated, so every shard draws the same
                # thresholds: votes are scored by the same randomized gain
                # the final voted scan uses
                rand_t = jax.random.randint(rkey, (F,), 0, 1 << 30) % nb_m1
            if voting:
                ltr = jnp.sum(hist[0], axis=0)    # local parent sums (RAW
                # level sums in quant_exact mode — same units as hist)
                if bundled:
                    from ..ops.histogram import unbundle_hist
                    hist = unbundle_hist(hist, self.ub_src, self.ub_kind,
                                         ltr[0], ltr[1], ltr[2])
                if quant and qexact:
                    qsc = jnp.stack([gs, hs, jnp.float32(1.0)])
                    hist_s = hist.astype(jnp.float32) * qsc
                    lt = ltr.astype(jnp.float32) * qsc
                else:
                    hist_s, lt = hist, ltr
                lgain, *_ = per_feature_best(
                    hist_s, lt[0], lt[1], lt[2], jnp.float32(0.0), num_bins,
                    default_bins, missing_types, is_cat_arr, fm, p, has_cat,
                    rand_thresholds=rand_t)
                _, local_top = lax.top_k(lgain, vote_k)
                votes = lax.all_gather(local_top.astype(jnp.int32),
                                       self.axis, tiled=True)     # [D*k]
                # in quant_exact mode this psum reduces raw integer level
                # sums (exact, order-independent — the voted-column analog
                # of the full-histogram integer reduction in leaf_hist);
                # scales apply after
                hist_v = lax.psum(hist[votes], self.axis)
                if quant and qexact:
                    hist_v = hist_v.astype(jnp.float32) * qsc
                cons_v = (mono_arr[votes], lo, hi) if mono_on else None
                gain_v, thr_v, dl_v, lg_v, lh_v, lc_v, bits_v = \
                    per_feature_best(
                        hist_v, pg, ph, pc, pout, num_bins[votes],
                        default_bins[votes], missing_types[votes],
                        is_cat_arr[votes], fm[votes], p, has_cat,
                        constraints=cons_v,
                        rand_thresholds=(rand_t[votes]
                                         if rand_t is not None else None))
                # scatter voted results back to [F] (duplicate votes write
                # identical values)
                gain = jnp.full((F,), K_MIN_SCORE,
                                jnp.float32).at[votes].set(gain_v)
                thr = jnp.zeros((F,), jnp.int32).at[votes].set(thr_v)
                dl = jnp.zeros((F,), bool).at[votes].set(dl_v)
                lg = jnp.zeros((F,), jnp.float32).at[votes].set(lg_v)
                lh = jnp.zeros((F,), jnp.float32).at[votes].set(lh_v)
                lc = jnp.zeros((F,), jnp.float32).at[votes].set(lc_v)
                bits = jnp.zeros((F, 8), jnp.uint32).at[votes].set(bits_v)
            else:
                if bundled:
                    from ..ops.histogram import unbundle_hist
                    hist = unbundle_hist(hist, self.ub_src, self.ub_kind,
                                         pg, ph, pc)
                gain, thr, dl, lg, lh, lc, bits = per_feature_best(
                    hist, pg, ph, pc, pout, num_bins, default_bins,
                    missing_types, is_cat_arr, fm, p, has_cat,
                    constraints=cons, rand_thresholds=rand_t)
            parent_gain = leaf_gain(pg, ph, p, pc, pout)
            shift = parent_gain + p.min_gain_to_split
            mult = contri
            if mono_on and self.mono_penalty > 0:
                # depth-dependent monotone split penalty (reference:
                # serial_tree_learner.cpp:998)
                from ..ops.split import monotone_split_penalty
                mp = jnp.where(mono_arr != 0,
                               monotone_split_penalty(depth,
                                                      self.mono_penalty),
                               1.0)
                mult = mp if mult is None else mult * mp
            if mult is not None:
                # feature_contri / monotone penalty scale the post-shift
                # gain (reference: feature_histogram.hpp:174)
                gain = jnp.where(jnp.isfinite(gain),
                                 (gain - shift) * mult + shift, gain)
            f = jnp.argmax(gain, axis=0).astype(jnp.int32)
            g = gain[f] - shift
            ok = jnp.isfinite(gain[f]) & (g > 0.0)
            if max_depth > 0:
                ok = ok & (depth < max_depth)
            lout = calculate_leaf_output(lg[f], lh[f], p, lc[f], pout)
            rout = calculate_leaf_output(pg - lg[f], ph - lh[f], p,
                                         pc - lc[f], pout)
            if mono_on:
                lout = jnp.clip(lout, lo, hi)
                rout = jnp.clip(rout, lo, hi)
            return (jnp.where(ok, g, K_MIN_SCORE), f, thr[f], dl[f],
                    is_cat_arr[f], bits[f], lg[f], lh[f], lc[f], lout, rout)

        best_children = jax.vmap(best_of,
                                 in_axes=(0, 0, 0, 0, 0, 0, 0, None, 0, 0))

        # ------------------------------------------------------ state init
        # consolidated per-leaf/per-node state; row L / row NODES is the dump
        # row that masked-off writes land on
        # leaf_f columns: sum_g, sum_h, cnt, out, bgain, blg, blh, blc,
        #                 blout, brout, mono_min, mono_max
        # leaf_i columns: begin, count, depth, parent, is_left, bfeat, bthr,
        #                 bdl, bcat
        # node_f columns: gain, value, weight, count
        # node_i columns: feature, threshold, default_left, is_cat, left, right
        # W rows of padding let every window read be a clamped-free
        # dynamic slice; pad rows point at row 0 and are always masked
        perm0 = jnp.concatenate([jnp.arange(N, dtype=jnp.int32),
                                 jnp.zeros(W, jnp.int32)])
        hist_root = leaf_hist(perm0, srows, jnp.int32(0), jnp.int32(N))
        totals = jnp.sum(hist_root[0], axis=0)
        if fax is not None and self.axis is not None:
            # 2-D data x feature execution: hist_root[0] is each feature
            # shard's LOCAL column 0, so the f32 bin-sum above adds the
            # same rows in a different (bin-grouping) order per shard —
            # ulp-divergent parent sums would make the per-shard scans
            # disagree. Broadcast shard 0's totals so every shard scans
            # with bit-identical aggregates (exact under quantization,
            # and the contract the stream mirror replays).
            fidx = lax.axis_index(fax)
            totals = lax.psum(
                jnp.where(fidx == 0, totals, jnp.zeros_like(totals)), fax)
        if voting:
            # local root hist: global parent sums need their own (tiny) psum
            totals = lax.psum(totals, self.axis)
            if quant and qexact:
                # raw level sums -> gradient units (voting defers scaling
                # until after its collectives; see leaf_hist)
                totals = totals.astype(jnp.float32) * jnp.stack(
                    [gs, hs, jnp.float32(1.0)])
        root_out = calculate_leaf_output(totals[0], totals[1], p, totals[2],
                                         0.0)
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)
        # ekey carries TWO independent streams: [0] extra_trees random
        # thresholds, [1] by-node column sampling (separate seeds, like the
        # host learner's _extra_rng vs _col_rng)
        need_keys = extra_on or bynode_on
        xkey, bkey = ekey[0], ekey[1]
        root_key = jax.random.fold_in(xkey, NODES) if need_keys else xkey
        if ic_on or bynode_on:
            fm0 = node_fmask(jnp.zeros(PW, jnp.uint32),
                             jax.random.fold_in(bkey, NODES))
        else:
            fm0 = fmask
        (bg0, bf0, bt0, bdl0, bcat0, bbits0, blg0, blh0, blc0, blout0,
         brout0) = best_of(hist_root, totals[0], totals[1], totals[2],
                           root_out, neg_inf, pos_inf, jnp.int32(0),
                           root_key, fm0)

        iota_l1 = jnp.arange(L + 1, dtype=jnp.int32)
        f32 = jnp.float32
        i32 = jnp.int32
        leaf_f = jnp.zeros((L + 1, 12), f32)
        leaf_f = leaf_f.at[:, 4].set(K_MIN_SCORE) \
                       .at[:, 10].set(-jnp.inf).at[:, 11].set(jnp.inf)
        leaf_f = leaf_f.at[0].set(jnp.stack(
            [totals[0], totals[1], totals[2], root_out, bg0, blg0, blh0,
             blc0, blout0, brout0, neg_inf, pos_inf]))
        leaf_i = jnp.zeros((L + 1, 9), i32)
        # inactive leaves carry out-of-range begins so the final
        # position->leaf searchsorted never matches them
        leaf_i = leaf_i.at[:, 0].set(N + iota_l1).at[:, 3].set(-1)
        leaf_i = leaf_i.at[0].set(jnp.stack(
            [i32(0), i32(N), i32(0), i32(-1), i32(0), bf0, bt0,
             bdl0.astype(i32), bcat0.astype(i32)]))
        leaf_bits = jnp.zeros((L + 1, 8), jnp.uint32).at[0].set(bbits0)
        node_f = jnp.zeros((NODES + 1, 4), f32)
        node_i = jnp.zeros((NODES + 1, 6), i32).at[:, 4:6].set(~0)
        node_bits = jnp.zeros((NODES + 1, 8), jnp.uint32)
        state = dict(
            perm=perm0,
            perm_buf=jnp.zeros(N + W, jnp.int32),
            leaf_f=leaf_f, leaf_i=leaf_i, leaf_bits=leaf_bits,
            node_f=node_f, node_i=node_i, node_bits=node_bits,
            hist=jnp.zeros((L + 1, C, Bb, HIST_C), f32).at[0].set(hist_root),
            num_leaves=jnp.int32(1),
        )
        if layout_sorted:
            # the leaf-ordered payload + its partition double buffer ride
            # the carry so each split's permutation delta applies in place
            state["srows"] = srows
            state["srows_buf"] = jnp.zeros_like(srows)
        if ic_on:
            state["path"] = jnp.zeros((L + 1, PW), jnp.uint32)
        if inter:
            # per-leaf bin-space boxes ([lo, hi) per feature, root = full
            # range), per-leaf ancestor-node bitsets, the stale-scan marks,
            # and node parent/side pointers for the up-walk
            state["box_lo"] = jnp.zeros((L + 1, F), jnp.int32)
            state["box_hi"] = jnp.zeros((L + 1, F),
                                        jnp.int32).at[0].set(num_bins)
            state["npath"] = jnp.zeros((L + 1, NPW_N), jnp.uint32)
            state["stale"] = jnp.zeros(L + 1, bool)
            state["node_par"] = jnp.full(NODES + 1, -1, jnp.int32)
            state["node_side"] = jnp.zeros(NODES + 1, jnp.int32)

        forced = self.forced_seq
        if forced is not None:
            f_leaf = jnp.asarray(forced[0])
            f_feat = jnp.asarray(forced[1])
            f_thr = jnp.asarray(forced[2])
            f_on = jnp.asarray(forced[3])
            state["forcing"] = jnp.asarray(True)

        # ------------------------------------------------------ split step
        def split_step(k, st):
            if inter:
                # eager re-scan of every leaf whose bounds the previous
                # split's propagation tightened (the host learner re-scans
                # them inside apply_split; here the re-scan runs at the
                # start of the next step — before the argmax, so the
                # choice sees only fresh gains). Loop trips are derived
                # from replicated state, so every shard runs the same
                # number of (collective-bearing, under voting) re-scans.
                def rescan_cond(rst):
                    return jnp.any(rst[3][:L])

                def rescan_body(rst):
                    lf_c, li_c, lb_c, stale_c = rst
                    rl = jnp.argmax(stale_c[:L]).astype(jnp.int32)
                    lfr = lf_c[rl]
                    lir = li_c[rl]
                    if need_keys:
                        rk = jax.random.fold_in(
                            jax.random.fold_in(xkey, NODES + 1),
                            k * (L + 1) + rl)
                    else:
                        rk = xkey
                    if ic_on or bynode_on:
                        cp = (st["path"][rl] if ic_on
                              else jnp.zeros(PW, jnp.uint32))
                        fm_l = node_fmask(cp, jax.random.fold_in(
                            jax.random.fold_in(bkey, NODES + 1),
                            k * (L + 1) + rl))
                    else:
                        fm_l = fmask
                    (rg, rf, rt, rdl, rcat, rbits, rlg, rlh, rlc, rlout,
                     rrout) = best_of(st["hist"][rl], lfr[0], lfr[1],
                                      lfr[2], lfr[3], lfr[10], lfr[11],
                                      lir[2], rk, fm_l)
                    new_lf = jnp.stack([lfr[0], lfr[1], lfr[2], lfr[3],
                                        rg, rlg, rlh, rlc, rlout, rrout,
                                        lfr[10], lfr[11]])
                    new_li = jnp.stack([lir[0], lir[1], lir[2], lir[3],
                                        lir[4], rf, rt,
                                        rdl.astype(jnp.int32),
                                        rcat.astype(jnp.int32)])
                    return (lf_c.at[rl].set(new_lf),
                            li_c.at[rl].set(new_li),
                            lb_c.at[rl].set(rbits),
                            stale_c.at[rl].set(False))

                leaf_f, leaf_i, leaf_bits, stale = lax.while_loop(
                    rescan_cond, rescan_body,
                    (st["leaf_f"], st["leaf_i"], st["leaf_bits"],
                     st["stale"]))
            else:
                leaf_f, leaf_i = st["leaf_f"], st["leaf_i"]
                leaf_bits = st["leaf_bits"]
            leaf = jnp.argmax(leaf_f[:L, 4]).astype(jnp.int32)
            forcing_next = None
            fon = use_f = None
            if forced is not None:
                # gather the forced split's stats from the forced leaf's
                # histogram; if it is invalid (no positive gain / depth),
                # forcing aborts and THIS step falls back to the argmax best
                # split, so an abort costs no split budget (matching the
                # serial ForceSplits abort_last_forced_split behavior)
                fon = f_on[k] & st["forcing"]
                fleaf = f_leaf[k]
                flf = leaf_f[fleaf]
                fli = leaf_i[fleaf]
                hist_leaf = st["hist"][fleaf]
                if bundled:
                    from ..ops.histogram import unbundle_hist
                    histF = unbundle_hist(hist_leaf, self.ub_src, self.ub_kind,
                                          flf[0], flf[1], flf[2])
                else:
                    histF = hist_leaf
                fk = f_feat[k]
                res = gather_threshold_split(
                    histF[fk], flf[0], flf[1], flf[2], flf[3], fk, f_thr[k],
                    num_bins[fk], default_bins[fk], missing_types[fk],
                    is_cat_arr[fk], p,
                    bounds=(flf[10], flf[11]) if mono_on else None)
                fok = res.gain > 0.0
                if max_depth > 0:
                    fok = fok & (fli[2] < max_depth)
                forcing_next = st["forcing"] & jnp.where(f_on[k], fok, True)
                use_f = fon & fok
                leaf = jnp.where(use_f, fleaf, leaf)
            lf = leaf_f[leaf]
            li = leaf_i[leaf]
            ok = lf[4] > 0.0

            # the chosen split: the leaf's stored best, unless this step is
            # a (valid) forced one — then the gathered fixed split
            bgain = lf[4]
            feat = li[5]
            thrv, dlv, catv = li[6], li[7].astype(bool), li[8].astype(bool)
            bitsv = leaf_bits[leaf]
            blg, blh, blc = lf[5], lf[6], lf[7]
            blout, brout = lf[8], lf[9]
            if forced is not None:
                ok = jnp.where(use_f, True, ok)
                bgain = jnp.where(use_f, res.gain, bgain)
                feat = jnp.where(use_f, fk, feat)
                thrv = jnp.where(use_f, f_thr[k], thrv)
                dlv = jnp.where(use_f, res.default_left, dlv)
                catv = jnp.where(use_f, res.is_categorical, catv)
                bitsv = jnp.where(use_f, res.cat_bitset, bitsv)
                blg = jnp.where(use_f, res.left_sum_g, blg)
                blh = jnp.where(use_f, res.left_sum_h, blh)
                blc = jnp.where(use_f, res.left_count, blc)
                blout = jnp.where(use_f, res.left_output, blout)
                brout = jnp.where(use_f, res.right_output, brout)

            begin = li[0]
            count_eff = jnp.where(ok, li[1], 0)
            srows_cur = st["srows"] if layout_sorted else None
            if layout_sorted:
                # the split feature's bin value is decoded from the sorted
                # window itself inside pbody — no column gather, and no
                # column-major matrix at all (x_cols is a placeholder)
                col = None
                colidx = self.bcol[feat] if bundled else feat
            elif fax is not None:
                # the winning feature's column lives on ONE shard: psum
                # broadcasts it for the (row-replicated) partition — the
                # analog of the reference's best-split partition broadcast
                # (feature_parallel_tree_learner.cpp SyncUp + split apply)
                C_loc_p = x_cols.shape[0]
                f_loc = feat - lax.axis_index(fax) * C_loc_p
                owned = (f_loc >= 0) & (f_loc < C_loc_p)
                col_l = x_cols[jnp.clip(f_loc, 0, C_loc_p - 1)]
                # psum in the native bin dtype: exactly one shard is
                # nonzero, so no overflow — and the wire moves 1-2 B per
                # row instead of 4 (pbody casts to i32 as it reads)
                col = lax.psum(
                    jnp.where(owned, col_l, jnp.zeros_like(col_l)), fax)
            else:
                col = x_cols[self.bcol[feat] if bundled else feat]  # [N]
            nch = (count_eff + W - 1) // W
            perm_in = st["perm"]

            # -- chunked stable partition into perm_buf ----------------
            # under the sorted layout the SAME scatter positions route the
            # full packed row payload into srows_buf: the permutation
            # delta of this split applied physically, over only this
            # leaf's slice — positions form two monotone runs (lefts
            # ascending, rights descending), so the writes are two nearly
            # contiguous streams, not random scatters
            def pbody(s):
                if layout_sorted:
                    c, lcur, rcur, pbuf, sbuf = s
                else:
                    c, lcur, rcur, pbuf = s
                live = jnp.clip(count_eff - c * W, 0, W)
                valid = lane < live
                rows = perm_slice(perm_in, begin + c * W)
                if layout_sorted:
                    dw = srow_slice(srows_cur, begin + c * W)
                    cv = jnp.take(unpack(dw), colidx,
                                  axis=1).astype(jnp.int32)
                else:
                    cv = col[rows].astype(jnp.int32)
                if bundled:
                    # rank-decode the feature's bin out of its bundle column
                    r = cv - self.boff[feat]
                    d = default_bins[feat]
                    in_r = (r >= 0) & (r < num_bins[feat] - 1)
                    cv = jnp.where(self.bsingle[feat], cv,
                                   jnp.where(in_r, r + (r >= d), d))
                gl = decision_go_left(
                    cv, thrv, dlv, default_bins[feat],
                    missing_types[feat], num_bins[feat], catv, bitsv) & valid
                cums_gl = jnp.cumsum(gl.astype(jnp.int32))
                nl = cums_gl[W - 1]
                # valid lanes are a prefix, so the right-side rank needs no
                # second cumsum
                prefix_valid = jnp.minimum(lane + 1, live)
                lpos = lcur + cums_gl - 1
                # rights fill backward from the slice end: stable within a
                # chunk, chunk order reversed on the right side — a
                # deterministic permutation, only affecting later gather order
                rpos = rcur - (prefix_valid - cums_gl)
                pos = jnp.where(gl, lpos, jnp.where(valid, rpos, N))
                pbuf = pbuf.at[pos].set(rows, mode="drop")
                if layout_sorted:
                    sbuf = sbuf.at[pos].set(dw, mode="drop")
                    return c + 1, lcur + nl, rcur - (live - nl), pbuf, sbuf
                return c + 1, lcur + nl, rcur - (live - nl), pbuf

            with jax.named_scope("partition"):
                if layout_sorted:
                    _, lend, _, pbuf, sbuf = lax.while_loop(
                        lambda s: s[0] < nch, pbody,
                        (jnp.int32(0), begin, begin + count_eff,
                         st["perm_buf"], st["srows_buf"]))
                else:
                    _, lend, _, pbuf = lax.while_loop(
                        lambda s: s[0] < nch, pbody,
                        (jnp.int32(0), begin, begin + count_eff,
                         st["perm_buf"]))
                    sbuf = None
            left_count = lend - begin
            right_count = count_eff - left_count

            # copy the partitioned slice back into perm (chunked); both reads
            # and the write are contiguous-window DMAs, with the stale tail
            # of the last window re-written from perm itself. The sorted
            # payload copies back the same way — stream reads, stream write.
            def cbody(s):
                if layout_sorted:
                    c, pm, sr = s
                else:
                    c, pm = s
                # same window-pad invariant as perm_slice: starts stay
                # <= N, the W-row tail pad absorbs the last window
                assert pbuf.shape[0] == N + W
                start = begin + c * W
                valid = (c * W + lane) < count_eff
                vals = jnp.where(valid, perm_slice(pbuf, start),
                                 perm_slice(pm, start))
                pm = lax.dynamic_update_slice(pm, vals, (start,))
                if layout_sorted:
                    sw = jnp.where(valid[:, None], srow_slice(sbuf, start),
                                   srow_slice(sr, start))
                    sr = lax.dynamic_update_slice(sr, sw, (start, 0))
                    return c + 1, pm, sr
                return c + 1, pm

            with jax.named_scope("partition_copyback"):
                if layout_sorted:
                    _, perm, srows_new = lax.while_loop(
                        lambda s: s[0] < nch, cbody,
                        (jnp.int32(0), perm_in, srows_cur))
                else:
                    _, perm = lax.while_loop(lambda s: s[0] < nch, cbody,
                                             (jnp.int32(0), perm_in))
                    srows_new = None

            # -- masked write indices (dump rows swallow no-op steps) --
            # nodes are indexed by the number of REALIZED splits, not the
            # loop counter: a no-op step (e.g. an aborted forced split)
            # must not leave a hole in the node array
            new_leaf = st["num_leaves"]
            nidx = new_leaf - 1
            wl = jnp.where(ok, leaf, L)
            wn = jnp.where(ok, new_leaf, L)
            wk = jnp.where(ok, nidx, NODES)

            # parent node's child pointer now points at node k
            pnode = li[3]
            was_left = li[4].astype(bool)
            safe_p = jnp.where((pnode >= 0) & ok, pnode, NODES)
            prow = st["node_i"][safe_p]
            prow = jnp.where(was_left, prow.at[4].set(nidx),
                             prow.at[5].set(nidx))
            node_i = st["node_i"].at[safe_p].set(prow)

            # aggregates
            pg, ph, pc = lf[0], lf[1], lf[2]
            lg, lh, lc = blg, blh, blc
            rg, rh, rc = pg - lg, ph - lh, pc - lc
            lout, rout = blout, brout
            depth = li[2] + 1

            # children's monotone bounds. basic: the mid of the two outputs
            # caps the subtree on the constrained side; intermediate: each
            # child is capped by its SIBLING's output — looser, recovered
            # accuracy is the method's point (reference:
            # UpdateConstraintsWithOutputs, monotone_constraints.hpp:545)
            pmin, pmax = lf[10], lf[11]
            mono_f = mono_arr[feat]
            if inter:
                lcap, rcap = rout, lout
            else:
                lcap = rcap = (lout + rout) * 0.5
            lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, lcap), pmin)
            lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, lcap), pmax)
            rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, rcap), pmin)
            rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, rcap), pmax)

            node_f = st["node_f"].at[wk].set(
                jnp.stack([bgain, lf[3], ph, pc]))
            node_i = node_i.at[wk].set(jnp.stack(
                [feat, thrv, dlv.astype(jnp.int32), catv.astype(jnp.int32),
                 ~leaf, ~new_leaf]))
            node_bits = st["node_bits"].at[wk].set(bitsv)

            # -- children histograms (smaller built, larger by subtraction)
            if self.axis is None:
                small_is_left = left_count <= right_count
            else:
                # the side choice must be identical on every shard (each
                # shard's local hist feeds one psum); local partition counts
                # differ per shard, the scan's global (in-bag) counts do not
                small_is_left = lc <= pc - lc
            sb = jnp.where(small_is_left, begin, begin + left_count)
            sc = jnp.where(small_is_left, left_count, right_count)
            hist_small = leaf_hist(perm, srows_new, sb, sc)
            hist_large = st["hist"][leaf] - hist_small
            hist_left = jnp.where(small_is_left, hist_small, hist_large)
            hist_right = jnp.where(small_is_left, hist_large, hist_small)
            hist = st["hist"].at[wl].set(hist_left).at[wn].set(hist_right)

            # -- both children's best splits in one vmapped scan -------
            if extra_on or bynode_on:
                xstep = jax.random.fold_in(xkey, k)
                bstep = jax.random.fold_in(bkey, k)
                child_keys = jnp.stack([jax.random.fold_in(xstep, 0),
                                        jax.random.fold_in(xstep, 1)])
            else:
                bstep = bkey
                child_keys = jnp.zeros((2,) + xkey.shape, xkey.dtype)
            if ic_on:
                # children inherit the path plus the feature just split on
                pbit = jnp.where(
                    jnp.arange(PW, dtype=jnp.uint32)
                    == (feat // 32).astype(jnp.uint32),
                    jnp.left_shift(jnp.uint32(1),
                                   (feat % 32).astype(jnp.uint32)),
                    jnp.uint32(0))
                child_path = st["path"][leaf] | pbit
            if ic_on or bynode_on:
                cp = child_path if ic_on else jnp.zeros(PW, jnp.uint32)
                fms = jnp.stack([
                    node_fmask(cp, jax.random.fold_in(bstep, 2)),
                    node_fmask(cp, jax.random.fold_in(bstep, 3))])
            else:
                fms = jnp.broadcast_to(fmask, (2, F))
            with jax.named_scope("split_scan"):
                (bg2, bf2, bt2, bdl2, bcat2, bbits2, blg2, blh2, blc2,
                 blout2, brout2) = best_children(
                    jnp.stack([hist_left, hist_right]),
                    jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                    jnp.stack([lc, rc]), jnp.stack([lout, rout]),
                    jnp.stack([lmin, rmin]), jnp.stack([lmax, rmax]), depth,
                    child_keys, fms)

            i32 = jnp.int32
            lrow_f = jnp.stack([lg, lh, lc, lout, bg2[0], blg2[0], blh2[0],
                                blc2[0], blout2[0], brout2[0], lmin, lmax])
            rrow_f = jnp.stack([rg, rh, rc, rout, bg2[1], blg2[1], blh2[1],
                                blc2[1], blout2[1], brout2[1], rmin, rmax])
            lrow_i = jnp.stack([begin, left_count, depth, nidx, i32(1),
                                bf2[0], bt2[0], bdl2[0].astype(i32),
                                bcat2[0].astype(i32)])
            rrow_i = jnp.stack([begin + left_count, right_count, depth, nidx,
                                i32(0), bf2[1], bt2[1], bdl2[1].astype(i32),
                                bcat2[1].astype(i32)])

            if inter:
                # -- intermediate constraint propagation ---------------
                # The reference walks up from the new node; at every
                # monotone numeric ancestor it tightens the bounds of
                # leaves in the opposite subtree that stay contiguous to
                # the split leaf, using the new children's outputs
                # (GoUpToFindLeavesToUpdate / GoDownToFindLeavesToUpdate,
                # monotone_constraints.hpp:560-850). Here the recursive
                # down-walk collapses to vectorized [L] box tests: the
                # contiguity pruning is interval overlap between each
                # leaf's bin-space box and the split leaf's PRE-split box
                # on the features crossed so far, and the use-left/right
                # output choice is overlap with each child's range on the
                # split feature. Tightened leaves are marked stale and
                # eagerly re-scanned at the next step's start.
                plo_vec = st["box_lo"][leaf]           # [F] pre-split box
                phi_vec = st["box_hi"][leaf]
                lo_col = st["box_lo"]                  # [L+1, F]
                hi_col = st["box_hi"]
                sf_lo = lo_col[:, feat]                # [L+1] on the new
                sf_hi = hi_col[:, feat]                # split's feature
                # active leaves whose cached best split is still viable:
                # the reference skips leaves with best gain == kMinScore
                # (e.g. at max_depth) — tightening a dead leaf's bounds
                # only buys pointless re-scan loop trips (each bearing
                # collectives under voting), and bounds can never turn an
                # unsplittable leaf splittable (they only shrink gain)
                splittable = leaf_f[:, 4] > K_MIN_SCORE
                if max_depth > 0:
                    splittable &= leaf_i[:, 2] < max_depth
                row_ok = (iota_l1 < L) & ok & splittable
                npath_s = st["npath"]
                BIGB = jnp.int32(1 << 30)

                def wbody(wst):
                    a, child_left, crossed, keep, lf_c, stale_c = wst
                    g = node_i[a, 0]
                    t_a = node_i[a, 1]
                    is_num_a = node_i[a, 3] == 0
                    m_g = mono_arr[g]
                    opposite_ok = is_num_a & ~crossed[
                        g, child_left.astype(jnp.int32)]
                    in_sub = ((npath_s[:, a // 32]
                               >> (a % 32).astype(jnp.uint32)) & 1) == 1
                    opp_side = jnp.where(child_left,
                                         lo_col[:, g] > t_a,
                                         hi_col[:, g] <= t_a + 1)
                    opp = in_sub & opp_side
                    # which child output applies to leaf M: the reference
                    # flips use_left/use_right only at sf-splits INSIDE the
                    # opposite subtree — in box terms, M keeps a side unless
                    # its own sf-range moved past the new threshold relative
                    # to the subtree ROOT's range (= the subtree extrema)
                    alo = jnp.min(jnp.where(opp, sf_lo, BIGB))
                    ahi = jnp.max(jnp.where(opp, sf_hi, -BIGB))
                    use_l = catv | (sf_lo <= thrv) | (sf_lo == alo)
                    use_r = catv | (sf_hi > thrv + 1) | (sf_hi == ahi)
                    both = use_l & use_r
                    lo_v = jnp.where(both, jnp.minimum(lout, rout),
                                     jnp.where(use_r, rout, lout))
                    hi_v = jnp.where(both, jnp.maximum(lout, rout),
                                     jnp.where(use_r, rout, lout))
                    cand = (opp & keep & row_ok
                            & opposite_ok & (m_g != 0))
                    update_max = jnp.where(m_g > 0, ~child_left, child_left)
                    cur_lo = lf_c[:, 10]
                    cur_hi = lf_c[:, 11]
                    new_hi = jnp.where(cand & update_max,
                                       jnp.minimum(cur_hi, lo_v), cur_hi)
                    new_lo = jnp.where(cand & ~update_max,
                                       jnp.maximum(cur_lo, hi_v), cur_lo)
                    changed = (new_hi < cur_hi) | (new_lo > cur_lo)
                    lf_c = lf_c.at[:, 10].set(new_lo).at[:, 11].set(new_hi)
                    stale_c = stale_c | changed
                    # record the crossing + the (one-sided) contiguity
                    # constraint this up-path entry imposes on leaves seen
                    # from higher ancestors: leaves past the crossed
                    # threshold in the crossing's direction are pruned
                    crossed = crossed.at[g, child_left.astype(
                        jnp.int32)].set(crossed[g, child_left.astype(
                            jnp.int32)] | opposite_ok)
                    entry_keep = jnp.where(child_left,
                                           lo_col[:, g] <= t_a,
                                           hi_col[:, g] > t_a + 1)
                    keep = keep & jnp.where(opposite_ok, entry_keep, True)
                    return (st["node_par"][a], st["node_side"][a] == 1,
                            crossed, keep, lf_c, stale_c)

                a0 = jnp.where(ok, li[3], -1)
                (_, _, _, _, leaf_f, stale) = lax.while_loop(
                    lambda wst: wst[0] >= 0, wbody,
                    (a0, li[4] == 1,
                     jnp.zeros((F, 2), bool),
                     jnp.ones(L + 1, bool), leaf_f, stale))

            out = dict(
                perm=perm, perm_buf=pbuf,
                leaf_f=leaf_f.at[wl].set(lrow_f).at[wn].set(rrow_f),
                leaf_i=leaf_i.at[wl].set(lrow_i).at[wn].set(rrow_i),
                leaf_bits=leaf_bits.at[wl].set(bbits2[0])
                                   .at[wn].set(bbits2[1]),
                node_f=node_f, node_i=node_i, node_bits=node_bits,
                hist=hist,
                num_leaves=st["num_leaves"] + ok.astype(jnp.int32),
            )
            if layout_sorted:
                out["srows"] = srows_new
                out["srows_buf"] = sbuf
            if forced is not None:
                out["forcing"] = forcing_next
            if ic_on:
                out["path"] = st["path"].at[wl].set(child_path) \
                                        .at[wn].set(child_path)
            if inter:
                # children inherit the parent's box narrowed on the split
                # feature (categorical splits scatter bins to both sides;
                # keeping the parent box is conservative — matches the
                # host learner's apply_split)
                l_hi_box = jnp.where(catv, phi_vec,
                                     phi_vec.at[feat].set(thrv + 1))
                r_lo_box = jnp.where(catv, plo_vec,
                                     plo_vec.at[feat].set(thrv + 1))
                out["box_lo"] = st["box_lo"].at[wl].set(plo_vec) \
                                            .at[wn].set(r_lo_box)
                out["box_hi"] = st["box_hi"].at[wl].set(l_hi_box) \
                                            .at[wn].set(phi_vec)
                nbit = jnp.where(
                    jnp.arange(NPW_N, dtype=jnp.int32) == nidx // 32,
                    jnp.left_shift(jnp.uint32(1),
                                   (nidx % 32).astype(jnp.uint32)),
                    jnp.uint32(0))
                child_npath = st["npath"][leaf] | nbit
                out["npath"] = st["npath"].at[wl].set(child_npath) \
                                          .at[wn].set(child_npath)
                out["stale"] = stale.at[wl].set(False).at[wn].set(False)
                out["node_par"] = st["node_par"].at[wk].set(li[3])
                out["node_side"] = st["node_side"].at[wk].set(li[4])
            return out

        if L > 1:
            state = lax.fori_loop(0, NODES, split_step, state)

        # -------------------------------------------------- row -> leaf id
        # leaves with zero (local) rows would duplicate another leaf's begin
        # offset — push them past the end so searchsorted never picks them
        # (common under sharding: a leaf can be empty on one shard)
        leaf_begin = jnp.where(state["leaf_i"][:L, 1] > 0,
                               state["leaf_i"][:L, 0],
                               N + jnp.arange(L, dtype=jnp.int32))
        order = jnp.argsort(leaf_begin)
        sorted_begin = leaf_begin[order]
        which = jnp.searchsorted(sorted_begin,
                                 jnp.arange(N, dtype=jnp.int32),
                                 side="right") - 1
        pos_leaf = order[which]
        row_leaf = jnp.zeros(N, jnp.int32).at[state["perm"][:N]].set(pos_leaf)

        node_f = state["node_f"]
        node_i = state["node_i"]
        leaf_f = state["leaf_f"]
        leaf_i = state["leaf_i"]
        # an unsplittable tree contributes NOTHING — the reference turns
        # one-leaf trees into constant-0 trees (gbdt.cpp:408-436
        # AsConstantTree(0); the host learner matches); without this the
        # fused fast path would add the root's Newton step every round
        leaf_value_out = jnp.where(state["num_leaves"] > 1,
                                   leaf_f[:L, 3],
                                   jnp.zeros_like(leaf_f[:L, 3]))
        if quant and cfg.quant_train_renew_leaf:
            # re-fit leaf outputs with the full-precision gradient sums
            # (reference: GradientDiscretizer::RenewIntGradTreeOutput)
            gsum = jax.ops.segment_sum(grad, row_leaf, num_segments=L)
            hsum = jax.ops.segment_sum(hess, row_leaf, num_segments=L)
            if self.axis is not None:
                gsum = lax.psum(gsum, self.axis)
                hsum = lax.psum(hsum, self.axis)
            parent_out = node_f[jnp.clip(leaf_i[:L, 3], 0, NODES - 1), 1]
            renewed = calculate_leaf_output(gsum, hsum, p, leaf_f[:L, 2],
                                            parent_out)
            # renew only real trees: a one-leaf tree stays constant-0
            active = ((jnp.arange(L, dtype=jnp.int32) < state["num_leaves"])
                      & (state["num_leaves"] > 1))
            leaf_value_out = jnp.where(active, renewed, leaf_value_out)
        return DeviceTree(
            node_feature=node_i[:NODES, 0],
            node_threshold=node_i[:NODES, 1],
            node_default_left=node_i[:NODES, 2].astype(bool),
            node_is_cat=node_i[:NODES, 3].astype(bool),
            node_cat_bits=state["node_bits"][:NODES],
            node_left=node_i[:NODES, 4],
            node_right=node_i[:NODES, 5],
            node_gain=node_f[:NODES, 0],
            node_value=node_f[:NODES, 1],
            node_weight=node_f[:NODES, 2],
            node_count=node_f[:NODES, 3],
            leaf_value=leaf_value_out,
            leaf_weight=leaf_f[:L, 1],
            leaf_count=leaf_f[:L, 2],
            leaf_depth=leaf_i[:L, 2],
            leaf_parent_node=leaf_i[:L, 3],
            num_leaves=state["num_leaves"],
            row_leaf=row_leaf,
        )

    # ------------------------------------------------------------------
    # data_residency=stream: out-of-core tree build
    # ------------------------------------------------------------------
    # The binned matrix lives in host shards (data/stream.py); the device
    # keeps only the O(N)-scalar per-row state (grad/hess/mask, the
    # permutation, and — under the sorted layout — the physically ordered
    # gradient channels). Each tree is built by a host-driven loop of
    # small jitted kernels whose traced math replicates the fused
    # program's split step op-for-op for the supported option subset, and
    # whose histogram windows accumulate in the same W-chunk order — so
    # streamed trees are bit-identical to resident ones
    # (tests/test_stream.py). Row windows ride the double-buffered H2D
    # ring (ShardRing): the transfer of window k+1 is issued while the
    # device chews window k, instrumented by the h2d_prefetch/chunk_wait
    # telemetry phases. With a sampling mask (GOSS/bagging), windows are
    # COMPACTED host-side: only in-bag rows cross the link, the kernel
    # re-expands them into their window lanes, and the masked lanes'
    # exact-zero contributions keep bit-identity.

    def _stream_blockers(self, config: Config):
        """Fused-program options the multi-dispatch stream build does not
        replicate (config-only: runs from the base __init__)."""
        blockers = []
        if config.use_quantized_grad:
            blockers.append("use_quantized_grad")
        if config.forcedsplits_filename:
            blockers.append("forcedsplits_filename")
        if config.interaction_constraints:
            blockers.append("interaction_constraints")
        if config.extra_trees:
            blockers.append("extra_trees")
        if config.feature_fraction_bynode < 1.0:
            blockers.append("feature_fraction_bynode")
        if config.monotone_constraints and any(
                int(m) != 0 for m in config.monotone_constraints):
            blockers.append("monotone_constraints")
        if config.feature_contri:
            blockers.append("feature_contri")
        return blockers

    def _estimate_residency_bytes(self) -> int:
        """The fused hbm path pins the packed row matrix (bins + gh/mask
        channels) PLUS either the column-major copy (gather) or the
        per-tree sorted buffer + double buffer — ~2x the packed bytes."""
        item = 1 if self.max_num_bins <= 256 else 2
        C = self.num_features
        packed = self.num_data * (C * item + 9)
        return 2 * packed

    def _init_stream_jits(self) -> None:
        self._sj_init = jax.jit(self._stream_init_impl)
        self._sj_pick = jax.jit(self._stream_pick_impl)
        self._sj_part = jax.jit(self._stream_partition_impl)
        self._sj_chunk = jax.jit(self._stream_chunk_impl,
                                 static_argnames=("has_mask",))
        self._sj_finish = jax.jit(self._stream_finish_impl)
        self._sj_final = jax.jit(self._stream_finalize_impl)

    # -- traced pieces (shared by the jitted stream kernels) -----------
    def _stream_best_of(self, hist, pg, ph, pc, pout, depth, fm):
        """best_of of the fused program restricted to the stream-mode
        option subset (no voting/feature-sharding/bundle/extra/monotone/
        contri) — the surviving ops are replicated verbatim so gains,
        tie-breaks, and outputs match the resident program bit-for-bit."""
        p = self.params
        gain, thr, dl, lg, lh, lc, bits = per_feature_best(
            hist, pg, ph, pc, pout, self.num_bins_arr,
            self.default_bins_arr, self.missing_types_arr,
            self.is_categorical_arr, fm, p, self.has_categorical,
            constraints=None, rand_thresholds=None)
        parent_gain = leaf_gain(pg, ph, p, pc, pout)
        shift = parent_gain + p.min_gain_to_split
        f = jnp.argmax(gain, axis=0).astype(jnp.int32)
        g = gain[f] - shift
        ok = jnp.isfinite(gain[f]) & (g > 0.0)
        if self.config.max_depth > 0:
            ok = ok & (depth < self.config.max_depth)
        lout = calculate_leaf_output(lg[f], lh[f], p, lc[f], pout)
        rout = calculate_leaf_output(pg - lg[f], ph - lh[f], p,
                                     pc - lc[f], pout)
        return (jnp.where(ok, g, K_MIN_SCORE), f, thr[f], dl[f],
                self.is_categorical_arr[f], bits[f], lg[f], lh[f], lc[f],
                lout, rout)

    def _stream_chosen(self, state):
        """The pending split the argmax selects — the head of the fused
        split_step, recomputed identically by partition and finish so no
        host round-trip of split metadata can drift."""
        L = self.config.num_leaves
        leaf_f, leaf_i = state["leaf_f"], state["leaf_i"]
        leaf = jnp.argmax(leaf_f[:L, 4]).astype(jnp.int32)
        lf = leaf_f[leaf]
        li = leaf_i[leaf]
        ok = lf[4] > 0.0
        return leaf, lf, li, ok

    # -- jitted kernels -------------------------------------------------
    def _stream_init_impl(self, hist_root, fmask, gs, hs, ms):
        """State init of the fused program (root totals, root best split,
        consolidated leaf/node matrices), with the sorted-layout gradient
        channels riding the carry instead of the packed payload."""
        cfg = self.config
        N = self.num_data
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        W = self._window(N)
        p = self.params
        f32, i32 = jnp.float32, jnp.int32
        totals = jnp.sum(hist_root[0], axis=0)
        root_out = calculate_leaf_output(totals[0], totals[1], p,
                                         totals[2], 0.0)
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)
        (bg0, bf0, bt0, bdl0, bcat0, bbits0, blg0, blh0, blc0, blout0,
         brout0) = self._stream_best_of(hist_root, totals[0], totals[1],
                                        totals[2], root_out, jnp.int32(0),
                                        fmask)
        iota_l1 = jnp.arange(L + 1, dtype=i32)
        leaf_f = jnp.zeros((L + 1, 12), f32)
        leaf_f = leaf_f.at[:, 4].set(K_MIN_SCORE) \
                       .at[:, 10].set(-jnp.inf).at[:, 11].set(jnp.inf)
        leaf_f = leaf_f.at[0].set(jnp.stack(
            [totals[0], totals[1], totals[2], root_out, bg0, blg0, blh0,
             blc0, blout0, brout0, neg_inf, pos_inf]))
        leaf_i = jnp.zeros((L + 1, 9), i32)
        leaf_i = leaf_i.at[:, 0].set(N + iota_l1).at[:, 3].set(-1)
        leaf_i = leaf_i.at[0].set(jnp.stack(
            [i32(0), i32(N), i32(0), i32(-1), i32(0), bf0, bt0,
             bdl0.astype(i32), bcat0.astype(i32)]))
        leaf_bits = jnp.zeros((L + 1, 8), jnp.uint32).at[0].set(bbits0)
        state = dict(
            perm=jnp.concatenate([jnp.arange(N, dtype=i32),
                                  jnp.zeros(W, i32)]),
            perm_buf=jnp.zeros(N + W, i32),
            leaf_f=leaf_f, leaf_i=leaf_i, leaf_bits=leaf_bits,
            node_f=jnp.zeros((NODES + 1, 4), f32),
            node_i=jnp.zeros((NODES + 1, 6), i32).at[:, 4:6].set(~0),
            node_bits=jnp.zeros((NODES + 1, 8), jnp.uint32),
            hist=jnp.zeros((L + 1, self.num_features, self.Bb, HIST_C),
                           f32).at[0].set(hist_root),
            num_leaves=jnp.int32(1),
        )
        if self.layout == "sorted":
            state["gs"], state["hs"] = gs, hs
            state["gs_buf"] = jnp.zeros_like(gs)
            state["hs_buf"] = jnp.zeros_like(hs)
            if ms is not None:
                state["ms"] = ms
                state["ms_buf"] = jnp.zeros_like(ms)
        return state

    def _stream_pick_impl(self, state):
        leaf, lf, li, ok = self._stream_chosen(state)
        return leaf, ok, li[0], li[1], li[5]

    def _stream_partition_impl(self, state, cvals):
        """pbody + cbody of the fused split step, with the split feature's
        bin values arriving as the uploaded ``cvals`` buffer (slice-lane
        indexed, PV = pow2(count) >= nch*W) instead of a resident
        column/payload read. Also collects the per-lane go_left flags so
        the host can mirror the two-monotone-run placement (lefts
        ascending, rights reversed) onto its shard-side structures."""
        N = self.num_data
        W = self._window(N)
        PV = cvals.shape[0]
        # window-read invariants (the resident perm_slice/srow_slice
        # contracts): every start is begin + c*W <= begin + count <= N and
        # the carried buffers pad one full window past N, so no
        # dynamic_slice below can clamp; cvals is padded to a whole number
        # of windows so the c*W reads stay in range
        assert state["perm"].shape[0] == N + W
        assert state["perm_buf"].shape[0] == N + W
        assert PV % W == 0 and PV >= W
        lane = jnp.arange(W, dtype=jnp.int32)
        i32 = jnp.int32
        leaf, lf, li, ok = self._stream_chosen(state)
        feat = li[5]
        thrv, dlv, catv = li[6], li[7].astype(bool), li[8].astype(bool)
        bitsv = state["leaf_bits"][leaf]
        begin = li[0]
        count_eff = jnp.where(ok, li[1], 0)
        nch = (count_eff + W - 1) // W
        perm_in = state["perm"]
        sorted_mode = self.layout == "sorted"
        chans = [k for k in ("gs", "hs", "ms") if k in state]

        def pbody(s):
            c, lcur, rcur, pbuf, gbuf = s[:5]
            cbufs = list(s[5:])
            live = jnp.clip(count_eff - c * W, 0, W)
            valid = lane < live
            rows = lax.dynamic_slice(perm_in, (begin + c * W,), (W,))
            cv = lax.dynamic_slice(cvals, (c * W,), (W,)).astype(i32)
            gl = decision_go_left(
                cv, thrv, dlv, self.default_bins_arr[feat],
                self.missing_types_arr[feat], self.num_bins_arr[feat],
                catv, bitsv) & valid
            cums_gl = jnp.cumsum(gl.astype(i32))
            nl = cums_gl[W - 1]
            prefix_valid = jnp.minimum(lane + 1, live)
            lpos = lcur + cums_gl - 1
            rpos = rcur - (prefix_valid - cums_gl)
            pos = jnp.where(gl, lpos, jnp.where(valid, rpos, N))
            pbuf = pbuf.at[pos].set(rows, mode="drop")
            gbuf = lax.dynamic_update_slice(gbuf, gl, (c * W,))
            if sorted_mode:
                cbufs = [
                    b.at[pos].set(
                        lax.dynamic_slice(state[k], (begin + c * W,), (W,)),
                        mode="drop")
                    for k, b in zip(chans, cbufs)]
            return tuple([c + 1, lcur + nl, rcur - (live - nl), pbuf, gbuf]
                         + cbufs)

        init = [jnp.int32(0), begin, begin + count_eff,
                state["perm_buf"], jnp.zeros(PV, bool)]
        if sorted_mode:
            init += [state[k + "_buf"] for k in chans]
        out = lax.while_loop(lambda s: s[0] < nch, pbody, tuple(init))
        lend, pbuf, gbuf = out[1], out[3], out[4]
        cbufs = list(out[5:])
        left_count = lend - begin

        def cbody(s):
            c, pm = s[:2]
            cms = list(s[2:])
            start = begin + c * W
            valid = (c * W + lane) < count_eff
            vals = jnp.where(valid, lax.dynamic_slice(pbuf, (start,), (W,)),
                             lax.dynamic_slice(pm, (start,), (W,)))
            pm = lax.dynamic_update_slice(pm, vals, (start,))
            if sorted_mode:
                cms = [lax.dynamic_update_slice(
                    m, jnp.where(valid,
                                 lax.dynamic_slice(b, (start,), (W,)),
                                 lax.dynamic_slice(m, (start,), (W,))),
                    (start,))
                    for m, b in zip(cms, cbufs)]
            return tuple([c + 1, pm] + cms)

        cinit = [jnp.int32(0), perm_in]
        if sorted_mode:
            cinit += [state[k] for k in chans]
        cout = lax.while_loop(lambda s: s[0] < nch, cbody, tuple(cinit))
        new_state = dict(state)
        new_state["perm"] = cout[1]
        new_state["perm_buf"] = pbuf
        if sorted_mode:
            for k, m, b in zip(chans, cout[2:], cbufs):
                new_state[k] = m
                new_state[k + "_buf"] = b
        return new_state, gbuf, left_count

    def _stream_chunk_impl(self, acc, bins_up, pos, perm, gs, hs, ms,
                           grad, hess, row_mask, start, done, count, *,
                           has_mask: bool):
        """chunk_hist of the fused program with the window's bins uploaded
        (optionally compacted to the in-bag rows + their lane positions)
        while the gradient channels read device-resident state. Same
        values, same gh_contract/hist_pallas shapes, same ``acc + part``
        → bit-identical accumulation."""
        N = self.num_data
        W = self._window(N)
        C = self.num_features
        Bb = self.Bb
        # same pad invariant as the fused program's perm_slice/srow_slice:
        # start + done <= start + count <= N and the per-row buffers carry
        # a full window of tail padding, so the slices never clamp
        assert perm is None or perm.shape[0] == N + W
        assert gs is None or gs.shape[0] == N + W
        lane = jnp.arange(W, dtype=jnp.int32)
        if bins_up.shape[0] == W and pos is None:
            bins = bins_up
        else:
            # re-expand the compacted transfer into its window lanes;
            # out-of-bag lanes keep zero bins — their gh channels are
            # exactly 0.0 below, so each contributes the same exact +0.0
            # the resident program adds for masked rows
            bins = jnp.zeros((W, C), bins_up.dtype).at[pos].set(
                bins_up, mode="drop")
        valid = (done + lane) < count
        if self.layout == "sorted":
            g = lax.dynamic_slice(gs, (start + done,), (W,))
            h = lax.dynamic_slice(hs, (start + done,), (W,))
            if has_mask:
                valid = valid & (lax.dynamic_slice(
                    ms, (start + done,), (W,)) > 0)
        else:
            rows = lax.dynamic_slice(perm, (start + done,), (W,))
            g = grad[rows]
            h = hess[rows]
            if has_mask:
                valid = valid & row_mask[rows]
        if self.hist_impl == "pallas":
            from ..ops.hist_pallas import hist_pallas, pack_gh8
            live = jnp.clip(count - done, 0, W)
            gh8 = pack_gh8(g, h, valid)
            return acc + hist_pallas(bins, gh8, Bb, live)
        g0 = jnp.where(valid, g, 0.0)
        h0 = jnp.where(valid, h, 0.0)
        gh = jnp.stack([g0, h0, valid.astype(jnp.float32)], axis=1)
        bin_iota = jnp.arange(Bb, dtype=bins.dtype)
        onehot = (bins[:, :, None] == bin_iota).astype(jnp.bfloat16)
        part = gh_contract(gh, onehot.reshape(W, C * Bb),
                           self.hist_precision)
        return acc + part.reshape(HIST_C, C, Bb).transpose(1, 2, 0)

    def _stream_finish_impl(self, state, hist_small, left_count, fmask):
        """The tail of the fused split step: parent pointers, histogram
        subtraction, both children's best-split scans, consolidated state
        writes — everything after the row-touching loops."""
        cfg = self.config
        N = self.num_data
        F = self.num_features
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        i32 = jnp.int32
        leaf, lf, li, ok = self._stream_chosen(state)
        leaf_f, leaf_i = state["leaf_f"], state["leaf_i"]
        leaf_bits = state["leaf_bits"]
        bgain = lf[4]
        feat = li[5]
        thrv, dlv, catv = li[6], li[7].astype(bool), li[8].astype(bool)
        bitsv = leaf_bits[leaf]
        blg, blh, blc = lf[5], lf[6], lf[7]
        blout, brout = lf[8], lf[9]
        begin = li[0]
        count_eff = jnp.where(ok, li[1], 0)
        right_count = count_eff - left_count

        new_leaf = state["num_leaves"]
        nidx = new_leaf - 1
        wl = jnp.where(ok, leaf, L)
        wn = jnp.where(ok, new_leaf, L)
        wk = jnp.where(ok, nidx, NODES)

        pnode = li[3]
        was_left = li[4].astype(bool)
        safe_p = jnp.where((pnode >= 0) & ok, pnode, NODES)
        prow = state["node_i"][safe_p]
        prow = jnp.where(was_left, prow.at[4].set(nidx),
                         prow.at[5].set(nidx))
        node_i = state["node_i"].at[safe_p].set(prow)

        pg, ph, pc = lf[0], lf[1], lf[2]
        lg, lh, lc = blg, blh, blc
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        lout, rout = blout, brout
        depth = li[2] + 1

        pmin, pmax = lf[10], lf[11]
        mono_f = self.mono_arr[feat]
        lcap = rcap = (lout + rout) * 0.5
        lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, lcap), pmin)
        lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, lcap), pmax)
        rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, rcap), pmin)
        rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, rcap), pmax)

        node_f = state["node_f"].at[wk].set(
            jnp.stack([bgain, lf[3], ph, pc]))
        node_i = node_i.at[wk].set(jnp.stack(
            [feat, thrv, dlv.astype(i32), catv.astype(i32),
             ~leaf, ~new_leaf]))
        node_bits = state["node_bits"].at[wk].set(bitsv)

        small_is_left = left_count <= right_count
        hist_large = state["hist"][leaf] - hist_small
        hist_left = jnp.where(small_is_left, hist_small, hist_large)
        hist_right = jnp.where(small_is_left, hist_large, hist_small)
        hist = state["hist"].at[wl].set(hist_left).at[wn].set(hist_right)

        fms = jnp.broadcast_to(fmask, (2, F))
        best_children = jax.vmap(self._stream_best_of,
                                 in_axes=(0, 0, 0, 0, 0, None, 0))
        (bg2, bf2, bt2, bdl2, bcat2, bbits2, blg2, blh2, blc2,
         blout2, brout2) = best_children(
            jnp.stack([hist_left, hist_right]),
            jnp.stack([lg, rg]), jnp.stack([lh, rh]),
            jnp.stack([lc, rc]), jnp.stack([lout, rout]), depth, fms)

        lrow_f = jnp.stack([lg, lh, lc, lout, bg2[0], blg2[0], blh2[0],
                            blc2[0], blout2[0], brout2[0], lmin, lmax])
        rrow_f = jnp.stack([rg, rh, rc, rout, bg2[1], blg2[1], blh2[1],
                            blc2[1], blout2[1], brout2[1], rmin, rmax])
        lrow_i = jnp.stack([begin, left_count, depth, nidx, i32(1),
                            bf2[0], bt2[0], bdl2[0].astype(i32),
                            bcat2[0].astype(i32)])
        rrow_i = jnp.stack([begin + left_count, right_count, depth, nidx,
                            i32(0), bf2[1], bt2[1], bdl2[1].astype(i32),
                            bcat2[1].astype(i32)])

        out = dict(state)
        out["leaf_f"] = leaf_f.at[wl].set(lrow_f).at[wn].set(rrow_f)
        out["leaf_i"] = leaf_i.at[wl].set(lrow_i).at[wn].set(rrow_i)
        out["leaf_bits"] = leaf_bits.at[wl].set(bbits2[0]) \
                                    .at[wn].set(bbits2[1])
        out["node_f"] = node_f
        out["node_i"] = node_i
        out["node_bits"] = node_bits
        out["hist"] = hist
        out["num_leaves"] = state["num_leaves"] + ok.astype(i32)
        return out

    def _stream_finalize_impl(self, state):
        """row->leaf resolution + DeviceTree assembly (the fused
        program's epilogue, minus the quantized-leaf renewal the stream
        subset excludes)."""
        cfg = self.config
        N = self.num_data
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        leaf_begin = jnp.where(state["leaf_i"][:L, 1] > 0,
                               state["leaf_i"][:L, 0],
                               N + jnp.arange(L, dtype=jnp.int32))
        order = jnp.argsort(leaf_begin)
        sorted_begin = leaf_begin[order]
        which = jnp.searchsorted(sorted_begin,
                                 jnp.arange(N, dtype=jnp.int32),
                                 side="right") - 1
        pos_leaf = order[which]
        row_leaf = jnp.zeros(N, jnp.int32).at[
            state["perm"][:N]].set(pos_leaf)
        node_f = state["node_f"]
        node_i = state["node_i"]
        leaf_f = state["leaf_f"]
        leaf_i = state["leaf_i"]
        leaf_value_out = jnp.where(state["num_leaves"] > 1,
                                   leaf_f[:L, 3],
                                   jnp.zeros_like(leaf_f[:L, 3]))
        return DeviceTree(
            node_feature=node_i[:NODES, 0],
            node_threshold=node_i[:NODES, 1],
            node_default_left=node_i[:NODES, 2].astype(bool),
            node_is_cat=node_i[:NODES, 3].astype(bool),
            node_cat_bits=state["node_bits"][:NODES],
            node_left=node_i[:NODES, 4],
            node_right=node_i[:NODES, 5],
            node_gain=node_f[:NODES, 0],
            node_value=node_f[:NODES, 1],
            node_weight=node_f[:NODES, 2],
            node_count=node_f[:NODES, 3],
            leaf_value=leaf_value_out,
            leaf_weight=leaf_f[:L, 1],
            leaf_count=leaf_f[:L, 2],
            leaf_depth=leaf_i[:L, 2],
            leaf_parent_node=leaf_i[:L, 3],
            num_leaves=state["num_leaves"],
            row_leaf=row_leaf,
        )

    # -- the host-driven per-tree loop ----------------------------------
    def _stream_small_hist(self, state, grad, hess, row_mask, sb: int,
                           sc: int, payload, perm_host, mask_order):
        """One leaf's histogram via the window pump: host fetch (shard
        gather or payload memcpy, compacted to in-bag rows when a
        sampling mask is live), async device_put through the ring, jitted
        accumulate in the resident W-chunk order."""
        from ..data.stream import stream_windows
        N = self.num_data
        W = self._window(N)
        C = self.num_features
        nch = (sc + W - 1) // W
        dtype = self.sdata.shards[0].dtype
        compact = (mask_order is not None
                   and self.config.stream_goss_compact)
        acc = [jnp.zeros((C, self.Bb, HIST_C), jnp.float32)]
        has_mask = row_mask is not None
        gs = state.get("gs")
        hs = state.get("hs")
        ms = state.get("ms")
        sorted_mode = self.layout == "sorted"

        def fetch(c):
            lo = sb + c * W
            live = min(W, sc - c * W)
            if sorted_mode:
                lanes = np.arange(live)
                rows = None
            else:
                rows = perm_host[lo:lo + live]
                lanes = np.arange(live)
            if compact:
                inbag = (mask_order[lo:lo + live] if sorted_mode
                         else mask_order[rows])
                lanes = lanes[inbag]
                if rows is not None:
                    rows = rows[inbag]
            nsel = len(lanes)
            if not compact or nsel > (W * 7) // 8:
                buf = np.zeros((W, C), dtype=dtype)
                if sorted_mode:
                    buf[:live] = payload[lo:lo + live]
                else:
                    self.sdata.gather_rows(rows if not compact
                                           else perm_host[lo:lo + live],
                                           out=buf[:live])
                return (buf,)
            wc = max(_next_pow2(max(nsel, 1)), 256)
            buf = np.zeros((wc, C), dtype=dtype)
            pos = np.full(wc, W, np.int32)
            pos[:nsel] = lanes
            if nsel:
                if sorted_mode:
                    buf[:nsel] = payload[lo + lanes]
                else:
                    self.sdata.gather_rows(rows, out=buf[:nsel])
            return (buf, pos)

        def consume(c, bins_dev, *rest):
            pos_dev = rest[0] if rest else None
            acc[0] = self._sj_chunk(
                acc[0], bins_dev, pos_dev, state["perm"], gs, hs, ms,
                grad, hess, row_mask, jnp.int32(sb), jnp.int32(c * W),
                jnp.int32(sc), has_mask=has_mask)

        stream_windows(nch, fetch, consume, self.telemetry,
                       self.config.stream_prefetch_depth)
        return acc[0]

    def _train_tree_stream(self, grad, hess, row_mask) -> DeviceTree:
        """Grow one tree out-of-core: root histogram over all shards, then
        per split — pick (one small D2H), host column fetch + device
        partition, go_left mirror update, streamed small-child histogram,
        jitted finish. Breaking when no leaf has positive gain is exact:
        the remaining fused steps would all be masked no-ops."""
        cfg = self.config
        N = self.num_data
        W = self._window(N)
        NODES = max(cfg.num_leaves - 1, 1)
        fmask = self._feature_mask()
        has_mask = row_mask is not None
        mask_dev = row_mask if has_mask else None
        sorted_mode = self.layout == "sorted"

        # host-side per-tree state
        mask_host = None
        if has_mask and cfg.stream_goss_compact:
            # one D2H of the in-bag mask per tree drives window compaction
            # graftlint: disable=R1 — per-tree (not per-chunk) fetch; the
            # mask is the host-side input of the GOSS working-set shrink
            mask_host = np.asarray(jax.device_get(row_mask)).astype(bool)
        if sorted_mode:
            with self.telemetry.phase("layout_apply"):
                payload = self.sdata.dataset_order_copy()
                gs = jnp.concatenate([grad, jnp.zeros(W, jnp.float32)])
                hs = jnp.concatenate([hess, jnp.zeros(W, jnp.float32)])
                ms = (jnp.concatenate([row_mask.astype(jnp.float32),
                                       jnp.zeros(W, jnp.float32)])
                      if has_mask else None)
            perm_host = None
            mask_order = mask_host
        else:
            payload = None
            gs = hs = ms = None
            perm_host = np.arange(N, dtype=np.int64)
            mask_order = mask_host

        # root histogram over every shard window
        root_perm = jnp.concatenate([jnp.arange(N, dtype=jnp.int32),
                                     jnp.zeros(W, jnp.int32)])
        root_state = {"perm": root_perm}
        if sorted_mode:
            root_state.update(gs=gs, hs=hs)
            if ms is not None:
                root_state["ms"] = ms
        hist_root = self._stream_small_hist(
            root_state, grad, hess, mask_dev, 0, N, payload,
            np.arange(N, dtype=np.int64) if perm_host is None
            else perm_host, mask_order)
        state = self._sj_init(hist_root, fmask, gs, hs, ms)

        for _k in range(NODES if cfg.num_leaves > 1 else 0):
            # graftlint: disable=R1 — the stream mode's per-split sync:
            # the host must learn which leaf/feature to fetch from its
            # shards; this is the capacity-for-latency trade the mode IS
            pick = jax.device_get(self._sj_pick(state))
            leaf, ok, begin, count, feat = (int(pick[0]), bool(pick[1]),
                                            int(pick[2]), int(pick[3]),
                                            int(pick[4]))
            if not ok:
                break

            # split column values for the leaf slice: 1-2 B/row H2D
            pv = max(_next_pow2(max(count, 1)), W)
            dtype = self.sdata.shards[0].dtype
            with self.telemetry.phase("h2d_prefetch"):
                cv_host = np.zeros(pv, dtype=dtype)
                if sorted_mode:
                    cv_host[:count] = payload[begin:begin + count, feat]
                else:
                    cv_host[:count] = self.sdata.gather_col(
                        feat, perm_host[begin:begin + count])
                cvals = jax.device_put(cv_host)
            state, gbuf, left_cnt_dev = self._sj_part(state, cvals)
            # graftlint: disable=R1 — go_left + left count drive the host
            # mirror (payload/permutation) update; one small D2H per split
            gl, left_count = jax.device_get((gbuf, left_cnt_dev))
            gl = np.asarray(gl)[:count]
            left_count = int(left_count)
            # mirror the fused pbody placement: lefts stable ascending,
            # rights filled backward (reversed subsequence)
            if sorted_mode:
                sl = payload[begin:begin + count]
                payload[begin:begin + count] = np.concatenate(
                    [sl[gl], sl[~gl][::-1]])
                if mask_order is not None:
                    mo = mask_order[begin:begin + count]
                    mask_order[begin:begin + count] = np.concatenate(
                        [mo[gl], mo[~gl][::-1]])
            else:
                rs = perm_host[begin:begin + count]
                perm_host[begin:begin + count] = np.concatenate(
                    [rs[gl], rs[~gl][::-1]])

            right_count = count - left_count
            small_is_left = left_count <= right_count
            sb = begin if small_is_left else begin + left_count
            sc = left_count if small_is_left else right_count
            hist_small = self._stream_small_hist(
                state, grad, hess, mask_dev, sb, sc, payload, perm_host,
                mask_order)
            state = self._sj_finish(state, hist_small,
                                    jnp.int32(left_count), fmask)

        return self._sj_final(state)


# ---------------------------------------------------------------------------
# graftir IR contracts: the single-device fused programs carry no mesh, so
# their schedule clause is "collective-free"; what C2-C4 buy here is
# transfer-freedom, f64-freedom under the x64 retrace, and one-trace
# steady state (the ragged 900/703-row stream shards in the scenario
# inventory prove the pow2 bucketing keeps every kernel at one trace).
from ..analysis.ir.contracts import register_program

register_program(
    "FusedTreeLearner._train_tree_impl", collective_free=True,
    notes="whole-tree single-device program: split loop fused, no mesh")
for _k in ("init", "pick", "partition", "chunk", "finish", "finalize"):
    register_program(
        f"FusedTreeLearner._stream_{_k}_impl", collective_free=True,
        notes="host-streamed kernel; shard rows bucket to pow2 so ragged "
              "shards replay one trace")
del _k
