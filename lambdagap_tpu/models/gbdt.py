"""GBDT boosting orchestration.

TPU re-implementation of the reference's GBDT class
(reference: src/boosting/gbdt.{h:37,cpp} — Init :73-129, TrainOneIter
:346-454, BoostFromAverage :321, UpdateScore :495-524, eval :476-493).

Scores live on device as ``[K, N]`` float32. The training-score update never
traverses trees: the learner's partition already knows every row's leaf, so
adding a tree is one gather + scatter-add (the analog of
``ScoreUpdater::AddScore`` going through ``AddScoreByLeaf``,
reference: src/boosting/score_updater.hpp:21-110).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import BinnedDataset
from ..metrics.base import Metric, create_metrics
from ..objectives.base import ObjectiveFunction, create_objective
from ..ops.predict import (_round_depth, build_forest_blocks,
                           forest_to_arrays, predict_forest,
                           predict_forest_leaf, predict_tree_binned,
                           tree_to_arrays)
from ..ops.predict_tensor import (build_tree_tiles, predict_forest_leaf_tensor,
                                  predict_forest_tensor)
from ..guard.nonfinite import NULL_GUARD, TrainGuard
from ..obs import costplane
from ..obs.telemetry import NULL_TELEMETRY, TrainTelemetry
from ..utils import log
from .learner import SerialTreeLearner
from .sample_strategy import create_sample_strategy
from .tree import Tree

K_EPSILON = 1e-15


def _fused_mode_enabled(mode) -> bool:
    """tpu_fused_learner truthiness ('auto' counts as enabled; the serial
    branch additionally gates 'auto' on the backend)."""
    return mode == "auto" or mode in ("1", "true", "on", "yes", True)


def _demote_advanced_monotone(cfg, where: str) -> None:
    """advanced needs per-threshold dense bound arrays rebuilt per affected
    leaf (host-orchestrated only); basic and intermediate run in-program."""
    if (cfg.monotone_constraints
            and cfg.monotone_constraints_method == "advanced"):
        log.warning("monotone_constraints_method=advanced is not available "
                    "on %s; using 'intermediate' (basic and intermediate "
                    "run in-program)", where)
        cfg.monotone_constraints_method = "intermediate"


def _cegb_requested(cfg) -> bool:
    """Any CEGB penalty configured — the learner-routing predicate
    (reference: src/treelearner/cost_effective_gradient_boosting.hpp)."""
    return cfg.cegb_tradeoff > 0 and (
        cfg.cegb_penalty_split > 0
        or cfg.cegb_penalty_feature_coupled
        or cfg.cegb_penalty_feature_lazy)


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def _add_tree_score(score, perm, leaf_begin, leaf_count, leaf_values,
                    num_leaves: int):
    """score[perm[i]] += leaf_value[leaf containing position i]."""
    del leaf_count
    N = score.shape[0]
    order = jnp.argsort(leaf_begin)
    sorted_begin = leaf_begin[order]
    which = jnp.searchsorted(sorted_begin, jnp.arange(N, dtype=leaf_begin.dtype),
                             side="right") - 1
    pos_leaf = order[which]
    vals = leaf_values[pos_leaf]
    return score.at[perm].add(vals)


def dispatch_forest_predict(cfg, x, forest, tree_class, num_class: int,
                            max_depth: int, binned: bool,
                            early_stop_freq: int = 0,
                            early_stop_margin: float = 0.0,
                            blocks=None, has_linear: bool = False):
    """Route a whole-forest score dispatch through the configured traversal
    engine (``predict_engine``): the tensorized [rows x trees] engine
    (ops.predict_tensor) or the sequential per-tree reference scan
    (ops.predict). Both return bit-identical [num_class, N] float32;
    ``blocks`` are pre-sliced tree tiles/blocks from the booster or serve
    caches (either engine consumes the same layout). ``has_linear`` turns
    on the per-leaf dot-product payload in the traversal carry (linear
    trees; raw rows only — binned linear replay stays host-side).

    ``predict_engine=compiled`` rides the tensor branch here: this entry
    point serves the training-side replay paths (binned rows, refit,
    training score rebuilds), which traverse the TRAINING-shaped tables
    the infer compiler does not model — the compiled artifact takes over
    in GBDT.predict_raw and the serve cache, the raw serving shapes it
    exists for (docs/serving.md "Compiled forest artifacts")."""
    if cfg.predict_engine in ("tensor", "compiled"):
        return predict_forest_tensor(
            x, forest, tree_class, num_class, max_depth, binned,
            early_stop_freq, early_stop_margin,
            tree_tile=cfg.predict_tree_tile, tiles=blocks,
            has_linear=has_linear)
    return predict_forest(x, forest, tree_class, num_class, max_depth,
                          binned, early_stop_freq, early_stop_margin,
                          blocks=blocks, has_linear=has_linear)


def dispatch_forest_leaf(cfg, x, forest, max_depth: int, binned: bool,
                         blocks=None):
    """Engine-routed leaf-index dispatch ([T, N] int32), same contract as
    :func:`dispatch_forest_predict` (compiled rides the tensor branch: the
    artifact renumbers nodes but never leaves, so leaf indices are already
    engine-invariant)."""
    if cfg.predict_engine in ("tensor", "compiled"):
        return predict_forest_leaf_tensor(
            x, forest, max_depth, binned,
            tree_tile=cfg.predict_tree_tile, tiles=blocks)
    return predict_forest_leaf(x, forest, max_depth, binned, blocks=blocks)


def _finalize_tree(tree: "Tree", shrinkage: float, bias: float) -> "Tree":
    """Shrinkage + boost-from-average bias fold shared by every FUSED
    materialization path (reference: Tree::Shrinkage + Tree::AddBias,
    gbdt.cpp:415-421).

    The leaf multiply is rounded in float32: the fused fast path already
    added ``f32(leaf_value * shrinkage)`` into the device training scores
    before this tree ever materialized, and auto-resume replays scores
    from the serialized leaf values — a float64 multiply here would
    disagree with the device product by 1 ulp and silently break
    kill-and-resume byte-identity (tests/test_guard.py)."""
    lv32 = (tree.leaf_value[:tree.num_leaves].astype(np.float32)
            * np.float32(shrinkage)).astype(np.float32)
    tree.apply_shrinkage(shrinkage)
    tree.leaf_value[:tree.num_leaves] = lv32.astype(np.float64)
    if abs(bias) > K_EPSILON:
        tree.leaf_value[:tree.num_leaves] += bias
        tree.internal_value = [v + bias for v in tree.internal_value]
        if getattr(tree, "is_linear", False):
            tree.leaf_const[:tree.num_leaves] += bias
    return tree


class _LazyTree:
    """A trained tree still resident on device (fused learner); materializes
    to a host :class:`Tree` on first access."""

    __slots__ = ("learner", "rec", "shrinkage", "bias")

    def __init__(self, learner, rec, shrinkage: float, bias: float) -> None:
        self.learner = learner
        self.rec = rec
        self.shrinkage = shrinkage
        self.bias = bias

    def materialize(self) -> "Tree":
        return _finalize_tree(self.learner.materialize(self.rec),
                              self.shrinkage, self.bias)


class GBDT:
    """Gradient Boosting Decision Tree booster."""

    average_output = False   # True for RF (reference: rf.hpp average_output_)

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]) -> None:
        self.config = config
        self.train_set = train_set
        self.iter_ = 0
        self.models: List[Tree] = []           # flat: iter-major, class-minor
        self.best_iteration = -1
        self.shrinkage_rate = config.learning_rate
        # predict caches + model generation id. The generation bumps on any
        # in-place mutation of the served forest (refit, set_leaf_output,
        # shuffle); serve's CompiledForestCache and the device-forest cache
        # below key on it so stale compiled forests can never be served.
        self.generation = 0
        self._fast_cache = None
        self._forest_cache = None

        self.objective: Optional[ObjectiveFunction] = create_objective(config)
        self.num_class = self.objective.num_class if self.objective else config.num_class
        self.num_tree_per_iteration = max(self.num_class, 1)

        self.train_metrics: List[Metric] = []
        self.valid_sets: List[Tuple[str, BinnedDataset]] = []
        self.valid_binned: List[jax.Array] = []
        self.valid_metrics: List[List[Metric]] = []
        self.valid_scores: List[jax.Array] = []
        self.telemetry: TrainTelemetry = NULL_TELEMETRY
        self.guard: TrainGuard = NULL_GUARD
        self.last_iteration_skipped = False

        if train_set is not None:
            self._setup_training(train_set)

    # ------------------------------------------------------------------
    def _setup_training(self, ds: BinnedDataset) -> None:
        self.num_data = ds.num_data
        if self.objective is not None:
            if self.config.linear_tree and self.objective.is_renew_tree_output:
                # (reference: config check "Cannot use regression_l1
                # objective when fitting linear trees")
                log.fatal("Cannot use the %s objective with linear_tree",
                          self.objective.name)
            self.objective.init(ds.metadata, ds.num_data)
        costplane.PLANE.configure(self.config)
        self.telemetry = TrainTelemetry.from_config(self.config)
        self.guard = TrainGuard.from_config(self.config)
        self.learner = self._create_learner(ds)
        # learners that host-orchestrate (SerialTreeLearner) record their
        # histogram/split/partition sub-phases through this handle; the
        # fused whole-tree program shows the same structure in profiler
        # windows via jax.named_scope instead
        self.learner.telemetry = self.telemetry
        self.sample_strategy = create_sample_strategy(
            self.config, ds.num_data,
            label=None if ds.metadata.label is None else np.asarray(ds.metadata.label),
            query_boundaries=ds.metadata.query_boundaries)
        K, N = self.num_tree_per_iteration, ds.num_data
        init = jnp.zeros((K, N), dtype=jnp.float32)
        if ds.metadata.init_score is not None:
            s = np.asarray(ds.metadata.init_score, dtype=np.float32)
            init = jnp.asarray(s.reshape(K, N) if s.size == K * N
                               else np.tile(s, (K, 1)))
            self.has_init_score = True
        else:
            self.has_init_score = False
        self.scores = init
        if self.config.is_provide_training_metric:
            self.train_metrics = create_metrics(self.config, ds.metadata, N)
        self._meta = ds.feature_arrays()
        if self.config.boosting == "rf":
            self.shrinkage_rate = 1.0

    def _forced_splits_data_parallel(self, ds, tl: str):
        """forcedsplits need a GLOBAL histogram of the forced leaf; voting
        keeps histograms shard-local and feature-parallel shards them by
        column — the full-histogram-psum learner honors the schedule."""
        log.warning("forcedsplits_filename with tree_learner=%s: training "
                    "with the fused data-parallel learner (full-histogram "
                    "psum per split) so forced splits apply", tl)
        if _cegb_requested(self.config):
            log.warning("cegb (cegb_tradeoff) is not applied by the fused "
                        "tree_learner=data learner")
        from ..parallel.fused_parallel import FusedDataParallelTreeLearner
        return FusedDataParallelTreeLearner(ds, self.config)

    def _route_fused_2d(self, ds: BinnedDataset, tl: str):
        """Route distributed training onto the fused 2-D data x feature
        learner (ISSUE 15) when either

        - ``mesh_shape`` names BOTH axes explicitly ("4x2", "1x8",
          "8x1", wildcard "0x2"): one program for every grid is what
          makes the bench's dd x ff sweep comparable and elastic resume
          across grid shapes byte-identical; or
        - ``data_residency=stream`` (or a pre-sharded dataset) is
          combined with ``tree_learner=data``: the composed out-of-core
          mode — the stream x distributed cell this learner flips from
          loud demotion to supported (docs/capability-matrix.md).

        Returns None when the 1-D learner dispatch below should run.
        """
        cfg = self.config
        if tl not in ("data", "voting", "feature"):
            return None
        s = str(cfg.mesh_shape).strip().lower().replace("*", "x")
        explicit_2d = "x" in s
        if not explicit_2d:
            from ..data.stream import ShardedBinnedDataset
            wants_stream = (cfg.data_residency == "stream"
                            or isinstance(ds, ShardedBinnedDataset))
            if not (wants_stream and tl == "data"):
                return None
        if not _fused_mode_enabled(cfg.tpu_fused_learner):
            if explicit_2d:
                log.fatal("mesh_shape=%s is a 2-D data x feature grid, "
                          "which only the fused learner executes; keep "
                          "tpu_fused_learner enabled or set one "
                          "mesh_shape extent implicit ('%s')",
                          cfg.mesh_shape, s.split("x")[0])
            return None
        if cfg.forcedsplits_filename:
            # forced splits need the forced leaf's FULL histogram on
            # every shard; the 2-D mesh shards histogram columns
            return self._forced_splits_data_parallel(ds, tl)
        not_applied = []
        if _cegb_requested(cfg):
            not_applied.append("cegb")
        if not_applied:
            log.warning("%s are not applied by the fused 2-D "
                        "tree_learner=%s learner", ", ".join(not_applied),
                        tl)
        from ..parallel.fused_parallel import Fused2DTreeLearner
        return Fused2DTreeLearner(ds, self.config)

    def _create_learner(self, ds: BinnedDataset):
        """Learner dispatch (reference: TreeLearner::CreateTreeLearner,
        src/treelearner/tree_learner.cpp — (tree_learner, device) -> class).

        For serial training the whole-tree-on-device FusedTreeLearner is the
        production path (auto on accelerators); the host-orchestrated
        SerialTreeLearner remains for debugging / explicit opt-out."""
        tl = self.config.tree_learner
        if getattr(ds, "process_sharded", False):
            # pre_partition=true multi-process data: only the fused
            # data-parallel learner consumes process-local row blocks
            # (reference: pre-partitioned loading feeds the distributed
            # learners, src/io/dataset_loader.cpp:1072)
            cfg = self.config
            if tl not in ("serial", "data"):
                log.fatal("pre-partitioned multi-process training supports "
                          "tree_learner=data (got %r)", tl)
            if cfg.linear_tree:
                log.warning("linear_tree is not supported with "
                            "pre_partition=true (pre-partitioned "
                            "multi-process training); training "
                            "constant-leaf trees")
                cfg.linear_tree = False
            _demote_advanced_monotone(
                cfg, "the fused data-parallel learner")
            not_applied = []
            if _cegb_requested(cfg):
                not_applied.append("cegb")
            if not_applied:
                log.warning("%s are not applied by pre_partition=true "
                            "training", ", ".join(not_applied))
            from ..parallel.fused_parallel import FusedDataParallelTreeLearner
            return FusedDataParallelTreeLearner(ds, self.config)
        if tl == "serial":
            cfg = self.config
            if cfg.linear_tree:
                # linear leaves are first-class on the fused learner (the
                # MXU-batched leaf solve, docs/linear-trees.md); demote the
                # combos the batched path cannot express LOUDLY before any
                # program compiles
                from .linear_leaf import resolve_linear_config
                resolve_linear_config(cfg)
            mode = cfg.tpu_fused_learner
            use_fused = (jax.default_backend() != "cpu" if mode == "auto"
                         else _fused_mode_enabled(mode))
            # niche tree options live on the host-orchestrated learner (the
            # same shape as the reference's CUDA learner deferring
            # unsupported combos to the CPU path)
            host_only = []
            if (cfg.monotone_constraints
                    and cfg.monotone_constraints_method == "advanced"):
                # advanced needs the per-threshold dense bound arrays
                # rebuilt per affected leaf — host-orchestrated only
                # (basic AND intermediate run inside the fused program,
                # incl. intermediate's cross-leaf propagation + re-scans)
                host_only.append("monotone_constraints_method=advanced")
            if _cegb_requested(cfg):
                host_only.append("cegb")
            if use_fused and host_only:
                log.warning("Using the host-driven serial learner for: %s "
                            "— on a high-latency device link this path "
                            "pays one host sync per split instead of the "
                            "fused whole-tree program's zero",
                            ", ".join(host_only))
                use_fused = False
            if cfg.use_quantized_grad and not use_fused:
                log.warning("use_quantized_grad is only implemented by the "
                            "fused device learner; training runs in full "
                            "precision")
            if use_fused:
                from .fused_learner import FusedTreeLearner
                return FusedTreeLearner(ds, self.config)
            return SerialTreeLearner(ds, self.config)
        if self.config.linear_tree:
            log.warning("linear_tree is not supported with tree_learner=%s; "
                        "training constant-leaf trees", tl)
            self.config.linear_tree = False
        if self.config.interaction_constraints and not (
                tl in ("data", "voting", "feature")
                and _fused_mode_enabled(self.config.tpu_fused_learner)):
            # only the fused data-parallel program filters features by the
            # per-leaf path in-program; the host-loop distributed learners
            # do not, and silently dropping a constraint is worse than
            # failing
            log.fatal("interaction_constraints with tree_learner=%s require "
                      "the fused learner (keep tpu_fused_learner enabled "
                      "on data/voting/feature) or tree_learner=serial", tl)
        if tl in ("data", "voting", "feature") and _fused_mode_enabled(
                self.config.tpu_fused_learner):
            _demote_advanced_monotone(self.config,
                                      "the fused distributed learners")
        learner_2d = self._route_fused_2d(ds, tl)
        if learner_2d is not None:
            return learner_2d
        if tl == "data":
            # the fused whole-tree shard_map program is the production
            # multi-chip path (one psum per split, zero per-split host
            # syncs); the host-loop learner is the explicit opt-out
            # (tpu_fused_learner=0). Options the chosen learner does not
            # apply are warned, not silently swallowed.
            cfg = self.config
            not_applied = []
            if _cegb_requested(cfg):
                not_applied.append("cegb")
            if _fused_mode_enabled(cfg.tpu_fused_learner):
                if not_applied:
                    log.warning("%s are not applied by tree_learner=data",
                                ", ".join(not_applied))
                from ..parallel.fused_parallel import \
                    FusedDataParallelTreeLearner
                return FusedDataParallelTreeLearner(ds, self.config)
            # host-loop learner: per-node sampling also unsupported
            if cfg.feature_fraction_bynode < 1.0:
                not_applied.append("feature_fraction_bynode")
            if not_applied:
                log.warning("%s are not applied by the host-loop "
                            "tree_learner=data", ", ".join(not_applied))
        if tl == "voting" and _fused_mode_enabled(
                self.config.tpu_fused_learner):
            # fused voting: whole-tree program with per-split top-k vote +
            # voted-column psum; combinations it cannot express fall back
            # to the host-loop voting learner below
            cfg = self.config
            if cfg.forcedsplits_filename:
                return self._forced_splits_data_parallel(ds, tl)
            host_only = []
            if _cegb_requested(cfg):
                host_only.append("cegb")
            if host_only:
                if cfg.interaction_constraints:
                    # the host-loop voting learner does not filter features
                    # by interaction set; dropping a constraint silently is
                    # worse than failing
                    log.fatal("interaction_constraints with "
                              "tree_learner=voting cannot be combined "
                              "with %s", ", ".join(host_only))
                log.info("Using the host-loop voting learner for: %s",
                         ", ".join(host_only))
            else:
                from ..parallel.fused_parallel import \
                    FusedVotingParallelTreeLearner
                return FusedVotingParallelTreeLearner(ds, self.config)
        if tl == "feature" and _fused_mode_enabled(
                self.config.tpu_fused_learner):
            cfg = self.config
            if cfg.forcedsplits_filename:
                return self._forced_splits_data_parallel(ds, tl)
            if _cegb_requested(cfg):
                log.warning("cegb (cegb_tradeoff) is not applied by "
                            "tree_learner=feature")
            from ..parallel.fused_parallel import \
                FusedFeatureParallelTreeLearner
            return FusedFeatureParallelTreeLearner(ds, self.config)
        from ..parallel import (DataParallelTreeLearner,
                                FeatureParallelTreeLearner,
                                VotingParallelTreeLearner)
        cls = {"data": DataParallelTreeLearner,
               "feature": FeatureParallelTreeLearner,
               "voting": VotingParallelTreeLearner}[tl]
        return cls(ds, self.config)

    def add_valid_set(self, ds: BinnedDataset, name: str) -> None:
        self.valid_sets.append((name, ds))
        self.valid_binned.append(jnp.asarray(ds.binned))
        self.valid_metrics.append(create_metrics(self.config, ds.metadata, ds.num_data))
        K = self.num_tree_per_iteration
        init = jnp.zeros((K, ds.num_data), dtype=jnp.float32)
        if ds.metadata.init_score is not None:
            s = np.asarray(ds.metadata.init_score, dtype=np.float32)
            init = jnp.asarray(s.reshape(K, ds.num_data) if s.size == K * ds.num_data
                               else np.tile(s, (K, 1)))
        self.valid_scores.append(init)
        # replay existing model onto the new valid set (one batched dispatch)
        if self.models:
            vi = len(self.valid_sets) - 1
            trees = self.host_models
            forest, depth = forest_to_arrays(trees, feature_meta=self._meta,
                                             use_inner_feature=True)
            if any(getattr(t, "is_linear", False) for t in trees):
                if ds.raw is None:
                    log.fatal("Valid set %r needs the raw feature matrix "
                              "retained to replay a linear_tree model", name)
                self.valid_scores[vi] = self._replay_linear_forest(
                    trees, forest, depth, self.valid_binned[vi], ds.raw,
                    self.valid_scores[vi])
                return
            tree_class = jnp.asarray(
                [i % K for i in range(len(trees))], jnp.int32)
            self.valid_scores[vi] = self.valid_scores[vi] + \
                dispatch_forest_predict(self.config, self.valid_binned[vi],
                                        forest, tree_class, K, depth,
                                        binned=True)

    def _replay_linear_forest(self, trees, forest, depth, binned, raw,
                              scores) -> jax.Array:
        """Add a linear-tree forest's outputs to ``scores`` (constant-leaf
        replay would silently diverge from predict()).

        The adds run PER TREE in forest order, each tree's float64 host
        outputs rounded to f32 before its device add — the exact addition
        sequence training used (`_update_train_score` adds one f32 tree at
        a time), so snapshot resume replays scores bit-identically. A
        single summed-in-f64 add would differ by ulps and silently break
        kill-and-resume byte-identity (the PR 6 drift class)."""
        from .tree import linear_leaf_outputs
        K = self.num_tree_per_iteration
        # one leaf-index fetch for the whole forest being replayed
        # (resume/valid attach — no hot function reaches this path, so R1
        # never fired here; the suppression this comment used to carry was
        # inert from birth and R14 flagged it)
        leaf_T = np.asarray(jax.device_get(dispatch_forest_leaf(
            self.config, binned, forest, depth, binned=True)))
        for i, t in enumerate(trees):
            add = linear_leaf_outputs(t, raw, leaf_T[i])
            scores = scores.at[i % K].add(
                jnp.asarray(add.astype(np.float32)))
        return scores

    # ------------------------------------------------------------------
    def boosting(self) -> Tuple[jax.Array, jax.Array]:
        """Compute gradients at current scores
        (reference: GBDT::Boosting, gbdt.cpp:222-237)."""
        return self.objective.get_gradients_fast(self.scores)

    def train_one_iter(self, grad: Optional[jax.Array] = None,
                       hess: Optional[jax.Array] = None) -> bool:
        """One boosting iteration. Returns True when training should stop
        (no splittable leaves), mirroring gbdt.cpp:346-454."""
        cfg = self.config
        tel = self.telemetry
        tel.begin_iteration(self.iter_)
        # crash fault point + skip_tree restore capture (a no-op when DART
        # already captured the pre-dropout state for this iteration)
        self.guard.begin_iteration(self)
        self.last_iteration_skipped = False
        init_scores = [0.0] * self.num_tree_per_iteration
        if grad is None or hess is None:
            if self.objective is None:
                log.fatal("No objective and no custom gradients provided")
            # boost from average once, before the first gradient computation
            if not self.models and not self.has_init_score \
                    and cfg.boost_from_average:
                init_obj = self.objective
                ts = self.train_set
                if (getattr(ts, "process_sharded", False)
                        and getattr(ts, "global_label", None) is not None):
                    # the init score must come from GLOBAL label stats or
                    # each rank bakes a different constant into tree 0
                    # (reference: BoostFromAverage syncs over Network)
                    from ..data.dataset import Metadata
                    from ..objectives.base import create_objective
                    md_g = Metadata()
                    md_g.label = ts.global_label
                    md_g.weight = ts.global_weight
                    if getattr(ts, "global_group", None) is not None:
                        md_g.set_group(ts.global_group)
                    init_obj = create_objective(cfg)
                    init_obj.init(md_g, len(ts.global_label))
                for k in range(self.num_tree_per_iteration):
                    init = init_obj.boost_from_score(k)
                    if abs(init) > K_EPSILON:
                        init_scores[k] = init
                        self.scores = self.scores.at[k].add(init)
                        for vi in range(len(self.valid_scores)):
                            self.valid_scores[vi] = self.valid_scores[vi].at[k].add(init)
                        log.info("Start training from score %f", init)
            with tel.phase("gradients"):
                grad, hess = self.boosting()
        grad, hess = self.guard.admit_gradients(self, grad, hess)

        with tel.phase("sampling"):
            grad, hess, mask = self.sample_strategy.sample(self.iter_, grad,
                                                           hess)

        from .fused_learner import FusedTreeLearner
        fast = (isinstance(self.learner, FusedTreeLearner)
                and type(self) is GBDT
                and not cfg.linear_tree
                and (self.objective is None
                     or not self.objective.is_renew_tree_output))
        if fast:
            # zero-sync path: the tree stays on device; host Tree objects are
            # materialized lazily (save/predict). The "no more splittable
            # leaves" stop check is skipped to avoid a per-iteration D2H —
            # converged training just appends constant trees.
            for k in range(self.num_tree_per_iteration):
                with tel.phase("tree", legacy="tree: fused train"):
                    rec = self.learner.train_device(grad[k], hess[k],
                                                    row_mask=mask)
                with tel.phase("score_update"):
                    lv = rec.leaf_value * self.shrinkage_rate
                    self.scores = self.scores.at[k].add(lv[rec.row_leaf])
                # drop the O(N) row->leaf map from the kept record: at
                # 10.5M rows x 500 trees it would pin ~21 GB of HBM that
                # materialization never reads
                rec = rec._replace(row_leaf=None)
                lazy = _LazyTree(self.learner, rec, self.shrinkage_rate,
                                 init_scores[k])
                self.models.append(lazy)
                if self.valid_sets:
                    tree = self._tree(len(self.models) - 1)
                    with tel.phase("eval"):
                        for vi in range(len(self.valid_sets)):
                            self._add_valid_tree_score(vi, tree, k)
            self.iter_ += 1
            tel.end_iteration(sync=self.scores)
            self.last_iteration_skipped = self.guard.end_iteration(self)
            return False

        should_continue = False
        for k in range(self.num_tree_per_iteration):
            with tel.phase("tree", legacy="tree: train"):
                tree = self.learner.train(grad[k], hess[k], row_mask=mask)
            if tree.num_leaves > 1:
                should_continue = True
                if cfg.linear_tree and type(self) is GBDT \
                        and type(self.learner) in (SerialTreeLearner,
                                                   FusedTreeLearner):
                    self._fit_linear_tree(tree, k, grad[k], hess[k])
                if self.objective is not None and self.objective.is_renew_tree_output:
                    self._renew_tree_output(tree, k, mask)
                tree.apply_shrinkage(self.shrinkage_rate)
                with tel.phase("score_update"):
                    self._update_train_score(tree, k)
                with tel.phase("eval"):
                    for vi in range(len(self.valid_sets)):
                        self._add_valid_tree_score(vi, tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    self._tree_add_bias(tree, init_scores[k], k)
            else:
                if len(self.models) < self.num_tree_per_iteration:
                    if self.objective is not None and not cfg.boost_from_average \
                            and not self.has_init_score:
                        init_scores[k] = self.objective.boost_from_score(k)
                        self.scores = self.scores.at[k].add(init_scores[k])
                        for vi in range(len(self.valid_scores)):
                            self.valid_scores[vi] = \
                                self.valid_scores[vi].at[k].add(init_scores[k])
                    tree.leaf_value[0] = init_scores[k]
            self.models.append(tree)

        if not should_continue:
            tel.end_iteration(sync=self.scores)
            if self.guard.end_iteration(self):
                # non-finite gradients made every leaf unsplittable: this is
                # a skipped iteration, not convergence — keep training
                self.last_iteration_skipped = True
                return False
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter_ += 1
        tel.end_iteration(sync=self.scores)
        self.last_iteration_skipped = self.guard.end_iteration(self)
        return False

    def _guard_state_capture(self) -> dict:
        """Restore point for guard_nonfinite=skip_tree: scores are immutable
        jax arrays, so holding the old references IS the snapshot (no
        copies). DART extends this with its dropout bookkeeping."""
        return {"scores": self.scores,
                "valid_scores": list(self.valid_scores),
                "n_models": len(self.models),
                "iter": self.iter_,
                "shrinkage": self.shrinkage_rate}

    def _guard_state_restore(self, st: dict) -> None:
        self.scores = st["scores"]
        self.valid_scores[:] = st["valid_scores"]
        del self.models[st["n_models"]:]
        self.iter_ = st["iter"]
        self.shrinkage_rate = st["shrinkage"]

    def _host_leaf_index(self, tree: Tree) -> np.ndarray:
        """Per-row leaf assignment from the serial learner's partition."""
        perm = np.asarray(jax.device_get(self.learner.last_perm))
        begins = self.learner.last_leaf_begin
        counts = self.learner.last_leaf_count
        leaf_idx = np.zeros(self.num_data, dtype=np.int32)
        for leaf in range(tree.num_leaves):
            b, c = int(begins[leaf]), int(counts[leaf])
            leaf_idx[perm[b:b + c]] = leaf
        return leaf_idx

    def _linear_raw_dev(self) -> jax.Array:
        """Device copy of the linear_tree-retained raw matrix, uploaded
        once per training run (the moment accumulation reads it every
        tree)."""
        raw = self.train_set.raw
        cache = getattr(self, "_linear_raw_cache", None)
        if cache is None or cache[0] is not raw:
            self._linear_raw_cache = (raw, jnp.asarray(raw))
        return self._linear_raw_cache[1]

    def _fit_linear_tree(self, tree: Tree, k: int, grad, hess) -> None:
        """Fit linear leaf models over the raw features of the leaf paths:
        MXU-batched moment accumulation + ONE stacked solve per tree
        (models/linear_leaf.py; reference:
        LinearTreeLearner::CalculateLinear host loop replaced wholesale —
        both the serial and the fused learner land here, so their linear
        trees are bit-identical by construction)."""
        from .linear_leaf import (fit_linear_leaves_batched,
                                  numeric_feature_mask)
        ds = self.train_set
        if ds.raw is None:
            log.warning("linear_tree needs the retained raw matrix; "
                        "skipping linear fit")
            return
        numeric = numeric_feature_mask(ds)
        if getattr(self.learner, "last_row_leaf", None) is not None:
            # fused learner: the device row->leaf map IS the membership
            leaf_dev = self.learner.last_row_leaf
            # graftlint: disable=R1 — one O(N) map fetch per tree: the
            # host mirror drives the linear score update + resume replay
            # (exact f64 leaf outputs), opt-in linear_tree path
            leaf_idx = np.asarray(jax.device_get(leaf_dev))
        else:
            # graftlint: disable=R1 — serial learner: the leaf permutation
            # is the membership source; ONE transfer per tree
            perm = np.asarray(jax.device_get(self.learner.last_perm))
            begins = self.learner.last_leaf_begin
            counts = self.learner.last_leaf_count
            leaf_idx = np.zeros(self.num_data, dtype=np.int32)
            for leaf in range(tree.num_leaves):
                b, c = int(begins[leaf]), int(counts[leaf])
                leaf_idx[perm[b:b + c]] = leaf
            leaf_dev = jnp.asarray(leaf_idx)
        fit_linear_leaves_batched(tree, self._linear_raw_dev(), leaf_dev,
                                  grad, hess, self.config.linear_lambda,
                                  numeric, self.config.num_leaves)
        # cache the per-row leaf map for the score update (saves a second
        # full-permutation D2H per iteration)
        self._linear_leaf_idx = leaf_idx

    def _tree_add_bias(self, tree: Tree, bias: float, k: int) -> None:
        """Fold the boost-from-average init into the first tree
        (reference: Tree::AddBias via gbdt.cpp:421)."""
        tree.leaf_value[:tree.num_leaves] += bias
        tree.internal_value = [v + bias for v in tree.internal_value]
        if getattr(tree, "is_linear", False):
            tree.leaf_const[:tree.num_leaves] += bias

    def _tree(self, i: int) -> Tree:
        m = self.models[i]
        if isinstance(m, _LazyTree):
            m = m.materialize()
            self.models[i] = m
        return m

    def _materialize_lazy(self, idx=None) -> None:
        """Materialize every (requested) device-resident tree in ONE batched
        transfer (fused learner's materialize_batch) instead of per-tree
        round-trips — the difference between one and hundreds of D2H syncs
        when predicting from a freshly trained model."""
        want = range(len(self.models)) if idx is None else idx
        lazy = [i for i in want if isinstance(self.models[i], _LazyTree)]
        if len(lazy) <= 1:
            return
        learner = self.models[lazy[0]].learner
        if not hasattr(learner, "materialize_batch"):
            return
        same = [i for i in lazy if self.models[i].learner is learner]
        trees = learner.materialize_batch([self.models[i].rec for i in same])
        for i, t in zip(same, trees):
            m = self.models[i]
            self.models[i] = _finalize_tree(t, m.shrinkage, m.bias)

    @property
    def host_models(self) -> List[Tree]:
        self._materialize_lazy()
        return [self._tree(i) for i in range(len(self.models))]

    def _update_train_score(self, tree: Tree, k: int) -> None:
        if getattr(tree, "is_linear", False):
            from .tree import linear_leaf_outputs
            leaf_idx = (self._linear_leaf_idx
                        if getattr(self, "_linear_leaf_idx", None) is not None
                        else self._host_leaf_index(tree))
            self._linear_leaf_idx = None
            add = linear_leaf_outputs(tree, self.train_set.raw, leaf_idx)
            self.scores = self.scores.at[k].add(
                jnp.asarray(add.astype(np.float32)))
            return
        if getattr(self.learner, "last_row_leaf", None) is not None:
            # fused learner: leaf membership is row_leaf (device)
            lv = jnp.asarray(
                np.asarray(tree.leaf_value[:tree.max_leaves], np.float32))
            self.scores = self.scores.at[k].add(
                lv[self.learner.last_row_leaf])
            return
        lv = jnp.asarray(tree.leaf_value[:tree.num_leaves], dtype=jnp.float32)
        if hasattr(self.learner, "update_scores"):   # distributed learners
            self.scores = self.scores.at[k].set(
                self.learner.update_scores(self.scores[k], lv))
            return
        self.scores = self.scores.at[k].set(_add_tree_score(
            self.scores[k], self.learner.last_perm,
            jnp.asarray(self.learner.last_leaf_begin, dtype=jnp.int32),
            jnp.asarray(self.learner.last_leaf_count, dtype=jnp.int32),
            lv, tree.num_leaves))

    def _add_valid_tree_score(self, vi: int, tree: Tree, k: int) -> None:
        x = self.valid_binned[vi]
        arrs = tree_to_arrays(tree, feature_meta=self._meta, use_inner_feature=True)
        depth = _round_depth(tree.max_depth + 1)
        if getattr(tree, "is_linear", False):
            from ..ops.predict import predict_leaf_index_binned
            from .tree import linear_leaf_outputs
            vraw = self.valid_sets[vi][1].raw
            if vraw is None:
                log.warning("Valid set %r has no retained raw matrix; "
                            "linear-tree eval falls back to constant leaf "
                            "values (metrics will not match predict())",
                            self.valid_sets[vi][0])
            if vraw is not None:
                # graftlint: disable=R1 — linear-tree valid-set eval must
                # gather raw feature rows per leaf on the host; one
                # transfer per tree per valid set, opt-in linear_tree path
                leaf_idx = np.asarray(jax.device_get(
                    predict_leaf_index_binned(x, arrs, depth)))
                add = linear_leaf_outputs(tree, vraw, leaf_idx)
                self.valid_scores[vi] = self.valid_scores[vi].at[k].add(
                    jnp.asarray(add.astype(np.float32)))
                return
        add = predict_tree_binned(x, arrs, depth)
        self.valid_scores[vi] = self.valid_scores[vi].at[k].add(add)

    def _renew_tree_output(self, tree: Tree, k: int, mask) -> None:
        """L1-family leaf refit by weighted percentile of residuals
        (reference: RenewTreeOutput path in gbdt.cpp:412 +
        regression_objective.hpp percentiles)."""
        # graftlint: disable=R1 — the L1-family leaf refit (RenewTreeOutput)
        # is a host percentile pass over residuals by design, once per tree
        # on the opt-in renew path; score + mask ride ONE batched transfer
        score, mask_np = (None if a is None else np.asarray(a)
                          for a in jax.device_get((self.scores[k], mask)))
        if getattr(self.learner, "last_row_leaf", None) is not None:
            # fused learner: leaf membership from row_leaf
            # graftlint: disable=R1 — same renew pass: leaf membership is
            # consumed by the host percentile refit, one transfer per tree
            row_leaf = np.asarray(jax.device_get(self.learner.last_row_leaf))
            for leaf in range(tree.num_leaves):
                rows = np.nonzero(row_leaf == leaf)[0]
                if mask_np is not None:
                    rows = rows[mask_np[rows]]
                if len(rows):
                    tree.leaf_value[leaf] = self.objective.renew_tree_output(
                        rows, score)
            return
        # graftlint: disable=R1 — same renew pass, host-loop learners: the
        # leaf permutation feeds the host percentile refit, once per tree
        perm = np.asarray(jax.device_get(self.learner.last_perm))
        begins = self.learner.last_leaf_begin
        counts = self.learner.last_leaf_count
        distributed = begins.ndim == 2     # [D, L] per-shard layout
        n_loc = getattr(self.learner, "n_loc", 0)
        for leaf in range(tree.num_leaves):
            if distributed:
                parts = []
                for d in range(begins.shape[0]):
                    b, c = int(begins[d, leaf]), int(counts[d, leaf])
                    parts.append(perm[d * n_loc + b: d * n_loc + b + c] + d * n_loc)
                rows = np.concatenate(parts) if parts else np.empty(0, np.int64)
                rows = rows[rows < self.num_data]
            else:
                rows = perm[int(begins[leaf]): int(begins[leaf]) + int(counts[leaf])]
            if mask_np is not None:
                rows = rows[mask_np[rows]]
            if len(rows) == 0:
                continue
            tree.leaf_value[leaf] = self.objective.renew_tree_output(rows, score)

    # ------------------------------------------------------------------
    # continued training / refit
    # ------------------------------------------------------------------
    def resume_from(self, trees: List[Tree]) -> None:
        """Continue training from a loaded model's trees: keep the tree list
        and replay their scores onto the train/valid sets in one batched
        dispatch (reference: Boosting::CreateBoosting(type, filename) +
        GBDT::ResetTrainingData, src/boosting/boosting.cpp:34 / gbdt.cpp;
        Python engine.py:109 init_model)."""
        import copy
        from .tree import rebind_to_dataset
        K = self.num_tree_per_iteration
        if len(trees) % K != 0:
            log.fatal("init_model has %d trees, not a multiple of "
                      "num_tree_per_iteration=%d", len(trees), K)
        if self.train_set is None:
            log.fatal("resume_from needs a training dataset")
        # deep-copy: rebinding mutates bin-space (and, for missing-type
        # mismatches, raw-space) fields — the caller's trees stay pristine
        trees = [copy.deepcopy(t) for t in trees]
        for t in trees:
            rebind_to_dataset(t, self.train_set)
        self.models = list(trees)
        self.iter_ = len(trees) // K
        forest, depth = forest_to_arrays(trees, feature_meta=self._meta,
                                         use_inner_feature=True)
        tree_class = jnp.asarray([i % K for i in range(len(trees))], jnp.int32)
        if any(getattr(t, "is_linear", False) for t in trees):
            # linear trees predict leaf_const + leaf_coeff·x, not leaf_value;
            # replaying with constant leaves would silently train all later
            # gradients against wrong scores. Replay host-side on raw rows.
            # (valid sets are added AFTER resume in engine.py/cli.py; their
            # linear replay lives in add_valid_set)
            if type(self) is not GBDT:
                # DART's dropout replays dropped trees with constant leaf
                # values — resumed linear trees would corrupt scores on the
                # first drop; RF averaging has the same blind spot
                log.fatal("Continued training from a linear_tree model is "
                          "only supported with boosting=gbdt")
            if self.train_set.raw is None or any(
                    ds.raw is None for _, ds in self.valid_sets):
                log.fatal("Continued training from a linear_tree model needs "
                          "the raw feature matrix retained on every dataset "
                          "(train a linear_tree Dataset or disable "
                          "init_model)")
            self.scores = self._replay_linear_forest(
                trees, forest, depth, jnp.asarray(self.train_set.binned),
                self.train_set.raw, self.scores)
            for vi, (_, vds) in enumerate(self.valid_sets):
                self.valid_scores[vi] = self._replay_linear_forest(
                    trees, forest, depth, self.valid_binned[vi], vds.raw,
                    self.valid_scores[vi])
            return
        self.scores = self.scores + dispatch_forest_predict(
            self.config, jnp.asarray(self.train_set.binned), forest,
            tree_class, K, depth, binned=True)
        for vi in range(len(self.valid_sets)):
            self.valid_scores[vi] = self.valid_scores[vi] + \
                dispatch_forest_predict(self.config, self.valid_binned[vi],
                                        forest, tree_class, K, depth,
                                        binned=True)

    def refit(self, data: np.ndarray, label: np.ndarray, weight=None,
              group=None, decay_rate: Optional[float] = None) -> None:
        """Refit the leaf values of the existing trees on new data, keeping
        the tree structures (reference: GBDT::RefitTree in gbdt.cpp +
        SerialTreeLearner::FitByExistingTree; CLI task=refit,
        application.cpp:254-290). New leaf outputs are the regularized
        Newton step over the rows landing in each leaf
        (feature_histogram.hpp:198 CalculateSplittedLeafOutput), blended by
        ``refit_decay_rate``."""
        from ..data.dataset import Metadata
        self.invalidate_predict_cache()     # leaf values change in place
        cfg = self.config
        decay = cfg.refit_decay_rate if decay_rate is None else float(decay_rate)
        X = np.ascontiguousarray(np.asarray(data, dtype=np.float32))
        N = X.shape[0]
        K = self.num_tree_per_iteration
        trees = self.host_models
        if not trees:
            log.fatal("refit needs a trained model")
        if any(getattr(t, "is_linear", False) for t in trees):
            # refit rewrites leaf_value only; predict() would keep preferring
            # the stale linear payload. Drop it so the refitted constant
            # leaves actually drive predictions.
            log.warning("refit drops linear-leaf models; the refitted trees "
                        "predict with constant leaf values")
            for t in trees:
                t.is_linear = False
        md = Metadata()
        md.label = np.asarray(label, dtype=np.float32).reshape(-1)
        if weight is not None:
            md.weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        md.set_group(group)
        md.check(N)
        obj = create_objective(cfg)
        if obj is None:
            log.fatal("refit requires a built-in objective")
        obj.init(md, N)

        forest, depth = forest_to_arrays(trees, use_inner_feature=False)
        leaf_of = np.asarray(jax.device_get(dispatch_forest_leaf(
            self.config, jnp.asarray(X), forest, depth,
            binned=False)))   # [T, N]

        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        mds = cfg.max_delta_step

        def newton_out(sg, sh):
            num = (-np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0)
                   if l1 > 0 else -sg)
            out = num / (sh + l2 + K_EPSILON)
            if mds > 0:
                out = np.clip(out, -mds, mds)
            return out

        scores = jnp.zeros((K, N), dtype=jnp.float32)
        for it in range(len(trees) // K):
            grad, hess = obj.get_gradients(scores)
            g = np.asarray(jax.device_get(grad))
            h = np.asarray(jax.device_get(hess))
            for k in range(K):
                ti = it * K + k
                t = trees[ti]
                L = t.num_leaves
                lf = leaf_of[ti]
                sg = np.bincount(lf, weights=g[k], minlength=L)[:L]
                sh = np.bincount(lf, weights=h[k], minlength=L)[:L]
                new_out = newton_out(sg, sh) * t.shrinkage
                old = t.leaf_value[:L].copy()
                t.leaf_value[:L] = decay * old + (1.0 - decay) * new_out
                scores = scores.at[k].add(
                    jnp.asarray(t.leaf_value[lf].astype(np.float32)))

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _converted_scores(self, raw: jax.Array) -> np.ndarray:
        out = self.objective.convert_output(raw) if self.objective else raw
        out = np.asarray(jax.device_get(out)).astype(np.float64)
        return out[0] if self.num_tree_per_iteration == 1 else out

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval("training", self.train_metrics,
                          self._converted_scores(self.scores))

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vi, (name, _) in enumerate(self.valid_sets):
            out.extend(self._eval(name, self.valid_metrics[vi],
                                  self._converted_scores(self.valid_scores[vi])))
        return out

    @staticmethod
    def _eval(data_name, metrics, converted) -> List[Tuple[str, str, float, bool]]:
        res = []
        for m in metrics:
            for mname, val in m.eval(converted):
                res.append((data_name, mname, val, m.greater_is_better))
        return res

    # ------------------------------------------------------------------
    # prediction
    # ------------------------------------------------------------------
    def _model_slice(self, start_iteration: int, num_iteration: int):
        K = self.num_tree_per_iteration
        end = len(self.models) if num_iteration < 0 else min(
            len(self.models), (start_iteration + num_iteration) * K)
        return list(range(start_iteration * K, end))

    def _check_predict_shape(self, data: np.ndarray) -> np.ndarray:
        """A matrix with fewer columns than the model's max split feature
        would silently mis-gather (clipped indices); fail loudly unless
        predict_disable_shape_check pads the missing columns with NaN
        (reference: c_api predict shape check + the override flag,
        include/LightGBM/config.h predict_disable_shape_check)."""
        key = len(self.models)
        cached = getattr(self, "_need_feats", None)
        if cached is None or cached[0] != key:
            need = 1 + max(
                (max(t.split_feature[:t.num_internal], default=0)
                 for t in (self._tree(i) for i in range(key))),
                default=0) if self.models else 0
            self._need_feats = (key, need)
        need = self._need_feats[1]
        if data.ndim != 2:
            log.fatal("predict expects a 2-D matrix, got shape %s",
                      (data.shape,))
        if data.shape[1] >= need:
            return data
        if not self.config.predict_disable_shape_check:
            log.fatal("The number of features in data (%d) is less than the "
                      "model needs (%d); set predict_disable_shape_check="
                      "true to pad missing features with NaN",
                      data.shape[1], need)
        pad = np.full((data.shape[0], need - data.shape[1]), np.nan,
                      dtype=data.dtype)
        return np.concatenate([data, pad], axis=1)

    def invalidate_predict_cache(self) -> None:
        """Drop every cached predict-side view of the forest and bump the
        model generation. Must be called by anything that mutates tree
        payloads in place (refit, set_leaf_output, shuffle_models);
        structural changes (train/rollback/resume) are covered by the
        model-count component of the cache keys."""
        self._fast_cache = None
        self._forest_cache = None
        self._compiled_cache = None
        self._pstream_cache = None
        self.generation += 1

    def _device_forest(self, idx, trees):
        """Device-resident stacked forest (+ pre-sliced tree blocks) for the
        raw-feature predict paths, cached on the booster: the forest is
        immutable between calls, so re-slicing and re-uploading it per
        predict call (ADVICE round 5, predict.py:313) was pure waste.
        Returns (forest, depth, tree_class, blocks)."""
        cfg = self.config
        key = (self.generation, len(self.models), idx[0], idx[-1], len(idx),
               cfg.predict_engine, cfg.predict_tree_tile)
        cache = getattr(self, "_forest_cache", None)
        if cache is None or cache[0] != key:
            K = self.num_tree_per_iteration
            forest, depth = forest_to_arrays(trees, use_inner_feature=False)
            tree_class = jnp.asarray([i % K for i in idx], jnp.int32)
            if cfg.predict_engine in ("tensor", "compiled"):
                blocks = build_tree_tiles(forest, tree_class,
                                          cfg.predict_tree_tile)
            else:
                blocks = build_forest_blocks(forest, tree_class)
            self._forest_cache = (key, (forest, depth, tree_class, blocks))
        return self._forest_cache[1]

    def _compiled_forest(self, start_iteration: int, num_iteration: int,
                         es_freq: int = 0):
        """Cached compiled-forest view (lambdagap_tpu.infer) for the raw
        serving path: the forest is lowered ONCE — pruned, merged,
        palette-quantized, blocked — and the CompiledForest holds the
        device-resident buffers across predict calls, like _device_forest
        does for the training-shaped tables."""
        cfg = self.config
        key = (self.generation, len(self.models), start_iteration,
               num_iteration, es_freq,
               float(cfg.pred_early_stop_margin), cfg.infer_quant,
               cfg.infer_prune, cfg.infer_merge_trees,
               cfg.infer_node_block_kb, cfg.infer_row_block)
        cache = getattr(self, "_compiled_cache", None)
        if cache is None or cache[0] != key:
            from ..infer import CompiledForest, compile_forest
            artifact = compile_forest(self, start_iteration, num_iteration)
            self._compiled_cache = (key, CompiledForest(
                artifact, early_stop_freq=es_freq,
                early_stop_margin=float(cfg.pred_early_stop_margin),
                row_block=cfg.infer_row_block))
        return self._compiled_cache[1]

    def _fast_forest(self, idx, trees):
        """Cached flat forest for the native low-latency predictor; None
        when the native lib is unavailable."""
        from ..native import FastForest, get_lib
        if get_lib() is None:
            return None
        key = (len(self.models), idx[0], idx[-1], len(idx))
        cache = getattr(self, "_fast_cache", None)
        if cache is None or cache[0] != key:
            K = self.num_tree_per_iteration
            self._fast_cache = (key, FastForest(trees, [i % K for i in idx],
                                                K))
        return self._fast_cache[1]

    def predict_raw(self, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
        """Raw scores for new data [N, D] -> [N] or [N, K].

        The whole forest runs in one jitted dispatch (stacked TreeArrays +
        scan; the analog of GBDT::Predict over inlined trees, reference:
        include/LightGBM/tree.h:130-141)."""
        data = np.asarray(data, dtype=np.float32)
        data = self._check_predict_shape(data)
        K = self.num_tree_per_iteration
        N = data.shape[0]
        idx = self._model_slice(start_iteration, num_iteration)
        if not idx:
            res = np.zeros((K, N), dtype=np.float32)
            return res[0] if K == 1 else res.T
        self._materialize_lazy(idx)
        trees = [self._tree(i) for i in idx]
        # margin-based prediction early stop, classification only
        # (reference: src/boosting/prediction_early_stop.cpp)
        # freq counts boosting iterations; trees are iter-major, so the
        # per-tree check interval is freq*K (keeps checks on iteration
        # boundaries — all classes equally updated)
        es_freq = (self.config.pred_early_stop_freq * K
                   if self.config.pred_early_stop and self.objective is not None
                   and self.objective.name in ("binary", "multiclass",
                                               "multiclassova") else 0)
        has_linear = any(getattr(t, "is_linear", False) for t in trees)
        if (N <= max(int(self.config.tpu_fast_predict_rows), 512)
                and not has_linear and es_freq == 0):
            # serving-shaped call: threaded native host traversal, no jit
            # dispatch (reference: src/c_api.cpp:63 SingleRowPredictorInner
            # + the OpenMP row loop of Predictor). The threshold is a
            # config knob: on a healthy chip the device forest wins earlier
            # than on a throttled one (bench measures both sides)
            # (reference: src/c_api.cpp:63)
            ff = self._fast_forest(idx, trees)
            if ff is not None and data.shape[1] > ff.max_feat:
                res = ff.predict(data).astype(np.float32).T      # [K, N]
                if self.average_output:
                    res = res / max(1, len(idx) // max(K, 1))
                return res[0] if K == 1 else res.T
        if self.config.predict_engine == "compiled":
            # serving-shaped path: the infer compiler lowers the forest
            # once (pruned/merged/quantized node blocks); traversal +
            # forest-order accumulation stay bit-identical to the engines
            # below, so averaging/conversion here is shared unchanged
            cf = self._compiled_forest(start_iteration, num_iteration,
                                       es_freq)
            with costplane.PLANE.wall("predict"):
                # device_get inside the bracket: the noted wall is
                # device-complete (the cost plane's roofline join contract)
                res = np.asarray(jax.device_get(
                    cf.predict(jnp.asarray(data))))
            if self.average_output:
                res = res / max(1, len(idx) // max(K, 1))
            return res[0] if K == 1 else res.T
        forest, depth, tree_class, blocks = self._device_forest(idx, trees)
        # linear forests ride the SAME device dispatch: the traversal carry
        # accumulates each leaf's dot product from the padded coefficient
        # tables stacked into the forest arrays (ops/linear.py), so serve's
        # compiled buckets and this path stay bit-identical
        with costplane.PLANE.wall("predict"):
            out = dispatch_forest_predict(
                self.config, jnp.asarray(data), forest, tree_class, K,
                depth, binned=False, early_stop_freq=es_freq,
                early_stop_margin=float(self.config.pred_early_stop_margin),
                blocks=blocks, has_linear=has_linear)
            res = np.asarray(jax.device_get(out))
        if self.average_output:
            n_iters = max(1, len(idx) // max(K, 1))
            res = res / n_iters
        return res[0] if K == 1 else res.T

    def predict_leaf(self, data: np.ndarray, start_iteration: int = 0,
                     num_iteration: int = -1) -> np.ndarray:
        """Leaf index per (row, tree) (reference: predict_leaf_index path)."""
        data = np.asarray(data, dtype=np.float32)
        data = self._check_predict_shape(data)
        idx = self._model_slice(start_iteration, num_iteration)
        if not idx:
            return np.zeros((data.shape[0], 0), np.int32)
        self._materialize_lazy(idx)
        trees = [self._tree(i) for i in idx]
        forest, depth, _, blocks = self._device_forest(idx, trees)
        ys = dispatch_forest_leaf(self.config, jnp.asarray(data), forest,
                                  depth, binned=False, blocks=blocks)
        return np.asarray(jax.device_get(ys)).astype(np.int32).T

    def predict_contrib(self, data: np.ndarray, start_iteration: int = 0,
                        num_iteration: int = -1) -> np.ndarray:
        """SHAP feature contributions: [N, F+1] per class, last column the
        expected value, rows summing to the raw prediction (reference:
        Tree::PredictContrib / TreeSHAP, src/io/tree.cpp; native kernel in
        native/treeshap.cpp)."""
        from .shap import tree_shap_accumulate, tree_shap_linear
        data = np.asarray(data, dtype=np.float64)
        data = np.ascontiguousarray(self._check_predict_shape(data))
        N, F_data = data.shape
        K = self.num_tree_per_iteration
        idx = self._model_slice(start_iteration, num_iteration)
        self._materialize_lazy(idx)
        trees = [self._tree(i) for i in idx]
        max_f = max((f for t in trees
                     for f in t.split_feature[:t.num_internal]), default=-1)
        if max_f >= F_data:
            log.fatal("pred_contrib input has %d features but the model "
                      "splits on feature %d", F_data, max_f)
        phi = np.zeros((K, N, F_data + 1), dtype=np.float64)
        with costplane.PLANE.wall("predict_shap"):
            for pos, i in enumerate(idx):
                t = trees[pos]
                if getattr(t, "is_linear", False):
                    # coefficient-attribution split (arXiv:1802.05640): the
                    # structural TreeSHAP runs over leaf CONSTANTS, the
                    # linear terms attribute directly to their features —
                    # rows still sum to the raw prediction (models/shap.py)
                    tree_shap_linear(t, data, phi[i % K])
                else:
                    tree_shap_accumulate(t, data, phi[i % K])
        if costplane.PLANE.enabled:
            # host numpy loop, no XLA lowering to inspect: an analytic
            # traffic model stands in (TreeSHAP visits each leaf's root
            # path once per row: ~O(N * leaves * depth^2) flops; each tree
            # pass streams the row matrix and accumulates into phi)
            leaves = sum(max(int(t.num_leaves), 1) for t in trees)
            depth_sq = max(int(self.config.max_depth), 6) ** 2
            costplane.PLANE.record_host(
                "predict.shap",
                flops=float(N) * leaves * depth_sq,
                bytes_accessed=float(len(trees)) * data.nbytes
                + 2.0 * phi.nbytes,
                peak_hbm_bytes=int(data.nbytes + phi.nbytes),
                phase="predict_shap", bucket=N)
        if self.average_output:
            phi /= max(1, len(idx) // max(K, 1))
        if K == 1:
            return phi[0]
        return phi.transpose(1, 0, 2).reshape(N, K * (F_data + 1))

    def predict(self, data: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1) -> np.ndarray:
        raw = self.predict_raw(data, start_iteration, num_iteration)
        if raw_score or self.objective is None:
            return raw
        stacked = raw.T if raw.ndim == 2 else raw[None, :]
        if raw.shape[0] <= 512:
            # serving-size batch: transform on host, no device dispatch
            conv = np.asarray(self.objective.convert_output_np(
                np.asarray(stacked)))
        else:
            conv = np.asarray(jax.device_get(
                self.objective.convert_output(jnp.asarray(stacked))))
        return conv[0] if self.num_tree_per_iteration == 1 else conv.T

    def predict_stream(self, data, start_iteration: int = 0,
                       num_iteration: int = -1, raw_score: bool = False,
                       pred_contrib: bool = False, window_rows: int = 0,
                       out: Optional[np.ndarray] = None,
                       signal_source=None, throttle=None,
                       stats_out: Optional[dict] = None) -> np.ndarray:
        """Warehouse-scale out-of-core batch scoring (infer/stream.py):
        pumps host/memmap/file/ShardedBinnedDataset row windows through
        the double-buffered H2D ring into the configured predict engine
        and streams scores back through the D2H score ring — bit-identical
        to :meth:`predict_raw` (``raw_score=True``) / :meth:`predict` on
        every engine, window split and mesh grid. ``out`` (e.g. an
        ``np.memmap``) receives rows in place; ``signal_source`` (a
        SignalPlane) arms the co-tenant throttle; ``stats_out`` receives
        the run report (windows, phase totals, throttle snapshot)."""
        from ..infer.stream import predict_stream as _predict_stream
        return _predict_stream(
            self, data, start_iteration=start_iteration,
            num_iteration=num_iteration, raw_score=raw_score,
            pred_contrib=pred_contrib, window_rows=window_rows, out=out,
            signal_source=signal_source, throttle=throttle,
            stats_out=stats_out)

    # ------------------------------------------------------------------
    # serialization
    # ------------------------------------------------------------------
    @property
    def feature_names(self) -> List[str]:
        if self.train_set is not None:
            return self.train_set.feature_names
        return getattr(self, "_feature_names",
                       [f"Column_{i}" for i in range(self.max_feature_idx + 1)])

    def objective_string(self) -> str:
        if self.objective is None:
            return getattr(self, "_objective_string", "custom")
        name = self.objective.name
        if name == "binary":
            return f"binary sigmoid:{self.config.sigmoid:g}"
        if name == "multiclass":
            return f"multiclass num_class:{self.num_class}"
        if name == "multiclassova":
            return (f"multiclassova num_class:{self.num_class} "
                    f"sigmoid:{self.config.sigmoid:g}")
        if name == "lambdarank":
            return "lambdarank"
        if name == "regression" and getattr(self.objective, "sqrt", False):
            return "regression sqrt"
        return name

    def feature_infos(self) -> List[str]:
        """Per-feature value ranges (reference: Dataset feature_infos /
        bin.h:224 bin_info_string)."""
        if self.train_set is None:
            return getattr(self, "_feature_infos", [])
        out = []
        for m in self.train_set.mappers:
            if m.is_trivial:
                out.append("none")
            elif m.bin_type == "categorical":
                cats = [str(c) for c in m.bin_2_categorical[1:]]
                out.append(":".join(cats) if cats else "none")
            else:
                out.append(f"[{m.min_val:g}:{m.max_val:g}]")
        return out

    def save_model_to_string(self, start_iteration: int = 0,
                             num_iteration: int = -1,
                             importance_type: int = 0) -> str:
        from .model_text import save_model_to_string
        return save_model_to_string(self, start_iteration, num_iteration,
                                    importance_type)

    def save_model(self, filename: str, start_iteration: int = 0,
                   num_iteration: int = -1, importance_type: int = 0) -> None:
        # atomic (tmp + fsync + rename): a crash mid-save must never leave a
        # torn model file that a later load or auto-resume trusts
        from ..guard.snapshot import atomic_write_text
        atomic_write_text(filename,
                          self.save_model_to_string(start_iteration,
                                                    num_iteration,
                                                    importance_type))

    @classmethod
    def from_model_string(cls, text: str, config: Optional[Config] = None):
        """Load a saved model for prediction / continued training
        (reference: GBDT::LoadModelFromString, gbdt_model_text.cpp)."""
        from .model_text import load_model_from_string
        header, trees = load_model_from_string(text)
        cfg = config or Config()
        obj_str = header.get("objective", "regression").split(" ")[0]
        params = {"objective": obj_str} if obj_str != "custom" else {}
        for tok in header.get("objective", "").split(" ")[1:]:
            if ":" in tok:
                k, v = tok.split(":", 1)
                params[k] = v
            elif tok == "sqrt":
                params["reg_sqrt"] = True
        if "num_class" in header:
            params["num_class"] = int(header["num_class"])
        cfg.update(params)
        booster = cls(cfg, None)
        booster.models = trees
        booster.iter_ = len(trees) // booster.num_tree_per_iteration
        booster.max_feature_idx = int(header.get("max_feature_idx", 0))
        if header.get("average_output"):
            booster.average_output = True
        booster._feature_names = header.get("feature_names", "").split()
        booster._feature_infos = header.get("feature_infos", "").split()
        booster._objective_string = header.get("objective", "custom")
        return booster

    @classmethod
    def from_model_file(cls, filename: str, config: Optional[Config] = None):
        with open(filename) as f:
            return cls.from_model_string(f.read(), config)

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter_

    def rollback_one_iter(self) -> None:
        """(reference: GBDT::RollbackOneIter, gbdt.cpp:456) — drop the last
        iteration's trees and subtract their score contributions."""
        if self.iter_ <= 0:
            return
        for k in range(self.num_tree_per_iteration):
            tree = self._tree(len(self.models) - self.num_tree_per_iteration + k)
            if getattr(tree, "is_linear", False):
                # subtracting constant leaf values would silently corrupt
                # the scores a linear tree updated with its dot products
                log.fatal("rollback_one_iter is not supported for "
                          "linear_tree models")
            # subtract contribution by re-adding with negated leaf values
            arrs = tree_to_arrays(tree, feature_meta=self._meta,
                                  use_inner_feature=True)
            arrs = arrs._replace(leaf_value=-arrs.leaf_value)
            depth = _round_depth(tree.max_depth + 1)
            self.scores = self.scores.at[k].add(
                predict_tree_binned(self.learner.x_binned, arrs, depth))
            for vi in range(len(self.valid_sets)):
                self.valid_scores[vi] = self.valid_scores[vi].at[k].add(
                    predict_tree_binned(self.valid_binned[vi], arrs, depth))
        del self.models[-self.num_tree_per_iteration:]
        self.iter_ -= 1


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "gbdt._add_tree_score", collective_free=True,
    notes="score accumulation after each tree; device-resident add")
