"""GBDT boosting orchestration.

TPU re-implementation of the reference's GBDT class
(reference: src/boosting/gbdt.{h:37,cpp} — Init :73-129, TrainOneIter
:346-454, BoostFromAverage :321, UpdateScore :495-524, eval :476-493).

Scores live on device as ``[K, N]`` float32. The training-score update never
traverses trees: the learner's partition already knows every row's leaf, so
adding a tree is one gather + scatter-add (the analog of
``ScoreUpdater::AddScore`` going through ``AddScoreByLeaf``,
reference: src/boosting/score_updater.hpp:21-110).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import BinnedDataset
from ..metrics.base import Metric, create_metrics
from ..objectives.base import ObjectiveFunction, create_objective
from ..ops.predict import predict_tree_binned, predict_tree_raw, tree_to_arrays
from ..utils import log
from .learner import SerialTreeLearner
from .sample_strategy import create_sample_strategy
from .tree import Tree

K_EPSILON = 1e-15


@functools.partial(jax.jit, static_argnames=("num_leaves",))
def _add_tree_score(score, perm, leaf_begin, leaf_count, leaf_values,
                    num_leaves: int):
    """score[perm[i]] += leaf_value[leaf containing position i]."""
    del leaf_count
    N = score.shape[0]
    order = jnp.argsort(leaf_begin)
    sorted_begin = leaf_begin[order]
    which = jnp.searchsorted(sorted_begin, jnp.arange(N, dtype=leaf_begin.dtype),
                             side="right") - 1
    pos_leaf = order[which]
    vals = leaf_values[pos_leaf]
    return score.at[perm].add(vals)


def _round_depth(d: int) -> int:
    """Pad traversal depth to a multiple of 8 to bound jit specializations."""
    return max(8, ((d + 7) // 8) * 8)


class GBDT:
    """Gradient Boosting Decision Tree booster."""

    def __init__(self, config: Config, train_set: Optional[BinnedDataset]) -> None:
        self.config = config
        self.train_set = train_set
        self.iter_ = 0
        self.models: List[Tree] = []           # flat: iter-major, class-minor
        self.best_iteration = -1
        self.shrinkage_rate = config.learning_rate

        self.objective: Optional[ObjectiveFunction] = create_objective(config)
        self.num_class = self.objective.num_class if self.objective else config.num_class
        self.num_tree_per_iteration = max(self.num_class, 1)

        self.train_metrics: List[Metric] = []
        self.valid_sets: List[Tuple[str, BinnedDataset]] = []
        self.valid_binned: List[jax.Array] = []
        self.valid_metrics: List[List[Metric]] = []
        self.valid_scores: List[jax.Array] = []

        if train_set is not None:
            self._setup_training(train_set)

    # ------------------------------------------------------------------
    def _setup_training(self, ds: BinnedDataset) -> None:
        self.num_data = ds.num_data
        if self.objective is not None:
            self.objective.init(ds.metadata, ds.num_data)
        self.learner = SerialTreeLearner(ds, self.config)
        self.sample_strategy = create_sample_strategy(
            self.config, ds.num_data,
            label=None if ds.metadata.label is None else np.asarray(ds.metadata.label),
            query_boundaries=ds.metadata.query_boundaries)
        K, N = self.num_tree_per_iteration, ds.num_data
        init = jnp.zeros((K, N), dtype=jnp.float32)
        if ds.metadata.init_score is not None:
            s = np.asarray(ds.metadata.init_score, dtype=np.float32)
            init = jnp.asarray(s.reshape(K, N) if s.size == K * N
                               else np.tile(s, (K, 1)))
            self.has_init_score = True
        else:
            self.has_init_score = False
        self.scores = init
        if self.config.is_provide_training_metric:
            self.train_metrics = create_metrics(self.config, ds.metadata, N)
        self._meta = ds.feature_arrays()
        if self.config.boosting == "rf":
            self.shrinkage_rate = 1.0

    def add_valid_set(self, ds: BinnedDataset, name: str) -> None:
        self.valid_sets.append((name, ds))
        self.valid_binned.append(jnp.asarray(ds.binned))
        self.valid_metrics.append(create_metrics(self.config, ds.metadata, ds.num_data))
        K = self.num_tree_per_iteration
        init = jnp.zeros((K, ds.num_data), dtype=jnp.float32)
        if ds.metadata.init_score is not None:
            s = np.asarray(ds.metadata.init_score, dtype=np.float32)
            init = jnp.asarray(s.reshape(K, ds.num_data) if s.size == K * ds.num_data
                               else np.tile(s, (K, 1)))
        self.valid_scores.append(init)
        # replay existing model onto the new valid set
        for i, tree in enumerate(self.models):
            k = i % self.num_tree_per_iteration
            self._add_valid_tree_score(len(self.valid_sets) - 1, tree, k)

    # ------------------------------------------------------------------
    def boosting(self) -> Tuple[jax.Array, jax.Array]:
        """Compute gradients at current scores
        (reference: GBDT::Boosting, gbdt.cpp:222-237)."""
        return self.objective.get_gradients(self.scores)

    def train_one_iter(self, grad: Optional[jax.Array] = None,
                       hess: Optional[jax.Array] = None) -> bool:
        """One boosting iteration. Returns True when training should stop
        (no splittable leaves), mirroring gbdt.cpp:346-454."""
        cfg = self.config
        init_scores = [0.0] * self.num_tree_per_iteration
        if grad is None or hess is None:
            if self.objective is None:
                log.fatal("No objective and no custom gradients provided")
            # boost from average once, before the first gradient computation
            if not self.models and not self.has_init_score \
                    and cfg.boost_from_average:
                for k in range(self.num_tree_per_iteration):
                    init = self.objective.boost_from_score(k)
                    if abs(init) > K_EPSILON:
                        init_scores[k] = init
                        self.scores = self.scores.at[k].add(init)
                        for vi in range(len(self.valid_scores)):
                            self.valid_scores[vi] = self.valid_scores[vi].at[k].add(init)
                        log.info("Start training from score %f", init)
            grad, hess = self.boosting()

        grad, hess, mask = self.sample_strategy.sample(self.iter_, grad, hess)

        should_continue = False
        for k in range(self.num_tree_per_iteration):
            tree = self.learner.train(grad[k], hess[k], row_mask=mask)
            if tree.num_leaves > 1:
                should_continue = True
                if self.objective is not None and self.objective.is_renew_tree_output:
                    self._renew_tree_output(tree, k, mask)
                tree.apply_shrinkage(self.shrinkage_rate)
                self._update_train_score(tree, k)
                for vi in range(len(self.valid_sets)):
                    self._add_valid_tree_score(vi, tree, k)
                if abs(init_scores[k]) > K_EPSILON:
                    self._tree_add_bias(tree, init_scores[k], k)
            else:
                if len(self.models) < self.num_tree_per_iteration:
                    if self.objective is not None and not cfg.boost_from_average \
                            and not self.has_init_score:
                        init_scores[k] = self.objective.boost_from_score(k)
                        self.scores = self.scores.at[k].add(init_scores[k])
                        for vi in range(len(self.valid_scores)):
                            self.valid_scores[vi] = \
                                self.valid_scores[vi].at[k].add(init_scores[k])
                    tree.leaf_value[0] = init_scores[k]
            self.models.append(tree)

        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > self.num_tree_per_iteration:
                del self.models[-self.num_tree_per_iteration:]
            return True
        self.iter_ += 1
        return False

    def _tree_add_bias(self, tree: Tree, bias: float, k: int) -> None:
        """Fold the boost-from-average init into the first tree
        (reference: Tree::AddBias via gbdt.cpp:421)."""
        tree.leaf_value[:tree.num_leaves] += bias
        tree.internal_value = [v + bias for v in tree.internal_value]

    def _update_train_score(self, tree: Tree, k: int) -> None:
        lv = jnp.asarray(tree.leaf_value[:tree.num_leaves], dtype=jnp.float32)
        self.scores = self.scores.at[k].set(_add_tree_score(
            self.scores[k], self.learner.last_perm,
            jnp.asarray(self.learner.last_leaf_begin, dtype=jnp.int32),
            jnp.asarray(self.learner.last_leaf_count, dtype=jnp.int32),
            lv, tree.num_leaves))

    def _add_valid_tree_score(self, vi: int, tree: Tree, k: int) -> None:
        x = self.valid_binned[vi]
        arrs = tree_to_arrays(tree, feature_meta=self._meta, use_inner_feature=True)
        depth = _round_depth(tree.max_depth + 1)
        add = predict_tree_binned(x, arrs, depth)
        self.valid_scores[vi] = self.valid_scores[vi].at[k].add(add)

    def _renew_tree_output(self, tree: Tree, k: int, mask) -> None:
        """L1-family leaf refit by weighted percentile of residuals
        (reference: RenewTreeOutput path in gbdt.cpp:412 +
        regression_objective.hpp percentiles)."""
        perm = np.asarray(jax.device_get(self.learner.last_perm))
        score = np.asarray(jax.device_get(self.scores[k]))
        mask_np = None if mask is None else np.asarray(jax.device_get(mask))
        begins = self.learner.last_leaf_begin
        counts = self.learner.last_leaf_count
        for leaf in range(tree.num_leaves):
            rows = perm[int(begins[leaf]): int(begins[leaf]) + int(counts[leaf])]
            if mask_np is not None:
                rows = rows[mask_np[rows]]
            if len(rows) == 0:
                continue
            tree.leaf_value[leaf] = self.objective.renew_tree_output(rows, score)

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _converted_scores(self, raw: jax.Array) -> np.ndarray:
        out = self.objective.convert_output(raw) if self.objective else raw
        out = np.asarray(jax.device_get(out)).astype(np.float64)
        return out[0] if self.num_tree_per_iteration == 1 else out

    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval("training", self.train_metrics,
                          self._converted_scores(self.scores))

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vi, (name, _) in enumerate(self.valid_sets):
            out.extend(self._eval(name, self.valid_metrics[vi],
                                  self._converted_scores(self.valid_scores[vi])))
        return out

    @staticmethod
    def _eval(data_name, metrics, converted) -> List[Tuple[str, str, float, bool]]:
        res = []
        for m in metrics:
            for mname, val in m.eval(converted):
                res.append((data_name, mname, val, m.greater_is_better))
        return res

    # ------------------------------------------------------------------
    # prediction
    # ------------------------------------------------------------------
    def predict_raw(self, data: np.ndarray, start_iteration: int = 0,
                    num_iteration: int = -1) -> np.ndarray:
        """Raw scores for new data [N, D] -> [N] or [N, K]."""
        data = np.asarray(data, dtype=np.float32)
        x = jnp.asarray(data)
        K = self.num_tree_per_iteration
        N = data.shape[0]
        out = jnp.zeros((K, N), dtype=jnp.float32)
        end = len(self.models) if num_iteration < 0 else min(
            len(self.models), (start_iteration + num_iteration) * K)
        for i in range(start_iteration * K, end):
            tree = self.models[i]
            arrs = tree_to_arrays(tree, use_inner_feature=False)
            depth = _round_depth(tree.max_depth + 1)
            out = out.at[i % K].add(predict_tree_raw(x, arrs, depth))
        res = np.asarray(jax.device_get(out))
        return res[0] if K == 1 else res.T

    def predict(self, data: np.ndarray, raw_score: bool = False,
                start_iteration: int = 0, num_iteration: int = -1) -> np.ndarray:
        raw = self.predict_raw(data, start_iteration, num_iteration)
        if raw_score or self.objective is None:
            return raw
        dev = jnp.asarray(raw.T if raw.ndim == 2 else raw[None, :])
        conv = np.asarray(jax.device_get(self.objective.convert_output(dev)))
        return conv[0] if self.num_tree_per_iteration == 1 else conv.T

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter_

    def rollback_one_iter(self) -> None:
        """(reference: GBDT::RollbackOneIter, gbdt.cpp:456) — drop the last
        iteration's trees and subtract their score contributions."""
        if self.iter_ <= 0:
            return
        for k in range(self.num_tree_per_iteration):
            tree = self.models[-(self.num_tree_per_iteration - k)]
            # subtract contribution by re-adding with negated leaf values
            arrs = tree_to_arrays(tree, feature_meta=self._meta,
                                  use_inner_feature=True)
            arrs = arrs._replace(leaf_value=-arrs.leaf_value)
            depth = _round_depth(tree.max_depth + 1)
            self.scores = self.scores.at[k].add(
                predict_tree_binned(self.learner.x_binned, arrs, depth))
            for vi in range(len(self.valid_sets)):
                self.valid_scores[vi] = self.valid_scores[vi].at[k].add(
                    predict_tree_binned(self.valid_binned[vi], arrs, depth))
        del self.models[-self.num_tree_per_iteration:]
        self.iter_ -= 1
