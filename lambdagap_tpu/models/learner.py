"""Serial (single-device) leaf-wise tree learner.

TPU re-design of the reference's canonical leaf-wise loop
(reference: src/treelearner/serial_tree_learner.cpp:179-245 Train, :288
BeforeTrain, :340-384 histogram-pool juggling, :404-476 FindBestSplits,
:766-920 SplitInner). Like the CUDA learner
(reference: src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:158-260) the
host only orchestrates: every step is a jitted device call with shape-stable
padded sizes (power-of-2 buckets bound recompilation), and the
histogram-subtraction trick keeps per-split work at O(min(|left|, |right|)).

Host state per tree: leaf begin/count bookkeeping and fetched best-split
records (one small D2H per step, like the CUDA learner's single SplitInfo
copy at cuda_single_gpu_tree_learner.cpp:246).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import BinnedDataset
from ..obs import costplane
from ..obs.telemetry import NULL_TELEMETRY
from ..ops.histogram import (full_histogram, leaf_histogram,
                             leaf_histogram_sorted)
from ..ops.partition import split_partition, split_partition_sorted
from ..ops.split import (SplitParams, find_best_split, gather_threshold_split,
                         monotone_split_penalty)
from ..utils import log
from .tree import Tree


import os

# USE_DEBUG analog: heavy self-checks, off unless explicitly requested
_DEBUG_CHECKS = os.environ.get("LAMBDAGAP_DEBUG", "0") not in ("0", "", "false")


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class _HostSplit:
    """A fetched best-split record (host mirror of SplitInfo)."""
    __slots__ = ("gain", "feature", "threshold", "default_left",
                 "left_sum_g", "left_sum_h", "left_count",
                 "right_sum_g", "right_sum_h", "right_count",
                 "left_output", "right_output", "is_categorical", "cat_bitset")

    def __init__(self, res) -> None:
        (self.gain, self.feature, self.threshold, self.default_left,
         self.left_sum_g, self.left_sum_h, self.left_count,
         self.right_sum_g, self.right_sum_h, self.right_count,
         self.left_output, self.right_output, self.is_categorical,
         self.cat_bitset) = [np.asarray(x) for x in res]

    @property
    def gain_f(self) -> float:
        return float(self.gain)


class SerialTreeLearner:
    # phase-span handle; GBDT._setup_training rebinds it to the booster's
    # TrainTelemetry so histogram/split/partition sub-phases attribute
    # inside the enclosing "tree" span (docs/observability.md)
    telemetry = NULL_TELEMETRY
    """Single-device leaf-wise learner over a BinnedDataset."""

    def __init__(self, dataset: BinnedDataset, config: Config) -> None:
        self.dataset = dataset
        self.config = config
        self.num_data = dataset.num_data
        self.num_features = dataset.num_features

        meta = dataset.feature_arrays()
        self.num_bins_arr = jnp.asarray(meta["num_bins"])
        self.default_bins_arr = jnp.asarray(meta["default_bins"])
        self.missing_types_arr = jnp.asarray(meta["missing_types"])
        self.is_categorical_arr = jnp.asarray(meta["is_categorical"])
        self.has_categorical = bool(meta["is_categorical"].any())
        self.meta_host = meta

        # uniform per-feature bin budget (power of two for clean tiling)
        self.max_num_bins = int(meta["num_bins"].max())
        self.B = max(_next_pow2(self.max_num_bins), 8)

        # data_residency (docs/performance.md "Out-of-core"): hbm keeps the
        # binned matrix device-resident; stream keeps it in host shards and
        # uploads leaf windows on demand (bit-identical trees — the stream
        # hooks feed the same kernels the same values in the same order)
        self.residency = self._resolve_residency(config)
        if self.residency == "stream":
            from ..data.stream import as_sharded
            self.sdata = as_sharded(dataset, config)
            self.x_binned = None
            self._perm_host: Optional[np.ndarray] = None
            self._x_sorted_host: Optional[np.ndarray] = None
        else:
            self.sdata = None
            self.x_binned = jnp.asarray(dataset.binned)
        self.perm0 = jnp.arange(self.num_data, dtype=jnp.int32)

        self.params = SplitParams(
            lambda_l1=config.lambda_l1, lambda_l2=config.lambda_l2,
            max_delta_step=config.max_delta_step, path_smooth=config.path_smooth,
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            min_gain_to_split=config.min_gain_to_split,
            cat_smooth=config.cat_smooth, cat_l2=config.cat_l2,
            max_cat_threshold=config.max_cat_threshold,
            max_cat_to_onehot=config.max_cat_to_onehot,
            min_data_per_group=config.min_data_per_group)

        self.rows_per_block = config.tpu_rows_per_block
        self.hist_precision = config.tpu_hist_precision
        self.hist_impl = self._resolve_hist_impl(config.tpu_hist_impl)
        self.layout = self._resolve_layout(config)
        # physical leaf-ordered copies under tree_layout=sorted (rebuilt per
        # tree in train(); None under the gather layout)
        self._x_sorted: Optional[jax.Array] = None
        self._gh_sorted: Optional[jax.Array] = None
        self._col_rng = np.random.RandomState(config.feature_fraction_seed)

        # monotone constraints, mapped original-feature -> used-feature
        # (reference: monotone_constraints.hpp — 'basic', 'intermediate'
        # and 'advanced' methods)
        mono = np.zeros(self.num_features, dtype=np.int32)
        self.mono_method = config.monotone_constraints_method
        if config.monotone_constraints:
            mc = list(config.monotone_constraints)
            for k, j in enumerate(dataset.used_features):
                if j < len(mc):
                    mono[k] = int(mc[j])
            if (mono != 0)[meta["is_categorical"]].any():
                log.fatal("monotone_constraints cannot be set on "
                          "categorical features")
            if self.mono_method not in ("basic", "intermediate", "advanced"):
                log.fatal("unknown monotone_constraints_method %r",
                          self.mono_method)
        self._nb_np = meta["num_bins"].astype(np.int32)
        self.mono_np = mono
        self.mono_arr = jnp.asarray(mono)
        self.mono_on = bool((mono != 0).any())
        self.mono_penalty = float(config.monotone_penalty)

        # CEGB (reference: src/treelearner/cost_effective_gradient_boosting.hpp)
        c = config
        self.cegb_on = c.cegb_tradeoff > 0 and (
            c.cegb_penalty_split > 0
            or len(c.cegb_penalty_feature_coupled) > 0
            or len(c.cegb_penalty_feature_lazy) > 0)
        coupled = np.zeros(self.num_features, dtype=np.float32)
        for k, j in enumerate(dataset.used_features):
            if j < len(c.cegb_penalty_feature_coupled):
                coupled[k] = c.cegb_penalty_feature_coupled[j]
        self._cegb_coupled = jnp.asarray(c.cegb_tradeoff * coupled)
        self._cegb_split_pen = float(c.cegb_tradeoff * c.cegb_penalty_split)
        self._cegb_used = np.zeros(self.num_features, dtype=bool)
        # lazy per-datum on-demand costs (reference: CalculateOndemandCosts
        # :139-164 + the UpdateLeafBestSplits bitset insert :125-135): a
        # candidate (leaf, feature) pays lazy[f] per in-bag in-leaf row
        # that has not yet been routed through an f-split; applying a
        # split marks the leaf's in-bag rows used for that feature.
        self._cegb_lazy = None
        self._cegb_bag_np = None
        if c.cegb_tradeoff > 0 and c.cegb_penalty_feature_lazy:
            lazy = np.zeros(self.num_features, dtype=np.float64)
            for k, j in enumerate(dataset.used_features):
                if j < len(c.cegb_penalty_feature_lazy):
                    lazy[k] = c.cegb_penalty_feature_lazy[j]
            self._cegb_lazy = c.cegb_tradeoff * lazy
            # host-side bit-packed mask, 1 bit per (feature, row) — the
            # same footprint as the reference's feature_used_in_data
            # bitset (cost_effective_gradient_boosting.hpp); this learner
            # orchestrates splits from the host anyway, and an in-place
            # numpy update beats a functional [F, N] device copy per split
            mask_bytes = (self.num_data + 7) // 8
            if self.num_features * mask_bytes > (1 << 25):   # > 32 MiB
                log.warning("cegb_penalty_feature_lazy keeps a "
                            "[features x rows] used-bitset (%.0f MB here)",
                            self.num_features * mask_bytes / 2**20)
            self._cegb_lazy_used = np.zeros(
                (self.num_features, mask_bytes), dtype=np.uint8)

        # original-feature -> used-feature index map
        self._inner_of = {j: k for k, j in enumerate(dataset.used_features)}

        # interaction constraints (reference: src/treelearner/col_sampler.hpp
        # interaction-set filtering): groups of ORIGINAL feature indices
        self.ic_groups = None
        if c.interaction_constraints:
            self.ic_groups = [frozenset(self._inner_of[j] for j in g
                                        if j in self._inner_of)
                              for g in c.interaction_constraints]

        # extra_trees: each scan considers ONE uniform-random threshold per
        # feature (reference: feature_histogram.hpp:192-205 USE_RAND)
        self.extra_on = bool(config.extra_trees)
        self._extra_rng = np.random.RandomState(config.extra_seed)
        self._nb_minus1 = np.maximum(meta["num_bins"].astype(np.int64) - 1, 1)
        self.nb_minus1_arr = jnp.asarray(self._nb_minus1.astype(np.int32))
        # feature_contri: per-feature multiplier on the post-shift gain
        # (reference: feature_histogram.hpp:174 output->gain *= penalty)
        self.contri_arr = None
        if config.feature_contri:
            fc = list(config.feature_contri)
            contri = np.ones(self.num_features, dtype=np.float32)
            for k, j in enumerate(dataset.used_features):
                if j < len(fc):
                    contri[k] = fc[j]
            self.contri_arr = jnp.asarray(contri)

        # forced splits (reference: serial_tree_learner.cpp:624 ForceSplits;
        # the JSON schema of examples/binary_classification/forced_splits.json)
        self.forced_json = None
        if config.forcedsplits_filename:
            import json
            try:
                with open(config.forcedsplits_filename) as fh:
                    fj = json.load(fh)
            except (OSError, ValueError) as e:
                log.fatal("cannot read forcedsplits_filename=%r: %s",
                          config.forcedsplits_filename, e)
            if fj:
                self.forced_json = fj

        # outputs of the last Train call, used for the O(1)-per-row score update
        self.last_perm: Optional[jax.Array] = None
        self.last_leaf_begin: Optional[np.ndarray] = None
        self.last_leaf_count: Optional[np.ndarray] = None

    #: learners whose histogram/partition passes cannot consume the
    #: physically leaf-ordered layout override this to False and fall back
    #: to the gather layout (the host-loop distributed learners, whose
    #: device matrices are shared per-shard views, and the fused
    #: feature-parallel learner, whose winning split column lives on
    #: another shard)
    supports_sorted_layout = True

    #: learners that can train with the binned matrix in host shards
    #: (``data_residency=stream``); the distributed learners keep their
    #: device matrices resident and override this to False
    supports_stream = True

    def _stream_blockers(self, config: Config) -> List[str]:
        """Config combinations this learner's stream mode does not express
        (checked from config only — subclass __init__ state is not built
        yet when this runs). Non-empty → fall back to hbm residency."""
        return []

    def _estimate_residency_bytes(self) -> int:
        """Approximate device bytes the hbm path would pin for the binned
        matrix (the ``stream_hbm_budget_mb`` auto-residency input)."""
        item = 1 if self.max_num_bins <= 256 else 2
        return self.num_data * self.num_features * item

    def _resolve_residency(self, config: Config) -> str:
        """Resolve ``data_residency``: auto streams for pre-sharded
        datasets (and above ``stream_hbm_budget_mb`` when set), stays
        device-resident otherwise; unsupported learners/options fall back
        to hbm loudly, never silently change semantics."""
        from ..data.stream import ShardedBinnedDataset
        mode = config.data_residency
        sharded = isinstance(self.dataset, ShardedBinnedDataset)
        if mode == "hbm":
            return "hbm"
        if not self.supports_stream:
            if mode == "stream" or sharded:
                # LOUD fallback (warning, not info): silently training a
                # requested-stream distributed run device-resident would
                # hide an OOM footprint the caller sized for streaming.
                # Both axes named (R12b): the demoted knob AND the
                # tree_learner value that forced the demotion. Since
                # ISSUE 15 the stream x distributed cell is SUPPORTED for
                # tree_learner=data on the fused 2-D learner (gbdt routes
                # it there before this resolver runs), so this branch
                # fires only for the learners whose programs genuinely
                # keep the matrix resident: the host-loop distributed
                # trio, fused voting/feature, and pre-partitioned
                # multi-process data.
                log.warning("data_residency=stream is not supported with "
                            "tree_learner=%s (%s keeps its device "
                            "matrices resident); falling back to "
                            "data_residency=hbm — tree_learner=data "
                            "streams through the fused 2-D mesh program",
                            config.tree_learner, type(self).__name__)
            return "hbm"
        blocker_knobs = self._stream_blockers(config)
        if blocker_knobs:
            if mode == "stream" or sharded:
                log.warning("data_residency=stream does not support %s; "
                            "training device-resident",
                            ", ".join(blocker_knobs))
            return "hbm"
        if mode == "stream" or sharded:
            return "stream"
        if config.stream_hbm_budget_mb > 0 and (
                self._estimate_residency_bytes()
                > config.stream_hbm_budget_mb << 20):
            log.info("data_residency=auto: estimated %.0f MB residency "
                     "exceeds stream_hbm_budget_mb=%d; streaming",
                     self._estimate_residency_bytes() / 2**20,
                     config.stream_hbm_budget_mb)
            return "stream"
        return "hbm"

    @staticmethod
    def _resolve_hist_impl(impl: str) -> str:
        """Pick the histogram strategy (the analog of TrainingShareStates'
        col/row-wise probe, reference: src/io/train_share_states.cpp — here
        the choice is XLA one-hot contraction vs the Pallas VMEM kernel;
        'auto' = Pallas on TPU, where Mosaic compiles it; one-hot
        elsewhere. An explicit 'pallas' off-TPU runs the kernel in
        interpret mode — exact but slow, the tier-1 CPU parity path)."""
        from ..ops.hist_pallas import HAS_PALLAS
        if impl == "auto":
            return ("pallas" if HAS_PALLAS and jax.default_backend() == "tpu"
                    else "onehot")
        if impl not in ("onehot", "pallas"):
            log.fatal("tpu_hist_impl must be auto/onehot/pallas, got %r", impl)
        if impl == "pallas" and not HAS_PALLAS:
            log.fatal("tpu_hist_impl=pallas but jax.experimental.pallas is "
                      "unavailable in this jax build")
        return impl

    def _resolve_layout(self, config: Config) -> str:
        """Resolve ``tree_layout``: 'auto' picks the physically sorted-leaf
        layout at shapes where gather-issue cost dominates the histogram
        pass (the BENCH_r05 roofline: random row-gathers issue at
        ~30 Mrows/s where the same bytes stream at ~20 GB/s); small data
        keeps the gather layout — the sorted copy's rebuild-per-tree and
        extra residency are not worth it there (docs/performance.md)."""
        layout = config.tree_layout
        if not self.supports_sorted_layout:
            if layout == "sorted":
                log.info("tree_layout=sorted is not supported with "
                         "tree_learner=%s (%s); using the gather layout",
                         config.tree_learner, type(self).__name__)
            return "gather"
        if layout == "auto":
            return "sorted" if self.num_data >= (1 << 20) else "gather"
        return layout

    # ------------------------------------------------------------------
    def _pad_size(self, count: int) -> int:
        return min(max(_next_pow2(max(count, 1)), 256), _next_pow2(self.num_data))

    def _feature_mask(self) -> jax.Array:
        """Per-tree column sampling (reference: src/treelearner/col_sampler.hpp)."""
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return jnp.ones(self.num_features, dtype=bool)
        k = max(1, int(np.ceil(frac * self.num_features)))
        chosen = self._col_rng.choice(self.num_features, k, replace=False)
        mask = np.zeros(self.num_features, dtype=bool)
        mask[chosen] = True
        return jnp.asarray(mask)

    def _node_fmask(self, fmask, path_feats):
        """Per-node feature availability: interaction-constraint filtering +
        by-node column sampling (reference: col_sampler.hpp
        GetByNode / interaction sets)."""
        frac = self.config.feature_fraction_bynode
        if self.ic_groups is None and frac >= 1.0:
            return fmask
        m = np.asarray(jax.device_get(fmask)).copy()
        if self.ic_groups is not None:
            allowed = np.zeros(self.num_features, dtype=bool)
            for g in self.ic_groups:
                if path_feats <= g:
                    allowed[list(g)] = True
            m &= allowed
        if frac < 1.0 and m.any():
            avail = np.nonzero(m)[0]
            k = max(1, int(np.ceil(frac * len(avail))))
            keep = self._col_rng.choice(avail, k, replace=False)
            m[:] = False
            m[keep] = True
        return jnp.asarray(m)

    def _draw_extra_thresholds(self) -> jax.Array:
        """One uniform-random threshold bin per feature from the host-side
        extra_trees stream (reference: feature_histogram.hpp:192-205
        USE_RAND) — shared by every host-loop scan (serial, data-parallel,
        voting) so the draw semantics cannot diverge between learners."""
        return jnp.asarray(
            (self._extra_rng.randint(0, 1 << 30, self.num_features)
             % self._nb_minus1).astype(np.int32))

    def _cegb_lazy_rows(self, perm, begin: int, count: int):
        """IN-BAG rows of a leaf spanning perm[begin:begin+count] (the
        partition routes out-of-bag rows too; the reference's bagged
        data_partition_ holds in-bag indices only, so lazy charging and
        marking must filter)."""
        rows = np.asarray(jax.device_get(perm[begin:begin + count]))
        if self._cegb_bag_np is not None:
            rows = rows[self._cegb_bag_np[rows]]
        return rows

    def _cegb_lazy_pen(self, perm, begin: int, count: int):
        """Per-feature lazy on-demand penalty for a leaf (reference:
        CalculateOndemandCosts — lazy[f] * number of in-bag in-leaf rows
        not yet routed through an f-split)."""
        if self._cegb_lazy is None or count <= 0:
            return None
        rows = self._cegb_lazy_rows(perm, begin, count)
        used = ((self._cegb_lazy_used[:, rows >> 3]
                 >> (rows & 7)) & 1).sum(axis=1)
        return jnp.asarray((self._cegb_lazy
                            * (len(rows) - used)).astype(np.float32))

    def _cegb_lazy_mark(self, perm, begin: int, count: int,
                        feat: int) -> None:
        """Applying a split on ``feat`` marks the leaf's in-bag rows as
        having paid its lazy cost (reference: UpdateLeafBestSplits bitset
        insert)."""
        if self._cegb_lazy is not None and count > 0:
            rows = self._cegb_lazy_rows(perm, begin, count)
            np.bitwise_or.at(self._cegb_lazy_used[feat], rows >> 3,
                             (1 << (rows & 7)).astype(np.uint8))

    def _best(self, hist, pg, ph, pc, parent_output, fmask,
              bounds=None, path_feats=frozenset(), depth=0,
              adv=None, lazy_pen=None) -> _HostSplit:
        cons = None
        if self.mono_on:
            if adv is not None:
                # advanced method: dense per-threshold bound arrays
                cons = (self.mono_arr,) + tuple(jnp.asarray(a) for a in adv)
            else:
                lo, hi = bounds if bounds is not None else (-np.inf, np.inf)
                cons = (self.mono_arr, jnp.float32(lo), jnp.float32(hi))
        pen = None
        if self.cegb_on:
            pen = (self._cegb_split_pen * pc
                   + self._cegb_coupled * jnp.asarray(~self._cegb_used))
            if lazy_pen is not None:
                pen = pen + lazy_pen
        rand_t = None
        if self.extra_on:
            rand_t = self._draw_extra_thresholds()
        contri = self.contri_arr
        if self.mono_on and self.mono_penalty > 0:
            # depth-dependent gain penalty on monotone features (reference:
            # serial_tree_learner.cpp:998 + monotone_constraints.hpp:357)
            mp = monotone_split_penalty(int(depth), self.mono_penalty)
            mono_pen = jnp.where(self.mono_arr != 0, mp, 1.0)
            contri = mono_pen if contri is None else contri * mono_pen
        with self.telemetry.phase("split"):
            res = costplane.observed_call(
                "train.serial.split", find_best_split,
                (hist, pg, ph, pc, parent_output,
                 self.num_bins_arr, self.default_bins_arr,
                 self.missing_types_arr, self.is_categorical_arr,
                 self._node_fmask(fmask, path_feats), self.params),
                dict(has_categorical=self.has_categorical,
                     constraints=cons, gain_penalty=pen,
                     rand_thresholds=rand_t, gain_contri=contri),
                phase="split")
            return _HostSplit(jax.device_get(res))

    # advanced monotone method -------------------------------------------
    # TPU-first re-design of AdvancedLeafConstraints (reference:
    # src/treelearner/monotone_constraints.hpp:858-1176). Instead of the
    # reference's recursive GoUp/GoDownToFindConstrainingLeaves walks
    # building piecewise (threshold, constraint) lists, every leaf carries
    # its bin-space bounding box; the constraining-leaf relation is one
    # vectorized box-adjacency test (m lies across a monotone feature g and
    # overlaps the leaf in every other feature — exactly the set the
    # reference's contiguity pruning converges to), and the per-threshold
    # cumulative extrema (CumulativeFeatureConstraint) become prefix/suffix
    # cummax/cummin over dense [F, B] arrays consumed by the vectorized
    # split scan.

    def _adv_constrainers(self, lo_l, hi_l, los, his):
        """For each monotone feature g: boolean masks over candidate leaves
        that bound this leaf from above/below in g while overlapping it in
        every other feature. Returns {g: (above[M], below[M])}."""
        ov = (los < hi_l[None, :]) & (lo_l[None, :] < his)       # [M, F]
        n_ov = ov.sum(axis=1)
        F = lo_l.shape[0]
        out = {}
        for g in np.nonzero(self.mono_np)[0]:
            others_ok = (n_ov - ov[:, g]) == (F - 1)
            above = (los[:, g] >= hi_l[g]) & others_ok
            below = (his[:, g] <= lo_l[g]) & others_ok
            out[int(g)] = (above, below)
        return out

    def _advanced_bound_arrays(self, leaf, boxes, tree):
        """Dense per-(feature, bin) monotone bounds for ``leaf`` from the
        current tree leaves, already cumulated into the four arrays the
        scan consumes: (min_left, max_left, min_right, max_right), each
        [F, B] f32 where index t carries the bound applicable to the
        left/right child of a split at threshold t."""
        F, B = self.num_features, self.B
        lo_l, hi_l = boxes[leaf]
        live = [m for m in range(tree.num_leaves)
                if m != leaf and m in boxes]
        min_raw = np.full((F, B), -np.inf, np.float32)
        max_raw = np.full((F, B), np.inf, np.float32)
        if live:
            los = np.stack([boxes[m][0] for m in live])
            his = np.stack([boxes[m][1] for m in live])
            outs = np.asarray([tree.leaf_value[m] for m in live], np.float32)
            bins = np.arange(B, dtype=np.int32)
            for g, (above, below) in self._adv_constrainers(
                    lo_l, hi_l, los, his).items():
                sgn = int(self.mono_np[g])
                uppers = above if sgn > 0 else below
                lowers = below if sgn > 0 else above
                pinf = np.float32(np.inf)
                for sel, is_upper in ((uppers, True), (lowers, False)):
                    idx = np.nonzero(sel)[0]
                    # chunk the constrainer axis: the [n, F, B] masks are
                    # transient reductions, so a bounded chunk keeps peak
                    # memory at CH*F*B regardless of leaf count (many-leaf
                    # trees otherwise pay O(leaves*F*B) per refreshed leaf)
                    CH = 64
                    for c0 in range(0, idx.size, CH):
                        ii = idx[c0:c0 + CH]
                        vs = outs[ii]
                        # each constrainer applies over ITS f-range for every
                        # scan feature f != g, and over the full range for
                        # f == g (all of this leaf lies across the boundary)
                        mask = ((bins[None, None, :] >= los[ii][:, :, None])
                                & (bins[None, None, :] < his[ii][:, :, None]))
                        mask[:, g, :] = True
                        if is_upper:
                            v = np.where(mask, vs[:, None, None], pinf)
                            max_raw = np.minimum(max_raw, v.min(axis=0))
                        else:
                            v = np.where(mask, vs[:, None, None], -pinf)
                            min_raw = np.maximum(min_raw, v.max(axis=0))
        # left child at threshold t covers bins [lo, t] -> inclusive prefix;
        # right child covers (t, hi) -> suffix shifted one past t
        min_l = np.maximum.accumulate(min_raw, axis=1)
        max_l = np.minimum.accumulate(max_raw, axis=1)
        sfx_min = np.maximum.accumulate(min_raw[:, ::-1], axis=1)[:, ::-1]
        sfx_max = np.minimum.accumulate(max_raw[:, ::-1], axis=1)[:, ::-1]
        min_r = np.concatenate([sfx_min[:, 1:], sfx_min[:, -1:]], axis=1)
        max_r = np.concatenate([sfx_max[:, 1:], sfx_max[:, -1:]], axis=1)
        return min_l, max_l, min_r, max_r

    def _adv_affected(self, lo_p, hi_p, boxes, leaves):
        """Leaves whose advanced constraints may change when the leaf that
        owned box (lo_p, hi_p) re-splits (its children's outputs are new):
        every leaf the OLD box constrained. The constrainer relation is
        symmetric in adjacency, so this is the union of the above/below
        masks from the shared box test (the reference tracks this as
        leaves_to_update_, monotone_constraints.hpp:560+)."""
        cand = [m for m in leaves if m in boxes]
        if not cand:
            return []
        los = np.stack([boxes[m][0] for m in cand])
        his = np.stack([boxes[m][1] for m in cand])
        hit = np.zeros(len(cand), dtype=bool)
        for above, below in self._adv_constrainers(lo_p, hi_p,
                                                   los, his).values():
            hit |= above | below
        return [m for m, h in zip(cand, hit) if h]

    # histogram hook points (overridden by the distributed learners) --------
    def _root_histogram(self, grad, hess, row_mask):
        if self.residency == "stream":
            return self._root_histogram_stream(grad, hess, row_mask)
        return full_histogram(self.x_binned, grad, hess, row_mask, self.B,
                              self.rows_per_block, self.hist_precision)

    def _root_histogram_stream(self, grad, hess, row_mask):
        """Root histogram over host shards: dataset-order windows pumped
        through the double-buffered H2D ring, accumulated on device in the
        resident scan's exact block order (data/stream.py)."""
        from ..data.stream import stream_windows
        from ..ops.histogram import finish_histogram_acc, histogram_block_acc
        N, F, B = self.num_data, self.num_features, self.B
        block = min(self.rows_per_block, N)
        nch = (N + block - 1) // block
        acc = [jnp.zeros((3, F * B), jnp.float32)]
        dtype = self.sdata.shards[0].dtype

        def fetch(c):
            lo = c * block
            hi = min(lo + block, N)
            buf = np.zeros((block, F), dtype=dtype)
            self.sdata.row_block(lo, hi, out=buf[:hi - lo])
            return (buf,)

        def consume(c, bins_dev):
            acc[0] = histogram_block_acc(
                acc[0], bins_dev, grad, hess, row_mask,
                jnp.int32(c * block), B, self.hist_precision)

        stream_windows(nch, fetch, consume, self.telemetry,
                       self.config.stream_prefetch_depth)
        return finish_histogram_acc(acc[0], F, B)

    def _leaf_histogram(self, perm, grad, hess, begin, count, padded, row_mask):
        if self.residency == "stream":
            return self._leaf_histogram_stream(grad, hess, begin, count,
                                               padded, row_mask)
        if self._x_sorted is not None:
            # sorted layout: the leaf is a contiguous position slice of the
            # physically reordered matrix — consecutive-index read, no
            # row gather (identical rows in identical order, so the
            # histogram is bit-identical to the gather oracle's)
            return costplane.observed_call(
                "train.serial.histogram", leaf_histogram_sorted,
                (self._x_sorted, self._gh_sorted, jnp.int32(begin),
                 jnp.int32(count), padded, self.B, self.rows_per_block,
                 self.hist_precision),
                bucket=padded, phase="histogram")
        return costplane.observed_call(
            "train.serial.histogram", leaf_histogram,
            (self.x_binned, perm, grad, hess, jnp.int32(begin),
             jnp.int32(count), padded, self.B, self.rows_per_block,
             row_mask, self.hist_precision),
            bucket=padded, phase="histogram")

    def _leaf_histogram_stream(self, grad, hess, begin, count, padded,
                               row_mask):
        """One leaf's histogram under stream residency: the host supplies
        the leaf's binned rows (a contiguous payload slice under the
        sorted layout, a shard gather under the gather layout); the
        gradient channels stay device-resident. Same kernels, same padded
        shapes, same values → bit-identical to the resident hooks."""
        from ..ops.histogram import (leaf_histogram_sorted_streamed,
                                     leaf_histogram_streamed)
        N = self.num_data
        if self.layout == "sorted":
            with self.telemetry.phase("h2d_prefetch"):
                buf = np.zeros((padded, self.num_features),
                               dtype=self.sdata.shards[0].dtype)
                hi = min(begin + count, N)
                buf[:hi - begin] = self._x_sorted_host[begin:hi]
                bins = jax.device_put(buf)
            return leaf_histogram_sorted_streamed(
                bins, self._gh_sorted, jnp.int32(begin), jnp.int32(count),
                self.B, self.rows_per_block, self.hist_precision)
        with self.telemetry.phase("h2d_prefetch"):
            idx = np.clip(np.arange(begin, begin + padded), 0, N - 1)
            rows_np = self._perm_host[idx]
            bins = jax.device_put(self.sdata.gather_rows(rows_np))
            rows = jax.device_put(rows_np.astype(np.int32))
        return leaf_histogram_streamed(bins, rows, grad, hess,
                                       jnp.int32(count), self.B,
                                       self.rows_per_block, row_mask,
                                       self.hist_precision)

    def _cat_bitset_real(self, feature_k: int, bitset_bins: np.ndarray) -> np.ndarray:
        """Convert a bin-space bitset to raw-category space for model export.

        The bitset is sized to the largest selected category (the reference
        sizes these dynamically, Common::ConstructBitset /
        src/io/tree.cpp cat_threshold_), so categories >= 256 route
        correctly at predict time."""
        j = self.dataset.used_features[feature_k]
        mapper = self.dataset.mappers[j]
        cats = []
        for b in range(mapper.num_bin):
            if (bitset_bins[b // 32] >> (b % 32)) & 1:
                cat = mapper.bin_2_categorical[b] if b < len(mapper.bin_2_categorical) else -1
                if cat >= 0:
                    cats.append(int(cat))
        words = max(8, (max(cats) + 32) // 32) if cats else 8
        out = np.zeros(words, dtype=np.uint32)
        for cat in cats:
            out[cat // 32] |= np.uint32(1) << np.uint32(cat % 32)
        return out

    def _forced_bin(self, node) -> Optional[tuple]:
        """Map a forced-split JSON node to (inner_feature, threshold_bin).
        Returns None (→ abort forcing) when the feature is unused or the
        threshold maps to no bin (the analog of InnerFeatureIndex +
        BinThreshold in ForceSplits)."""
        try:
            j = int(node["feature"])
            thr = float(node["threshold"])
        except (KeyError, TypeError, ValueError):
            log.warning("Malformed forced-split node %r; aborting forced "
                        "splits", node)
            return None
        k = self._inner_of.get(j)
        if k is None:
            log.warning("Forced split on unused feature %d; aborting forced "
                        "splits", j)
            return None
        mapper = self.dataset.mappers[j]
        if self.meta_host["is_categorical"][k]:
            thr_bin = mapper.categorical_2_bin.get(int(thr))
            if thr_bin is None:
                log.warning("Forced categorical split on unseen category %d "
                            "of feature %d; aborting forced splits",
                            int(thr), j)
                return None
        else:
            thr_bin = mapper._value_to_bin_scalar(thr)
        return k, int(thr_bin)

    def _split_partition_stream(self, perm, begin: int, count: int,
                                feat: int, s, P: int):
        """Stream-residency partition: the host supplies the split
        feature's bin values for the leaf slice (1-2 B/row over the link),
        the device runs the identical stable partition on ``perm`` (and
        the gradient channels under the sorted layout), and the returned
        go_left flags keep the host mirror — permutation or physical
        payload — in lockstep. Returns ``(new_perm, left_count_dev)``."""
        from ..ops.partition import (split_partition_sorted_vals,
                                     split_partition_vals)
        N = self.num_data
        idx = np.clip(np.arange(begin, begin + P), 0, N - 1)
        if self.layout == "sorted":
            with self.telemetry.phase("h2d_prefetch"):
                vals = jax.device_put(self._x_sorted_host[idx, feat])
            perm, self._gh_sorted, left_cnt_dev, gl = \
                split_partition_sorted_vals(
                    vals, self._gh_sorted, perm,
                    jnp.int32(begin), jnp.int32(count),
                    jnp.int32(s.threshold),
                    jnp.asarray(bool(s.default_left)),
                    self.default_bins_arr[feat],
                    self.missing_types_arr[feat],
                    self.num_bins_arr[feat],
                    jnp.asarray(bool(s.is_categorical)),
                    jnp.asarray(s.cat_bitset), P)
            # graftlint: disable=R1 — the go_left fetch IS the stream
            # design: the host must reorder its payload mirror; one small
            # D2H per split on the (already host-orchestrated) learner
            glh = np.asarray(jax.device_get(gl))[:count]
            sl = self._x_sorted_host[begin:begin + count]
            self._x_sorted_host[begin:begin + count] = np.concatenate(
                [sl[glh], sl[~glh]])
        else:
            rows_np = self._perm_host[idx]
            with self.telemetry.phase("h2d_prefetch"):
                vals = jax.device_put(self.sdata.gather_col(feat, rows_np))
            perm, left_cnt_dev, gl = split_partition_vals(
                vals, perm, jnp.int32(begin), jnp.int32(count),
                jnp.int32(s.threshold), jnp.asarray(bool(s.default_left)),
                self.default_bins_arr[feat], self.missing_types_arr[feat],
                self.num_bins_arr[feat], jnp.asarray(bool(s.is_categorical)),
                jnp.asarray(s.cat_bitset), P)
            # graftlint: disable=R1 — see above: the permutation mirror
            # must follow the device partition for the next host gather
            glh = np.asarray(jax.device_get(gl))[:count]
            rs = self._perm_host[begin:begin + count]
            self._perm_host[begin:begin + count] = np.concatenate(
                [rs[glh], rs[~glh]])
        return perm, left_cnt_dev

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              row_mask: Optional[jax.Array] = None) -> Tree:
        """Grow one tree. grad/hess are [N] float32 on device, already
        multiplied by the bagging mask when sampling is active."""
        cfg = self.config
        num_leaves = cfg.num_leaves
        max_depth = cfg.max_depth
        tree = Tree(max_leaves=num_leaves)
        fmask = self._feature_mask()
        if self._cegb_lazy is not None:
            self._cegb_bag_np = (None if row_mask is None
                                 else np.asarray(jax.device_get(row_mask)))

        perm = self.perm0
        if self.layout == "sorted":
            # physical leaf-ordered copies, rebuilt per tree (gradients
            # change every iteration and the permutation restarts at
            # identity); the layout_apply span makes the rebuild cost tile
            # the iteration wall like every other phase
            with self.telemetry.phase("layout_apply"):
                parts = [grad[:, None], hess[:, None]]
                if row_mask is not None:
                    parts.append(row_mask.astype(jnp.float32)[:, None])
                if self.residency == "stream":
                    # the payload copy the host physically reorders lives
                    # in host RAM; only the gradient channels ride HBM
                    self._x_sorted = None
                    self._x_sorted_host = self.sdata.dataset_order_copy()
                else:
                    self._x_sorted = self.x_binned
                self._gh_sorted = jnp.concatenate(parts, axis=1)
        else:
            self._x_sorted = self._gh_sorted = None
        if self.residency == "stream" and self.layout != "sorted":
            # host mirror of the device permutation (kept in lockstep by
            # the partition go_left flags) drives the per-leaf row gathers
            self._perm_host = np.arange(self.num_data, dtype=np.int64)
        leaf_begin = np.zeros(num_leaves, dtype=np.int64)
        leaf_count = np.zeros(num_leaves, dtype=np.int64)
        leaf_count[0] = self.num_data

        # root histogram + totals (BeforeTrain analog)
        with self.telemetry.phase("histogram"):
            hist_root = self._root_histogram(grad, hess, row_mask)
        totals = jnp.sum(hist_root[0], axis=0)   # (g, h, c) — every row hits f0
        root_out = _leaf_output_scalar(totals[0], totals[1], totals[2], self.params)
        hists: Dict[int, jax.Array] = {0: hist_root}
        sums: Dict[int, tuple] = {0: (totals[0], totals[1], totals[2], root_out)}
        bounds: Dict[int, tuple] = {0: (-np.inf, np.inf)}
        paths: Dict[int, frozenset] = {0: frozenset()}
        best: Dict[int, _HostSplit] = {
            0: self._best(hist_root, totals[0], totals[1], totals[2], root_out,
                          fmask, bounds[0], paths[0],
                          lazy_pen=self._cegb_lazy_pen(perm, 0,
                                                       self.num_data))}

        # non-finite gradients poison the histogram count channel; the int
        # conversion must not crash mid-iteration — the guard layer decides
        # what to do with the tree at the iteration boundary
        # (guard_nonfinite policy, docs/robustness.md)
        # graftlint: disable=R1 — root-stat D2H, ONE batched pytree get
        # per tree (value/weight/count ride a single sync instead of three
        # blocking scalar gets); graftir's I2 audit proves every jitted
        # program here is transfer-free, so this explicit boundary read is
        # the whole per-tree host cost on this path
        root_out_h, root_w, root_cnt = (
            float(v) for v in
            jax.device_get((root_out, totals[1], totals[2])))
        tree.leaf_value[0] = root_out_h
        tree.leaf_weight[0] = root_w
        tree.leaf_count[0] = int(root_cnt) if np.isfinite(root_cnt) else 0

        # intermediate monotone method: per-tree node topology + subtree
        # markers (reference: IntermediateLeafConstraints state). The
        # advanced method keeps the intermediate scalar-bound bookkeeping
        # (AdvancedLeafConstraints : IntermediateLeafConstraints) and adds
        # per-leaf bin-space boxes feeding _advanced_bound_arrays.
        adv_on = self.mono_on and self.mono_method == "advanced"
        inter_on = self.mono_on and self.mono_method in ("intermediate",
                                                         "advanced")
        node_parent: List[int] = []
        leaf_mono: Dict[int, bool] = {}
        boxes: Dict[int, tuple] = {}
        if adv_on:
            boxes[0] = (np.zeros(self.num_features, np.int32),
                        self._nb_np.copy())

        def apply_split(leaf: int, s: _HostSplit) -> Optional[int]:
            """Partition + record split ``s`` on ``leaf``, then compute both
            children's histograms and best splits (the loop body shared by
            the forced-splits phase and the gain-driven main loop). Returns
            the right child's leaf id, or None when numerically degenerate."""
            nonlocal perm
            pnode_before = int(tree.leaf_parent[leaf])
            begin, count = int(leaf_begin[leaf]), int(leaf_count[leaf])
            P = self._pad_size(count)
            feat = int(s.feature)
            with self.telemetry.phase("partition"):
                if self.residency == "stream":
                    perm, left_cnt_dev = self._split_partition_stream(
                        perm, begin, count, feat, s, P)
                elif self._x_sorted is not None:
                    # sorted layout: apply the stable partition physically
                    # to the row payload + gradient channels as well
                    (perm, self._x_sorted, self._gh_sorted,
                     left_cnt_dev) = costplane.observed_call(
                        "train.serial.partition", split_partition_sorted,
                        (self._x_sorted, self._gh_sorted, perm,
                         jnp.int32(begin), jnp.int32(count),
                         jnp.int32(feat), jnp.int32(s.threshold),
                         jnp.asarray(bool(s.default_left)),
                         self.default_bins_arr[feat],
                         self.missing_types_arr[feat],
                         self.num_bins_arr[feat],
                         jnp.asarray(bool(s.is_categorical)),
                         jnp.asarray(s.cat_bitset), P),
                        bucket=P, phase="partition")
                else:
                    perm, left_cnt_dev = costplane.observed_call(
                        "train.serial.partition", split_partition,
                        (self.x_binned, perm,
                         jnp.int32(begin), jnp.int32(count),
                         jnp.int32(feat), jnp.int32(s.threshold),
                         jnp.asarray(bool(s.default_left)),
                         self.default_bins_arr[feat],
                         self.missing_types_arr[feat],
                         self.num_bins_arr[feat],
                         jnp.asarray(bool(s.is_categorical)),
                         jnp.asarray(s.cat_bitset), P),
                        bucket=P, phase="partition")
                left_cnt = int(jax.device_get(left_cnt_dev))
            right_cnt = count - left_cnt
            if _DEBUG_CHECKS and row_mask is None:
                # re-check the partition against the histogram's split
                # counts (the analog of SerialTreeLearner::CheckSplit's
                # partition re-walk under USE_DEBUG,
                # reference: serial_tree_learner.cpp:1071+)
                expect = int(round(float(s.left_count)))
                if left_cnt != expect:
                    log.fatal("CheckSplit failed on leaf %d feature %d: "
                              "partition left=%d but histogram left=%d",
                              leaf, feat, left_cnt, expect)
            if left_cnt == 0 or right_cnt == 0:
                # numerically degenerate split; drop this leaf from candidates
                log.warning("Degenerate split on leaf %d (feature %d): "
                            "left=%d right=%d; skipping", leaf, feat, left_cnt, right_cnt)
                return None

            j = self.dataset.used_features[feat]
            mapper = self.dataset.mappers[j]
            cat_real = (self._cat_bitset_real(feat, s.cat_bitset)
                        if s.is_categorical else None)
            mt_code = {"None": 0, "Zero": 1, "NaN": 2}[mapper.missing_type]
            # recorded counts are the IN-BAG histogram counts (the partition
            # routes out-of-bag rows too, but the reference's bagging counts
            # only used indices — and the fused learner records in-bag)
            right_leaf = tree.split(
                leaf, feature=j, feature_inner=feat,
                threshold_bin=int(s.threshold),
                threshold_real=mapper.bin_to_value(int(s.threshold)),
                default_left=bool(s.default_left), missing_type=mt_code,
                gain=s.gain_f,
                left_value=float(s.left_output), right_value=float(s.right_output),
                left_weight=float(s.left_sum_h), right_weight=float(s.right_sum_h),
                left_count=int(round(float(s.left_count))),
                right_count=int(round(float(s.right_count))),
                is_categorical=bool(s.is_categorical),
                cat_bitset=np.asarray(s.cat_bitset),
                cat_bitset_real=cat_real)

            if inter_on:
                # BeforeSplit analog: record the new node's parent and mark
                # the monotone subtree membership of both children
                node_parent.append(pnode_before)
                if int(self.mono_np[feat]) != 0 or leaf_mono.get(leaf, False):
                    leaf_mono[leaf] = True
                    leaf_mono[right_leaf] = True

            leaf_begin[leaf] = begin
            leaf_count[leaf] = left_cnt
            leaf_begin[right_leaf] = begin + left_cnt
            leaf_count[right_leaf] = right_cnt

            parent_hist = hists.pop(leaf)
            l_sums = (jnp.float32(s.left_sum_g), jnp.float32(s.left_sum_h),
                      jnp.float32(s.left_count), jnp.float32(s.left_output))
            r_sums = (jnp.float32(s.right_sum_g), jnp.float32(s.right_sum_h),
                      jnp.float32(s.right_count), jnp.float32(s.right_output))

            # children's monotone bounds. basic: the mid of the two outputs
            # caps the subtree on the constrained side; intermediate: each
            # child is capped by its SIBLING's output — looser, recovered
            # accuracy is the method's point (reference:
            # UpdateConstraintsWithOutputs, monotone_constraints.hpp:545)
            plo, phi = bounds.pop(leaf, (-np.inf, np.inf))
            m = int(self.mono_np[feat])
            llo, lhi, rlo, rhi = plo, phi, plo, phi
            if m != 0:
                lout_f = float(s.left_output)
                rout_f = float(s.right_output)
                if inter_on:
                    if m > 0:
                        lhi = min(phi, rout_f)
                        rlo = max(plo, lout_f)
                    else:
                        llo = max(plo, rout_f)
                        rhi = min(phi, lout_f)
                else:
                    mid = (lout_f + rout_f) / 2.0
                    if m > 0:
                        lhi = min(phi, mid)
                        rlo = max(plo, mid)
                    else:
                        llo = max(plo, mid)
                        rhi = min(phi, mid)
            bounds[leaf] = (llo, lhi)
            bounds[right_leaf] = (rlo, rhi)
            if adv_on:
                # children inherit the parent's bin-space box narrowed on
                # the split feature (categorical splits scatter bins to
                # both sides; keeping the parent box is conservative)
                lo_p, hi_p = boxes.pop(leaf)
                llo_b, lhi_b = lo_p.copy(), hi_p.copy()
                rlo_b, rhi_b = lo_p.copy(), hi_p.copy()
                if not bool(s.is_categorical):
                    lhi_b[feat] = int(s.threshold) + 1
                    rlo_b[feat] = int(s.threshold) + 1
                boxes[leaf] = (llo_b, lhi_b)
                boxes[right_leaf] = (rlo_b, rhi_b)
            child_path = paths.pop(leaf, frozenset()) | {feat}
            paths[leaf] = child_path
            paths[right_leaf] = child_path
            if self.cegb_on:
                self._cegb_used[feat] = True
                # lazy CEGB: the applied split routes the parent's rows
                # through `feat` even when it is the tree's LAST split —
                # the mark must precede the early return or later trees
                # re-charge first-use costs already paid (reference:
                # UpdateLeafBestSplits runs on every applied split)
                self._cegb_lazy_mark(perm, begin, count, feat)

            if tree.num_leaves >= num_leaves:
                return right_leaf  # no more splits: skip children histograms

            # smaller child gets a fresh histogram; sibling by subtraction
            # (reference: serial_tree_learner.cpp:408-476)
            small_is_left = left_cnt <= right_cnt
            sb, sc = (begin, left_cnt) if small_is_left else (begin + left_cnt, right_cnt)
            Ph = self._pad_size(sc)
            with self.telemetry.phase("histogram"):
                hist_small = self._leaf_histogram(perm, grad, hess, sb, sc,
                                                  Ph, row_mask)
                hist_large = parent_hist - hist_small

            small_leaf = leaf if small_is_left else right_leaf
            large_leaf = right_leaf if small_is_left else leaf
            s_sums = l_sums if small_is_left else r_sums
            g_sums = r_sums if small_is_left else l_sums

            hists[small_leaf] = hist_small
            hists[large_leaf] = hist_large
            child_depth = int(tree.leaf_depth[leaf])
            adv_s = (self._advanced_bound_arrays(small_leaf, boxes, tree)
                     if adv_on else None)
            adv_g = (self._advanced_bound_arrays(large_leaf, boxes, tree)
                     if adv_on else None)
            best[small_leaf] = self._best(hist_small, *s_sums, fmask,
                                          bounds[small_leaf],
                                          paths[small_leaf], child_depth,
                                          adv=adv_s,
                                          lazy_pen=self._cegb_lazy_pen(
                                              perm,
                                              int(leaf_begin[small_leaf]),
                                              int(leaf_count[small_leaf])))
            best[large_leaf] = self._best(hist_large, *g_sums, fmask,
                                          bounds[large_leaf],
                                          paths[large_leaf], child_depth,
                                          adv=adv_g,
                                          lazy_pen=self._cegb_lazy_pen(
                                              perm,
                                              int(leaf_begin[large_leaf]),
                                              int(leaf_count[large_leaf])))
            sums[small_leaf] = s_sums
            sums[large_leaf] = g_sums

            if inter_on and not adv_on and leaf_mono.get(leaf, False):
                # tighten bounds of contiguous leaves in monotone ancestors'
                # opposite subtrees, then refresh their cached best splits
                upd = _intermediate_propagate(
                    tree, node_parent, tree.num_leaves - 2, feat,
                    int(s.threshold), s, bounds, self.mono_np,
                    lambda lf_: lf_ in best and np.isfinite(best[lf_].gain_f))
                for ul in set(upd):
                    if ul in hists:
                        best[ul] = self._best(
                            hists[ul], *sums[ul], fmask, bounds[ul],
                            paths[ul], int(tree.leaf_depth[ul]),
                            lazy_pen=self._cegb_lazy_pen(
                                perm, int(leaf_begin[ul]),
                                int(leaf_count[ul])))
            elif adv_on:
                # the split replaced one output with two new ones: refresh
                # the cached best split of every leaf the OLD box
                # constrained (reference: leaves_to_update_ +
                # RecomputeConstraintsIfNeeded)
                lo_pre, hi_pre = boxes[leaf][0].copy(), boxes[leaf][1].copy()
                if not bool(s.is_categorical):
                    hi_pre[feat] = boxes[right_leaf][1][feat]  # parent range
                for ul in self._adv_affected(
                        lo_pre, hi_pre, boxes,
                        [m for m in hists if m not in (leaf, right_leaf)]):
                    best[ul] = self._best(
                        hists[ul], *sums[ul], fmask, bounds[ul], paths[ul],
                        int(tree.leaf_depth[ul]),
                        adv=self._advanced_bound_arrays(ul, boxes, tree),
                        lazy_pen=self._cegb_lazy_pen(
                            perm, int(leaf_begin[ul]),
                            int(leaf_count[ul])))
            return right_leaf

        # ---- forced-splits phase (reference: serial_tree_learner.cpp:624
        # ForceSplits): BFS over the JSON tree, splitting each named node at
        # its fixed (feature, threshold) before any gain-driven search; a
        # non-positive forced gain aborts the remaining forcing
        if self.forced_json is not None:
            from collections import deque
            q = deque([(self.forced_json, 0)])
            while q and tree.num_leaves < num_leaves:
                node, leaf = q.popleft()
                fb = self._forced_bin(node)
                if fb is None:
                    break
                k, thr_bin = fb
                if max_depth > 0 and tree.leaf_depth[leaf] >= max_depth:
                    break
                pg, ph, pc, pout = sums[leaf]
                fbounds = None
                if self.mono_on:
                    lo, hi = bounds.get(leaf, (-np.inf, np.inf))
                    fbounds = (jnp.float32(lo), jnp.float32(hi))
                res = gather_threshold_split(
                    hists[leaf][k], pg, ph, pc, pout, jnp.int32(k),
                    jnp.int32(thr_bin), self.num_bins_arr[k],
                    self.default_bins_arr[k], self.missing_types_arr[k],
                    self.is_categorical_arr[k], self.params, bounds=fbounds)
                s = _HostSplit(jax.device_get(res))
                if not np.isfinite(s.gain_f) or s.gain_f <= 0:
                    log.warning("Forced split on feature %d ignored (gain "
                                "not positive); aborting remaining forced "
                                "splits", int(node["feature"]))
                    break
                best.pop(leaf, None)
                right_leaf = apply_split(leaf, s)
                if right_leaf is None:
                    break
                for key, child in (("left", leaf), ("right", right_leaf)):
                    ch = node.get(key)
                    if (isinstance(ch, dict) and "feature" in ch
                            and "threshold" in ch):
                        q.append((ch, child))

        # ---- gain-driven main loop: pick the leaf with max gain (ArgMax
        # over best_split_per_leaf_, reference: serial_tree_learner.cpp:225)
        while tree.num_leaves < num_leaves:
            cand = [(s.gain_f, leaf) for leaf, s in best.items()
                    if np.isfinite(s.gain_f) and s.gain_f > 0
                    and (max_depth <= 0 or tree.leaf_depth[leaf] < max_depth)]
            if not cand:
                break
            _, leaf = max(cand)
            apply_split(leaf, best.pop(leaf))

        self.last_perm = perm
        self.last_leaf_begin = leaf_begin[:tree.num_leaves].copy()
        self.last_leaf_count = leaf_count[:tree.num_leaves].copy()
        return tree


def _leaf_output_scalar(g, h, c, params: SplitParams):
    from ..ops.split import calculate_leaf_output
    return calculate_leaf_output(g, h, params, c, 0.0)


def _intermediate_propagate(tree: Tree, node_parent: List[int],
                            start_node: int, split_feat: int, thr_bin: int,
                            s, bounds: Dict[int, tuple], mono_np: np.ndarray,
                            splittable) -> List[int]:
    """Intermediate-method constraint propagation: walk up from the new
    split node; in every monotone ancestor's opposite subtree, tighten the
    min/max bound of each leaf contiguous to the new children using the new
    children's outputs (reference: monotone_constraints.hpp:560-850
    IntermediateLeafConstraints::Update / GoUpToFindLeavesToUpdate /
    GoDownToFindLeavesToUpdate / ShouldKeepGoingLeftRight). Mutates
    ``bounds`` in place; returns the leaves whose bounds tightened (their
    cached best splits must be recomputed)."""
    updated: List[int] = []
    up_feats: List[int] = []
    up_thrs: List[int] = []
    up_was_right: List[bool] = []
    lout, rout = float(s.left_output), float(s.right_output)

    def go_down(nidx: int, update_max: bool, use_left: bool,
                use_right: bool) -> None:
        if nidx < 0:
            leaf = ~nidx
            # unsplittable leaves never split again, so their (already
            # clamped) outputs need no tighter bound
            if not splittable(leaf):
                return
            if use_left and use_right:
                lo_v, hi_v = min(lout, rout), max(lout, rout)
            elif use_right:
                lo_v = hi_v = rout
            else:
                lo_v = hi_v = lout
            plo, phi = bounds.get(leaf, (-np.inf, np.inf))
            if update_max:
                new_hi = min(phi, lo_v)
                if new_hi < phi:
                    bounds[leaf] = (plo, new_hi)
                    updated.append(leaf)
            else:
                new_lo = max(plo, hi_v)
                if new_lo > plo:
                    bounds[leaf] = (new_lo, phi)
                    updated.append(leaf)
            return
        inner_f = tree.split_feature_inner[nidx]
        thr = tree.threshold_bin[nidx]
        is_num = not tree.is_categorical[nidx]
        # contiguity pruning against the recorded up-path splits
        keep_left = keep_right = True
        if is_num:
            for f_i, t_i, r_i in zip(up_feats, up_thrs, up_was_right):
                if f_i == inner_f:
                    if thr >= t_i and not r_i:
                        keep_right = False
                    if thr <= t_i and r_i:
                        keep_left = False
        # same-feature splits below decide which new leaf stays contiguous
        use_l_for_right = use_r_for_left = True
        if is_num and inner_f == split_feat:
            if thr >= thr_bin:
                use_l_for_right = False
            if thr <= thr_bin:
                use_r_for_left = False
        if keep_left:
            go_down(tree.left_child[nidx], update_max,
                    use_left, use_right and use_r_for_left)
        if keep_right:
            go_down(tree.right_child[nidx], update_max,
                    use_left and use_l_for_right, use_right)

    node = start_node
    while True:
        parent = node_parent[node] if 0 <= node < len(node_parent) else -1
        if parent < 0:
            break
        inner_f = tree.split_feature_inner[parent]
        is_right = tree.right_child[parent] == node
        is_num_parent = not tree.is_categorical[parent]
        # only branches contiguous to the original leaf can need updates:
        # for a feature already crossed in the same direction going up,
        # the opposite child cannot be contiguous
        opposite_ok = is_num_parent and all(
            not (f_i == inner_f and r_i == is_right)
            for f_i, r_i in zip(up_feats, up_was_right))
        if opposite_ok:
            if mono_np[inner_f] != 0:
                left_is_curr = tree.left_child[parent] == node
                opposite = (tree.right_child[parent] if left_is_curr
                            else tree.left_child[parent])
                update_max = (left_is_curr if mono_np[inner_f] < 0
                              else not left_is_curr)
                go_down(opposite, update_max, True, True)
            up_was_right.append(is_right)
            up_thrs.append(tree.threshold_bin[parent])
            up_feats.append(inner_f)
        node = parent
    return updated
