"""Piece-wise linear leaves: the trained model class glue.

This module makes ``linear_tree=true`` a first-class TPU model class
(arXiv:1802.05640; ROADMAP item 1): it owns the per-tree fit orchestration
— path-feature extraction on the host tree skeleton, the MXU-batched
moment accumulation + ONE regularized solve per tree (ops/linear.py), and
the constant-leaf fallback policy — and is the single entry point BOTH
learners call (``GBDT._fit_linear_tree``), so serial and fused linear
trees are bit-identical by construction.

The reference's per-leaf host loop (linear_tree_learner.cpp
CalculateLinear) gathered each leaf's raw rows and solved leaf by leaf;
here the leaf dimension is batched: one device pass over the raw matrix
builds every leaf's ``X^T H X`` / ``X^T g`` simultaneously, and one
``[L, P, P]`` stacked solve produces every coefficient vector. The only
per-tree host work left is walking the (already host-resident) tree
skeleton for path features and writing the payload back.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linear import (accumulate_leaf_moments, leaf_feature_width,
                          moment_chunk_rows, solve_linear_leaves)
from ..utils import log
from .tree import Tree


def numeric_feature_mask(ds) -> np.ndarray:
    """True for features a linear leaf may use (numeric, non-categorical;
    reference: CalculateLinear skips categorical splits on the path)."""
    from ..data.binning import BIN_CATEGORICAL
    numeric = np.ones(ds.num_total_features, dtype=bool)
    for j, m in enumerate(ds.mappers):
        if m.bin_type == BIN_CATEGORICAL:
            numeric[j] = False
    return numeric


def leaf_path_features(tree: Tree, numeric_mask: np.ndarray,
                       num_leaves_pad: int, width: int) -> np.ndarray:
    """[L_pad+1, FL] int32 table of each leaf's sorted numeric path
    features, ``-1`` on padding slots; row L_pad is the all-padding dump
    row the accumulation routes masked rows to."""
    tbl = np.full((num_leaves_pad + 1, width), -1, np.int32)
    if tree.num_internal == 0:
        return tbl
    path_feats = [[] for _ in range(tree.num_leaves)]

    def walk(node, feats):
        if node < 0:
            path_feats[~node] = feats
            return
        f = tree.split_feature[node]
        nxt = feats if (tree.is_categorical[node]
                        or not numeric_mask[f]) else feats + [f]
        walk(tree.left_child[node], nxt)
        walk(tree.right_child[node], nxt)

    walk(0, [])
    for leaf in range(tree.num_leaves):
        feats = sorted(set(path_feats[leaf]))
        tbl[leaf, :len(feats)] = feats
    return tbl


def fit_linear_leaves_batched(tree: Tree, X_dev: jax.Array,
                              leaf_idx_dev: jax.Array,
                              grad: jax.Array, hess: jax.Array,
                              linear_lambda: float,
                              numeric_mask: np.ndarray,
                              num_leaves_cap: int) -> None:
    """Fit every leaf's linear model in one accumulation + one solve.

    Mutates ``tree`` in place like the host reference did: sets
    ``is_linear`` and the per-leaf ``leaf_features``/``leaf_coeff``/
    ``leaf_const`` payload, leaving ineligible leaves (no numeric path
    features, too few non-NaN rows, singular/non-finite system) on their
    constant output. ``num_leaves_cap`` (config num_leaves) fixes the
    compiled accumulation shape so growing trees never retrace it.
    """
    L = tree.num_leaves
    Lc = max(int(num_leaves_cap), L)
    FL = leaf_feature_width(int(numeric_mask.sum()), Lc)
    tbl = leaf_path_features(tree, numeric_mask, Lc, FL)
    nfeat = (tbl[:Lc] >= 0).sum(axis=1).astype(np.int64)

    tree.is_linear = True
    tree.leaf_features = [[] for _ in range(L)]
    tree.leaf_coeff = [np.zeros(0, np.float64) for _ in range(L)]
    tree.leaf_const = np.asarray(tree.leaf_value[:L], np.float64).copy()
    if not nfeat[:L].any():
        return

    chunk = moment_chunk_rows(Lc, FL)
    XtHX_d, Xtg_d, cnt_d = accumulate_leaf_moments(
        X_dev, leaf_idx_dev, grad, hess, jnp.asarray(tbl),
        num_leaves=Lc, chunk=chunk)
    # graftlint: disable=R1 — the one O(leaves * P^2) moment fetch per
    # tree: the row-dimension work already ran on device; the tiny stacked
    # solve is float64 host math by payload contract (serialized coeffs),
    # and all three operands ride ONE batched transfer
    XtHX, Xtg, cnt = (np.asarray(a) for a in jax.device_get(
        (XtHX_d, Xtg_d, cnt_d)))
    sol, ok = solve_linear_leaves(XtHX[:Lc], Xtg[:Lc], cnt[:Lc],
                                  nfeat, linear_lambda)
    for leaf in range(L):
        if not ok[leaf]:
            continue
        nf = int(nfeat[leaf])
        tree.leaf_features[leaf] = [int(f) for f in tbl[leaf, :nf]]
        tree.leaf_coeff[leaf] = sol[leaf, :nf].copy()
        tree.leaf_const[leaf] = float(sol[leaf, FL])


def resolve_linear_config(cfg, ds=None) -> None:
    """Demote unsupported combos up front, loudly (called from learner
    dispatch before any program compiles)."""
    if not cfg.linear_tree:
        return
    if cfg.use_quantized_grad:
        log.warning("use_quantized_grad is not applied with linear_tree "
                    "(the leaf solve needs full-precision gradients); "
                    "training runs in full precision")
        cfg.use_quantized_grad = False
    if cfg.data_residency == "stream":
        log.warning("linear_tree does not support data_residency=stream "
                    "(the leaf solve reads the resident raw matrix); "
                    "falling back to hbm residency")
    # auto must not silently resolve to stream either: the raw matrix the
    # leaf solve reads is resident by linear_tree's retention contract
    cfg.data_residency = "hbm"
