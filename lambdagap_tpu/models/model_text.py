"""Text model serialization, compatible with the reference's format.

(reference: src/boosting/gbdt_model_text.cpp:311 SaveModelToString with
per-tree ``Tree=N`` blocks from Tree::ToString (src/io/tree.cpp:339),
LoadModelFromString; decision_type bit encoding from
include/LightGBM/tree.h:20-21,274-281.)

A model saved here loads in the reference's LightGBM and vice versa for the
shared feature set (numerical+categorical splits, missing handling).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from .tree import Tree

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
MODEL_VERSION = "v4"


def _fmt(v: float) -> str:
    """Round-trip float formatting (reference uses %.17g via
    ArrayToString<true>; repr() is the shortest round-trip equivalent)."""
    return repr(float(v))


def _arr_str(vals, fmt=str) -> str:
    return " ".join(fmt(v) for v in vals)


def _decision_type(tree: Tree, i: int) -> int:
    dt = 0
    if tree.is_categorical[i]:
        dt |= K_CATEGORICAL_MASK
    if tree.default_left[i]:
        dt |= K_DEFAULT_LEFT_MASK
    dt |= (tree.missing_type[i] & 3) << 2
    return dt


def tree_to_string(tree: Tree) -> str:
    n = tree.num_internal
    L = tree.num_leaves
    lines = [f"num_leaves={L}"]

    # categorical bookkeeping: threshold of a categorical node indexes into
    # cat_boundaries/cat_threshold (reference: tree.cpp ToString num_cat path)
    cat_nodes = [i for i in range(n) if tree.is_categorical[i]]
    num_cat = len(cat_nodes)
    lines.append(f"num_cat={num_cat}")

    thresholds: List[float] = []
    cat_boundaries = [0]
    cat_threshold: List[int] = []
    cat_idx = 0
    for i in range(n):
        if tree.is_categorical[i]:
            bits = np.trim_zeros(np.asarray(tree.cat_bitset_real[i], dtype=np.uint32),
                                 "b")
            if len(bits) == 0:
                bits = np.zeros(1, dtype=np.uint32)
            cat_threshold.extend(int(b) for b in bits)
            cat_boundaries.append(len(cat_threshold))
            thresholds.append(float(cat_idx))
            cat_idx += 1
        else:
            thresholds.append(tree.threshold_real[i])

    if n > 0:
        lines.append("split_feature=" + _arr_str(tree.split_feature[:n]))
        lines.append("split_gain=" + _arr_str(tree.split_gain[:n], _fmt))
        lines.append("threshold=" + _arr_str(thresholds, _fmt))
        lines.append("decision_type="
                     + _arr_str([_decision_type(tree, i) for i in range(n)]))
        lines.append("left_child=" + _arr_str(tree.left_child[:n]))
        lines.append("right_child=" + _arr_str(tree.right_child[:n]))
    else:
        for k in ("split_feature", "split_gain", "threshold", "decision_type",
                  "left_child", "right_child"):
            lines.append(f"{k}=")
    lines.append("leaf_value=" + _arr_str(tree.leaf_value[:L], _fmt))
    lines.append("leaf_weight=" + _arr_str(tree.leaf_weight[:L], _fmt))
    lines.append("leaf_count=" + _arr_str(int(c) for c in tree.leaf_count[:L]))
    if n > 0:
        lines.append("internal_value=" + _arr_str(tree.internal_value, _fmt))
        lines.append("internal_weight=" + _arr_str(tree.internal_weight, _fmt))
        lines.append("internal_count=" + _arr_str(tree.internal_count))
    else:
        lines.extend(["internal_value=", "internal_weight=", "internal_count="])
    if num_cat > 0:
        lines.append("cat_boundaries=" + _arr_str(cat_boundaries))
        lines.append("cat_threshold=" + _arr_str(cat_threshold))
    if getattr(tree, "is_linear", False):
        # (reference: tree.cpp ToString linear-tree block)
        lines.append("is_linear=1")
        lines.append("leaf_const=" + _arr_str(tree.leaf_const[:L], _fmt))
        nfs = [len(tree.leaf_features[i]) for i in range(L)]
        lines.append("num_features=" + _arr_str(nfs))
        flat_f = [f for i in range(L) for f in tree.leaf_features[i]]
        flat_c = [c for i in range(L) for c in tree.leaf_coeff[i]]
        lines.append("leaf_features=" + _arr_str(flat_f))
        lines.append("leaf_coeff=" + _arr_str(flat_c, _fmt))
    else:
        lines.append("is_linear=0")
    lines.append("shrinkage=" + _fmt(tree.shrinkage))
    return "\n".join(lines) + "\n"


def save_model_to_string(booster, start_iteration: int = 0,
                         num_iteration: int = -1,
                         importance_type: int = 0) -> str:
    """(reference: gbdt_model_text.cpp:311 SaveModelToString)"""
    cfg = booster.config
    sub_model = "tree"
    num_class = booster.num_class if booster.num_class > 1 else 1
    K = booster.num_tree_per_iteration
    feature_names = list(booster.feature_names)
    max_feature_idx = len(feature_names) - 1

    total_iters = len(booster.models) // max(K, 1)
    start_iteration = max(0, min(start_iteration, total_iters))
    num_used = len(booster.models)
    if num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * K, num_used)
    start_model = start_iteration * K

    out = [sub_model,
           f"version={MODEL_VERSION}",
           f"num_class={num_class}",
           f"num_tree_per_iteration={K}",
           "label_index=0",
           f"max_feature_idx={max_feature_idx}",
           f"objective={booster.objective_string()}"]
    if getattr(booster, "average_output", False):
        out.append("average_output")
    out.append("feature_names=" + " ".join(feature_names))
    out.append("feature_infos=" + " ".join(booster.feature_infos()))

    models = booster.host_models
    tree_strs = []
    for idx, i in enumerate(range(start_model, num_used)):
        tree_strs.append(f"Tree={idx}\n" + tree_to_string(models[i]) + "\n")
    out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
    body = "\n".join(out) + "\n\n" + "".join(tree_strs) + "end of trees\n"

    imp = feature_importance(booster, importance_type)
    pairs = [(int(v), feature_names[i]) for i, v in enumerate(imp) if v > 0]
    pairs.sort(key=lambda p: -p[0])
    body += "\nfeature_importances:\n"
    for v, name in pairs:
        body += f"{name}={v}\n"
    body += "\nparameters:\n"
    for key, val in sorted(cfg.to_dict().items()):
        if isinstance(val, list):
            val = ",".join(str(x) for x in val)
        body += f"[{key}: {val}]\n"
    body += "end of parameters\n"
    return body


def feature_importance(booster, importance_type: int = 0,
                       start: int = 0, end: int = -1) -> np.ndarray:
    """0 = split counts, 1 = total gains, over trees [start, end)
    (reference: GBDT::FeatureImportance, gbdt.cpp)."""
    n = len(booster.feature_names)
    imp = np.zeros(n, dtype=np.float64)
    models = booster.host_models
    if end < 0:
        end = len(models)
    for tree in models[start:end]:
        for i in range(tree.num_internal):
            f = tree.split_feature[i]
            if importance_type == 0:
                imp[f] += 1
            else:
                imp[f] += tree.split_gain[i]
    return imp


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def _parse_kv_block(text: str) -> Dict[str, str]:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if "=" in line:
            k, v = line.split("=", 1)
            out[k] = v
    return out


def tree_from_string(block: str) -> Tree:
    kv = _parse_kv_block(block)
    L = int(kv["num_leaves"])
    num_cat = int(kv.get("num_cat", "0"))
    tree = Tree(max_leaves=max(L, 1))
    tree.num_leaves = L
    tree.shrinkage = float(kv.get("shrinkage", "1"))

    def ints(key):
        s = kv.get(key, "")
        return [int(float(x)) for x in s.split()] if s.strip() else []

    def floats(key):
        s = kv.get(key, "")
        return [float(x) for x in s.split()] if s.strip() else []

    n = L - 1
    tree.split_feature = ints("split_feature")
    tree.split_feature_inner = list(tree.split_feature)
    tree.split_gain = floats("split_gain")
    thresholds = floats("threshold")
    dts = ints("decision_type")
    tree.left_child = ints("left_child")
    tree.right_child = ints("right_child")
    leaf_value = floats("leaf_value")
    tree.leaf_value[:L] = leaf_value[:L]
    lw = floats("leaf_weight")
    if lw:
        tree.leaf_weight[:L] = lw[:L]
    lc = ints("leaf_count")
    if lc:
        tree.leaf_count[:L] = lc[:L]
    tree.internal_value = floats("internal_value")
    tree.internal_weight = floats("internal_weight")
    tree.internal_count = ints("internal_count")
    cat_boundaries = ints("cat_boundaries")
    cat_threshold = [np.uint32(x) for x in ints("cat_threshold")]

    tree.threshold_real = []
    tree.threshold_bin = [0] * n
    tree.is_categorical = []
    tree.default_left = []
    tree.missing_type = []
    tree.cat_bitset = []
    tree.cat_bitset_real = []
    for i in range(n):
        dt = dts[i] if i < len(dts) else 0
        is_cat = bool(dt & K_CATEGORICAL_MASK)
        tree.is_categorical.append(is_cat)
        tree.default_left.append(bool(dt & K_DEFAULT_LEFT_MASK))
        tree.missing_type.append((dt >> 2) & 3)
        if is_cat and cat_boundaries:
            ci = int(thresholds[i])
            lo, hi = cat_boundaries[ci], cat_boundaries[ci + 1]
            # keep the full variable-length segment: reference bitsets can
            # span arbitrarily many words (tree.cpp cat_threshold_)
            seg = cat_threshold[lo:hi]
            bits = np.zeros(max(8, len(seg)), dtype=np.uint32)
            bits[:len(seg)] = seg
            tree.cat_bitset_real.append(bits)
            tree.cat_bitset.append(np.zeros(8, dtype=np.uint32))
            tree.threshold_real.append(0.0)
        else:
            tree.cat_bitset_real.append(np.zeros(8, dtype=np.uint32))
            tree.cat_bitset.append(np.zeros(8, dtype=np.uint32))
            tree.threshold_real.append(thresholds[i] if i < len(thresholds) else 0.0)

    if kv.get("is_linear", "0").strip() == "1":
        tree.is_linear = True
        tree.leaf_const = np.asarray(floats("leaf_const"), np.float64)
        nfs = ints("num_features")
        flat_f = ints("leaf_features")
        flat_c = floats("leaf_coeff")
        tree.leaf_features = []
        tree.leaf_coeff = []
        off = 0
        for cnt in nfs:
            tree.leaf_features.append(flat_f[off:off + cnt])
            tree.leaf_coeff.append(np.asarray(flat_c[off:off + cnt],
                                              np.float64))
            off += cnt

    # recompute leaf depths/parents from children arrays
    tree.leaf_parent[:] = -1
    depth = np.zeros(max(n, 1), dtype=np.int32)
    for i in range(n):
        for child in (tree.left_child[i], tree.right_child[i]):
            if child >= 0:
                depth[child] = depth[i] + 1
            else:
                tree.leaf_parent[~child] = i
                tree.leaf_depth[~child] = depth[i] + 1
    return tree


def read_model_source(source) -> str:
    """Model text from a filesystem path OR an already-in-memory model
    string (the serve hot-swap path accepts either). A multi-line string is
    always treated as model text; a single-line string must name a readable
    file."""
    import os
    s = str(source)
    if "\n" in s:
        return s
    if os.path.exists(s):
        with open(s) as f:
            return f.read()
    log.fatal("model source %r is neither a readable file nor model text", s)


def load_model_from_string(text: str):
    """Parse a saved model into (header dict, [Tree])."""
    if "end of trees" not in text:
        log.fatal("Model format error: missing 'end of trees'")
    head_and_trees = text.split("end of trees")[0]
    parts = head_and_trees.split("Tree=")
    header = _parse_kv_block(parts[0])
    if any(line.strip() == "average_output" for line in parts[0].splitlines()):
        header["average_output"] = "1"
    trees = []
    for blk in parts[1:]:
        body = blk.split("\n", 1)[1] if "\n" in blk else ""
        trees.append(tree_from_string(body))
    return header, trees


# ---------------------------------------------------------------------------
# JSON dump (reference: gbdt_model_text.cpp DumpModel + tree.cpp Tree::ToJSON)
# ---------------------------------------------------------------------------

_MT_NAMES = {0: "None", 1: "Zero", 2: "NaN"}


def _node_to_dict(tree: Tree, node: int) -> Dict:
    if node < 0:
        leaf = ~node
        return {
            "leaf_index": leaf,
            "leaf_value": float(tree.leaf_value[leaf]),
            "leaf_weight": float(tree.leaf_weight[leaf]),
            "leaf_count": int(tree.leaf_count[leaf]),
        }
    if tree.is_categorical[node]:
        bits = np.asarray(tree.cat_bitset_real[node], dtype=np.uint32)
        cats = [str(32 * w + b) for w in range(len(bits))
                for b in range(32) if (bits[w] >> b) & 1]
        threshold = "||".join(cats)
        decision_type = "=="
    else:
        threshold = tree.threshold_real[node]
        decision_type = "<="
    return {
        "split_index": node,
        "split_feature": tree.split_feature[node],
        "split_gain": float(tree.split_gain[node]),
        "threshold": threshold,
        "decision_type": decision_type,
        "default_left": bool(tree.default_left[node]),
        "missing_type": _MT_NAMES.get(tree.missing_type[node], "None"),
        "internal_value": float(tree.internal_value[node]),
        "internal_weight": float(tree.internal_weight[node]),
        "internal_count": int(tree.internal_count[node]),
        "left_child": _node_to_dict(tree, tree.left_child[node]),
        "right_child": _node_to_dict(tree, tree.right_child[node]),
    }


def dump_model(booster, start_iteration: int = 0,
               num_iteration: int = -1) -> Dict:
    """Model as a JSON-serializable dict
    (reference: GBDT::DumpModel, src/boosting/gbdt_model_text.cpp;
    Python Booster.dump_model)."""
    K = booster.num_tree_per_iteration
    feature_names = list(booster.feature_names)
    total_iters = len(booster.models) // max(K, 1)
    start_iteration = max(0, min(start_iteration, total_iters))
    num_used = len(booster.models)
    if num_iteration > 0:
        num_used = min((start_iteration + num_iteration) * K, num_used)
    trees = []
    models = booster.host_models
    for i in range(start_iteration * K, num_used):
        t = models[i]
        trees.append({
            "tree_index": i - start_iteration * K,
            "num_leaves": t.num_leaves,
            "num_cat": sum(t.is_categorical[:t.num_internal]),
            "shrinkage": float(t.shrinkage),
            "tree_structure": _node_to_dict(
                t, 0 if t.num_internal > 0 else ~0),
        })
    imp = feature_importance(booster, start=start_iteration * K, end=num_used)
    return {
        "name": "tree",
        "version": MODEL_VERSION,
        "num_class": booster.num_class if booster.num_class > 1 else 1,
        "num_tree_per_iteration": K,
        "label_index": 0,
        "max_feature_idx": len(feature_names) - 1,
        "objective": booster.objective_string(),
        "average_output": bool(getattr(booster, "average_output", False)),
        "feature_names": feature_names,
        "feature_infos": booster.feature_infos(),
        "tree_info": trees,
        "feature_importances": {
            feature_names[i]: int(v) for i, v in enumerate(imp) if v > 0},
    }
