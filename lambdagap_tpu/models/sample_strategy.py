"""Row sampling strategies: bagging and GOSS.

(reference: src/boosting/sample_strategy.{h,cpp} factory,
src/boosting/bagging.hpp:14, src/boosting/goss.hpp:18.)

TPU design: instead of compacting a ``bag_data_indices`` array (the
reference's subset path), sampling produces a boolean in-bag mask [N] on
device. Out-of-bag rows keep flowing through the partition with zeroed
grad/hess and are excluded from histogram counts via the mask.

Measured negative result (round 2, 500k rows x 255 leaves on one chip):
compacting the permutation to in-bag rows and assigning out-of-bag leaves
with one end-of-tree traversal was 2.4x SLOWER (570ms vs 242ms/iter at
bagging_fraction=0.3) — the traversal costs N x max_depth while keeping
OOB rows in the partition costs N x avg_depth, and leaf-wise max depth is
far above the average. Don't re-attempt without changing that calculus.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..utils import log


class SampleStrategy:
    """Base: no sampling."""

    def __init__(self, config: Config, num_data: int) -> None:
        self.config = config
        self.num_data = num_data

    @property
    def is_hessian_change(self) -> bool:
        return False

    def sample(self, iter_: int, grad: jax.Array, hess: jax.Array
               ) -> Tuple[jax.Array, jax.Array, Optional[jax.Array]]:
        """Returns (grad, hess, mask). mask=None means all rows in-bag."""
        return grad, hess, None

    # -- snapshot sidecar (guard/snapshot.py): RNG state capture ---------
    def get_state(self) -> dict:
        """JSON-safe RNG state for crash-safe snapshots; subclasses with
        randomness override. Restoring this state makes a resumed run draw
        the exact sampling sequence of the uninterrupted one."""
        return {"type": "none"}

    def set_state(self, state: dict) -> None:
        pass


class BaggingStrategy(SampleStrategy):
    """(reference: src/boosting/bagging.hpp — per-``bagging_freq`` Bernoulli
    subsample, with optional positive/negative class fractions)."""

    def __init__(self, config: Config, num_data: int,
                 label: Optional[np.ndarray] = None,
                 query_boundaries: Optional[np.ndarray] = None) -> None:
        super().__init__(config, num_data)
        self.key = jax.random.PRNGKey(config.bagging_seed)
        self.cur_mask: Optional[jax.Array] = None
        self.label = label
        self.query_boundaries = query_boundaries
        self.balanced = (config.pos_bagging_fraction < 1.0
                         or config.neg_bagging_fraction < 1.0)
        if self.balanced and label is not None:
            self.is_pos = jnp.asarray(label > 0)

    @property
    def enabled(self) -> bool:
        c = self.config
        return c.bagging_freq > 0 and (c.bagging_fraction < 1.0 or self.balanced)

    def _make_mask(self, sub) -> jax.Array:
        """The in-bag mask for one resample subkey. Factored out so a
        snapshot restore can regenerate the live mask from the recorded
        subkey instead of serializing [N] booleans."""
        c = self.config
        if c.bagging_by_query and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            qmask = jax.random.uniform(sub, (nq,)) < c.bagging_fraction
            qb = jnp.asarray(self.query_boundaries)
            qid = jnp.searchsorted(
                qb, jnp.arange(self.num_data, dtype=jnp.int32),
                side="right") - 1
            return qmask[qid]
        if self.balanced:
            u = jax.random.uniform(sub, (self.num_data,))
            frac = jnp.where(self.is_pos, c.pos_bagging_fraction,
                             c.neg_bagging_fraction)
            return u < frac
        u = jax.random.uniform(sub, (self.num_data,))
        return u < c.bagging_fraction

    def sample(self, iter_, grad, hess):
        c = self.config
        if not self.enabled:
            return grad, hess, None
        if iter_ % c.bagging_freq == 0:
            self.key, sub = jax.random.split(self.key)
            self._mask_key = sub
            self.cur_mask = self._make_mask(sub)
        m = self.cur_mask
        mf = m.astype(grad.dtype)
        return grad * mf, hess * mf, m

    def get_state(self) -> dict:
        st = {"type": "bagging",
              "key": np.asarray(self.key).tolist()}
        mk = getattr(self, "_mask_key", None)
        if mk is not None:
            st["mask_key"] = np.asarray(mk).tolist()
        return st

    def set_state(self, state: dict) -> None:
        if state.get("type") != "bagging":
            return
        self.key = jnp.asarray(np.asarray(state["key"], np.uint32))
        if state.get("mask_key") is not None:
            self._mask_key = jnp.asarray(
                np.asarray(state["mask_key"], np.uint32))
            # the live mask matters when resuming mid-window
            # (bagging_freq > 1): regenerate it from the recorded subkey
            self.cur_mask = self._make_mask(self._mask_key)


class GossStrategy(SampleStrategy):
    """Gradient-based one-side sampling
    (reference: src/boosting/goss.hpp — skip the first 1/learning_rate
    iterations, keep the ``top_rate`` fraction by |g*h|, sample ``other_rate``
    of the rest and amplify by (1-top_rate)/other_rate)."""

    def __init__(self, config: Config, num_data: int) -> None:
        super().__init__(config, num_data)
        self.key = jax.random.PRNGKey(config.bagging_seed)

    @property
    def is_hessian_change(self) -> bool:
        return True

    def sample(self, iter_, grad, hess):
        c = self.config
        # (reference: goss.hpp:33 — 1/learning_rate warmup iterations)
        if iter_ < max(1, int(1.0 / c.learning_rate)):
            return grad, hess, None
        self.key, sub = jax.random.split(self.key)
        return _goss_mask(grad, hess, sub, c.top_rate, c.other_rate)

    def get_state(self) -> dict:
        return {"type": "goss", "key": np.asarray(self.key).tolist()}

    def set_state(self, state: dict) -> None:
        if state.get("type") == "goss":
            self.key = jnp.asarray(np.asarray(state["key"], np.uint32))


@functools.partial(jax.jit, static_argnames=("top_rate", "other_rate"))
def _goss_mask(grad, hess, key, top_rate: float, other_rate: float):
    N = grad.shape[-1]
    score = jnp.abs(grad * hess)
    if score.ndim > 1:
        score = jnp.sum(score, axis=0)     # multiclass: combine classes
    top_k = max(1, int(top_rate * N))
    kth = -jnp.sort(-score)[top_k - 1]
    is_top = score >= kth
    u = jax.random.uniform(key, (N,))
    keep_prob = other_rate / max(1.0 - top_rate, 1e-12)
    sampled_rest = (~is_top) & (u < keep_prob)
    multiplier = (1.0 - top_rate) / max(other_rate, 1e-12)
    mask = is_top | sampled_rest
    amp = jnp.where(sampled_rest, multiplier, 1.0).astype(grad.dtype)
    mf = mask.astype(grad.dtype) * amp
    return grad * mf, hess * mf, mask


def create_sample_strategy(config: Config, num_data: int,
                           label=None, query_boundaries=None) -> SampleStrategy:
    """(reference: SampleStrategy::CreateSampleStrategy,
    src/boosting/sample_strategy.cpp)"""
    if config.data_sample_strategy == "goss":
        return GossStrategy(config, num_data)
    bs = BaggingStrategy(config, num_data, label, query_boundaries)
    if bs.enabled:
        log.info("Using bagging, fraction=%g freq=%d",
                 config.bagging_fraction, config.bagging_freq)
    return bs
