"""SHAP feature contributions (pred_contrib).

The native TreeSHAP kernel (native/treeshap.cpp) implements the reference's
per-row unique-path recursion (reference: src/io/tree.cpp TreeSHAP,
include/LightGBM/tree.h PredictContrib); this module marshals host trees
into its flat-array layout and provides a pure-Python fallback for
compiler-less environments.
"""
from __future__ import annotations

import ctypes
import math
from typing import List

import numpy as np

from ..utils import log
from .tree import MISSING_NAN_C, MISSING_ZERO_C, Tree


def _tree_arrays(tree: Tree):
    n = tree.num_internal
    L = tree.num_leaves
    split_feature = np.asarray(tree.split_feature[:n], np.int32)
    threshold = np.asarray(tree.threshold_real[:n], np.float64)
    default_left = np.asarray(tree.default_left[:n], np.uint8)
    missing_type = np.asarray(tree.missing_type[:n], np.int32)
    left = np.asarray(tree.left_child[:n], np.int32)
    right = np.asarray(tree.right_child[:n], np.int32)
    is_cat = np.asarray(tree.is_categorical[:n], np.uint8)
    offs = [0]
    words: List[int] = []
    for i in range(n):
        bits = np.asarray(tree.cat_bitset_real[i], np.uint32)
        words.extend(int(w) for w in bits)
        offs.append(len(words))
    cat_bits = np.asarray(words if words else [0], np.uint32)
    cat_offs = np.asarray(offs, np.int64)
    internal_value = np.asarray(tree.internal_value[:n], np.float64)
    internal_count = np.asarray(tree.internal_count[:n], np.float64)
    leaf_value = np.asarray(tree.leaf_value[:L], np.float64)
    leaf_count = np.asarray(tree.leaf_count[:L], np.float64)
    return (split_feature, threshold, default_left, missing_type, left,
            right, is_cat, cat_bits, cat_offs, internal_value,
            internal_count, leaf_value, leaf_count)


def tree_shap_accumulate(tree: Tree, X: np.ndarray, phi: np.ndarray) -> None:
    """Add one tree's SHAP values into phi [N, F+1] (last col = expected)."""
    from ..native import get_lib
    lib = get_lib()
    arrs = _tree_arrays(tree)
    if lib is not None:
        X64 = np.ascontiguousarray(X, dtype=np.float64)
        def ptr(a, ct):
            return a.ctypes.data_as(ctypes.POINTER(ct))
        (sf, th, dl, mt, lc, rc, ic, cb, co, iv, icnt, lv, lcnt) = arrs
        lib.lg_tree_shap(
            tree.num_internal,
            ptr(sf, ctypes.c_int32), ptr(th, ctypes.c_double),
            ptr(dl, ctypes.c_uint8), ptr(mt, ctypes.c_int32),
            ptr(lc, ctypes.c_int32), ptr(rc, ctypes.c_int32),
            ptr(ic, ctypes.c_uint8), ptr(cb, ctypes.c_uint32),
            ptr(co, ctypes.c_int64), ptr(iv, ctypes.c_double),
            ptr(icnt, ctypes.c_double), ptr(lv, ctypes.c_double),
            ptr(lcnt, ctypes.c_double),
            X64.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            X64.shape[0], X64.shape[1],
            phi.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        return
    _tree_shap_python(tree, X, phi)


def tree_shap_linear(tree: Tree, X: np.ndarray, phi: np.ndarray) -> None:
    """SHAP for a piece-wise linear tree via the coefficient-attribution
    split (arXiv:1802.05640): a linear leaf's output decomposes as
    ``leaf_const + sum_f coeff_f * x_f``, so the STRUCTURAL attribution
    runs standard TreeSHAP over the leaf constants (path credit for
    reaching the leaf) and each linear term attributes directly to its own
    feature. Rows then sum to the raw prediction exactly, the invariant
    the old ``pred_contrib`` rejection existed to protect.

    A row with NaN in its leaf's features predicts the constant fallback
    ``leaf_value``; the difference to the structurally-attributed
    ``leaf_const`` goes to the first NaN feature (the one that caused the
    fallback), keeping the sum invariant for fallback rows too."""
    L = tree.num_leaves
    const = np.asarray(tree.leaf_const[:L], np.float64)
    lv_save = tree.leaf_value
    lv = np.asarray(lv_save, np.float64).copy()
    lv[:L] = const
    tree.leaf_value = lv
    try:
        # structural pass over the constants (native kernel or fallback)
        tree_shap_accumulate(tree, X, phi)
    finally:
        tree.leaf_value = lv_save
    for r in range(X.shape[0]):
        row = X[r]
        node = 0 if tree.num_internal > 0 else ~0
        while node >= 0:
            node = (tree.left_child[node] if _decide(tree, node, row)
                    else tree.right_child[node])
        leaf = ~node
        feats = tree.leaf_features[leaf]
        if not feats:
            continue
        xs = row[list(feats)]
        nan = np.isnan(xs)
        if nan.any():
            phi[r, feats[int(np.argmax(nan))]] += \
                float(lv_save[leaf]) - float(const[leaf])
            continue
        coeff = np.asarray(tree.leaf_coeff[leaf], np.float64)
        for f, c, v in zip(feats, coeff, xs):
            phi[r, f] += c * v


# ---------------------------------------------------------------------------
# pure-Python fallback (same recursion; slow, for no-compiler environments)
# ---------------------------------------------------------------------------

def _tree_shap_python(tree: Tree, X: np.ndarray, phi: np.ndarray) -> None:
    n = tree.num_internal
    L = tree.num_leaves
    lv = tree.leaf_value[:L]
    lcnt = tree.leaf_count[:L].astype(np.float64)
    expected = (float(np.dot(lv, lcnt) / lcnt.sum())
                if n > 0 and lcnt.sum() > 0 else float(lv[0]))
    phi[:, -1] += expected
    if n == 0:
        return

    def cover(node):
        return (tree.internal_count[node] if node >= 0
                else float(tree.leaf_count[~node]))

    def extend(path, zf, of, fi):
        d = len(path)
        path.append([fi, zf, of, 1.0 if d == 0 else 0.0])
        for i in range(d - 1, -1, -1):
            path[i + 1][3] += of * path[i][3] * (i + 1) / (d + 1)
            path[i][3] = zf * path[i][3] * (d - i) / (d + 1)

    def unwind(path, i0):
        d = len(path) - 1
        of, zf = path[i0][2], path[i0][1]
        nop = path[d][3]
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = path[i][3]
                path[i][3] = nop * (d + 1) / ((i + 1) * of)
                nop = tmp - path[i][3] * zf * (d - i) / (d + 1)
            else:
                path[i][3] = path[i][3] * (d + 1) / (zf * (d - i))
        for i in range(i0, d):
            path[i][0], path[i][1], path[i][2] = \
                path[i + 1][0], path[i + 1][1], path[i + 1][2]
        path.pop()

    def unwound_sum(path, i0):
        d = len(path) - 1
        of, zf = path[i0][2], path[i0][1]
        nop = path[d][3]
        total = 0.0
        for i in range(d - 1, -1, -1):
            if of != 0:
                tmp = nop * (d + 1) / ((i + 1) * of)
                total += tmp
                nop = path[i][3] - tmp * zf * (d - i) / (d + 1)
            else:
                total += path[i][3] / (zf * (d - i) / (d + 1))
        return total

    def rec(row, phi_r, node, path, pzf, pof, pfi):
        path = [list(e) for e in path]
        extend(path, pzf, pof, pfi)
        if node < 0:
            v = float(tree.leaf_value[~node])
            for i in range(1, len(path)):
                w = unwound_sum(path, i)
                phi_r[path[i][0]] += w * (path[i][2] - path[i][1]) * v
            return
        go_left = _decide(tree, node, row)
        hot = tree.left_child[node] if go_left else tree.right_child[node]
        cold = tree.right_child[node] if go_left else tree.left_child[node]
        w = cover(node)
        hzf, czf = cover(hot) / w, cover(cold) / w
        izf = iof = 1.0
        f = tree.split_feature[node]
        k = next((i for i in range(len(path)) if path[i][0] == f), None)
        if k is not None:
            izf, iof = path[k][1], path[k][2]
            unwind(path, k)
        rec(row, phi_r, hot, path, hzf * izf, iof, f)
        rec(row, phi_r, cold, path, czf * izf, 0.0, f)

    for r in range(X.shape[0]):
        rec(X[r], phi[r], 0, [], 1.0, 1.0, -1)


def _decide(tree: Tree, node: int, row) -> bool:
    v = row[tree.split_feature[node]]
    if tree.is_categorical[node]:
        if math.isnan(v):
            return False
        c = int(v)
        bits = tree.cat_bitset_real[node]
        return 0 <= c < len(bits) * 32 and bool((bits[c // 32] >> (c % 32)) & 1)
    mt = tree.missing_type[node]
    if math.isnan(v) and mt != MISSING_NAN_C:
        v = 0.0
    if (mt == MISSING_NAN_C and math.isnan(v)) or \
       (mt == MISSING_ZERO_C and abs(v) <= 1e-35):
        return bool(tree.default_left[node])
    return v <= tree.threshold_real[node]
