"""Tree model.

TPU-native re-implementation of the reference's flat-array binary tree
(reference: include/LightGBM/tree.h:26, src/io/tree.cpp). A tree is built on
the host during training (appending one split per step, cheap) and stacked
into padded device arrays for batched prediction (see
:mod:`lambdagap_tpu.ops.predict`).

Node encoding follows the reference: internal nodes are indexed 0..n-1; child
pointers are either an internal index (>= 0) or ``~leaf_index`` (< 0).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

MISSING_NONE_C, MISSING_ZERO_C, MISSING_NAN_C = 0, 1, 2
_FORCE_LEFT_BIN = 1 << 30      # threshold_bin sentinel: every bin goes left
_FORCE_RIGHT_BIN = -1          # threshold_bin sentinel: every bin goes right


@dataclass
class Tree:
    """One decision tree with up to ``max_leaves`` leaves."""

    max_leaves: int
    num_leaves: int = 1
    shrinkage: float = 1.0

    # per internal node (index 0..num_leaves-2)
    split_feature: List[int] = field(default_factory=list)   # original feature idx
    split_feature_inner: List[int] = field(default_factory=list)  # used-feature idx
    threshold_bin: List[int] = field(default_factory=list)
    threshold_real: List[float] = field(default_factory=list)
    default_left: List[bool] = field(default_factory=list)
    missing_type: List[int] = field(default_factory=list)
    left_child: List[int] = field(default_factory=list)
    right_child: List[int] = field(default_factory=list)
    split_gain: List[float] = field(default_factory=list)
    is_categorical: List[bool] = field(default_factory=list)
    cat_bitset: List[np.ndarray] = field(default_factory=list)      # bin-space bitsets
    cat_bitset_real: List[np.ndarray] = field(default_factory=list)  # raw category values
    internal_value: List[float] = field(default_factory=list)
    internal_weight: List[float] = field(default_factory=list)
    internal_count: List[int] = field(default_factory=list)

    # linear-tree payload (reference: tree.h is_linear_ / leaf_coeff_)
    is_linear: bool = False
    leaf_features: Optional[list] = None
    leaf_coeff: Optional[list] = None
    leaf_const: Optional[np.ndarray] = None

    # per leaf
    leaf_value: Optional[np.ndarray] = None
    leaf_weight: Optional[np.ndarray] = None
    leaf_count: Optional[np.ndarray] = None
    leaf_parent: Optional[np.ndarray] = None
    leaf_depth: Optional[np.ndarray] = None

    def __post_init__(self) -> None:
        self.leaf_value = np.zeros(self.max_leaves, dtype=np.float64)
        self.leaf_weight = np.zeros(self.max_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(self.max_leaves, dtype=np.int64)
        self.leaf_parent = np.full(self.max_leaves, -1, dtype=np.int32)
        self.leaf_depth = np.zeros(self.max_leaves, dtype=np.int32)

    @property
    def num_internal(self) -> int:
        return self.num_leaves - 1

    def split(self, leaf: int, feature: int, feature_inner: int,
              threshold_bin: int, threshold_real: float, default_left: bool,
              missing_type: int, gain: float,
              left_value: float, right_value: float,
              left_weight: float, right_weight: float,
              left_count: int, right_count: int,
              is_categorical: bool = False,
              cat_bitset: Optional[np.ndarray] = None,
              cat_bitset_real: Optional[np.ndarray] = None) -> int:
        """Split ``leaf``; left child keeps the leaf index, right child becomes
        leaf ``num_leaves`` (reference: tree.h:63 Split / tree.cpp SplitInner).
        Returns the new right leaf index."""
        node = self.num_leaves - 1
        parent_node = self.leaf_parent[leaf]
        if parent_node >= 0:
            if self.left_child[parent_node] == ~leaf:
                self.left_child[parent_node] = node
            else:
                self.right_child[parent_node] = node

        new_leaf = self.num_leaves
        self.split_feature.append(int(feature))
        self.split_feature_inner.append(int(feature_inner))
        self.threshold_bin.append(int(threshold_bin))
        self.threshold_real.append(float(threshold_real))
        self.default_left.append(bool(default_left))
        self.missing_type.append(int(missing_type))
        self.left_child.append(~leaf)
        self.right_child.append(~new_leaf)
        self.split_gain.append(float(gain))
        self.is_categorical.append(bool(is_categorical))
        self.cat_bitset.append(cat_bitset if cat_bitset is not None
                               else np.zeros(8, dtype=np.uint32))
        self.cat_bitset_real.append(cat_bitset_real if cat_bitset_real is not None
                                    else np.zeros(8, dtype=np.uint32))
        parent_value = self.leaf_value[leaf]
        parent_weight = self.leaf_weight[leaf]
        self.internal_value.append(float(parent_value))
        self.internal_weight.append(float(parent_weight))
        self.internal_count.append(int(left_count + right_count))

        depth = self.leaf_depth[leaf] + 1
        self.leaf_value[leaf] = left_value
        self.leaf_weight[leaf] = left_weight
        self.leaf_count[leaf] = left_count
        self.leaf_parent[leaf] = node
        self.leaf_depth[leaf] = depth
        self.leaf_value[new_leaf] = right_value
        self.leaf_weight[new_leaf] = right_weight
        self.leaf_count[new_leaf] = right_count
        self.leaf_parent[new_leaf] = node
        self.leaf_depth[new_leaf] = depth
        self.num_leaves += 1
        return new_leaf

    def apply_shrinkage(self, rate: float) -> None:
        """(reference: tree.h Shrinkage)"""
        self.leaf_value[:self.num_leaves] *= rate
        self.internal_value = [v * rate for v in self.internal_value]
        if self.is_linear:
            self.leaf_const[:self.num_leaves] *= rate
            for leaf in range(self.num_leaves):
                self.leaf_coeff[leaf] = self.leaf_coeff[leaf] * rate
        self.shrinkage *= rate

    def set_leaf_values(self, values: np.ndarray) -> None:
        self.leaf_value[:self.num_leaves] = values[:self.num_leaves]

    @property
    def max_depth(self) -> int:
        return int(self.leaf_depth[:self.num_leaves].max()) if self.num_leaves > 1 else 0

    # ------------------------------------------------------------------
    def predict_row(self, row: np.ndarray) -> float:
        """Reference-semantics single-row traversal (host, for testing/export;
        reference: tree.h:130-141 Predict/NumericalDecision)."""
        if self.num_leaves == 1:
            leaf = 0
        else:
            node = 0
            while node >= 0:
                node = self._decision(row, node)
            leaf = ~node
        if self.is_linear:
            feats = self.leaf_features[leaf]
            vals = row[feats] if feats else np.empty(0)
            if not np.isnan(vals).any():
                return float(self.leaf_const[leaf]
                             + (vals @ self.leaf_coeff[leaf] if feats else 0.0))
        return float(self.leaf_value[leaf])

    def _decision(self, row: np.ndarray, node: int) -> int:
        fval = row[self.split_feature[node]]
        if self.is_categorical[node]:
            go_left = False
            if not np.isnan(fval):
                cat = int(fval)
                bits = self.cat_bitset_real[node]
                if 0 <= cat < len(bits) * 32:
                    go_left = bool((bits[cat // 32] >> (cat % 32)) & 1)
        else:
            mt = self.missing_type[node]
            if np.isnan(fval) and mt != MISSING_NAN_C:
                fval = 0.0
            if (mt == MISSING_NAN_C and np.isnan(fval)) or \
               (mt == MISSING_ZERO_C and abs(fval) <= 1e-35):
                go_left = self.default_left[node]
            else:
                go_left = fval <= self.threshold_real[node]
        return self.left_child[node] if go_left else self.right_child[node]


def rebind_to_dataset(tree: Tree, ds) -> None:
    """Fill a deserialized tree's bin-space fields from a dataset's mappers.

    Loaded models carry only raw-space decisions (real thresholds, raw
    category bitsets). Continued training and refit replay trees over the
    *binned* matrix, which needs ``split_feature_inner`` / ``threshold_bin`` /
    bin-space ``cat_bitset`` consistent with THIS dataset's binning
    (the reference keeps both representations on every tree —
    src/io/tree.cpp threshold_in_bin_ — so its continued training
    (GBDT::ResetTrainingData after LoadModelFromString) gets this for free).

    A feature that is trivial (constant) in the new dataset has no binned
    column; its nodes are constant-folded to route every row the way the
    constant value would go (missing-value routing of such nodes follows).
    """
    from ..data.binning import (BIN_CATEGORICAL, MISSING_NAN, MISSING_NONE,
                                MISSING_ZERO)
    from ..utils import log
    mt_code = {MISSING_NONE: MISSING_NONE_C, MISSING_ZERO: MISSING_ZERO_C,
               MISSING_NAN: MISSING_NAN_C}
    inner_of = {j: k for k, j in enumerate(ds.used_features)}
    n = tree.num_internal
    tree.split_feature_inner = list(tree.split_feature)
    tree.threshold_bin = [0] * n
    for i in range(n):
        f = tree.split_feature[i]
        if f >= len(ds.mappers):
            log.fatal("Model uses feature %d but dataset has only %d features",
                      f, len(ds.mappers))
        m = ds.mappers[f]
        if f not in inner_of:
            # constant feature in this data: fold the decision
            tree.split_feature_inner[i] = 0
            if tree.is_categorical[i]:
                cat = int(m.min_val) if not np.isnan(m.min_val) else -1
                bits = tree.cat_bitset_real[i]
                go_left = (0 <= cat < len(bits) * 32
                           and bool((bits[cat // 32] >> (cat % 32)) & 1))
                tree.cat_bitset[i] = (np.full(8, 0xFFFFFFFF, np.uint32)
                                      if go_left else np.zeros(8, np.uint32))
            else:
                v = m.min_val
                mt = tree.missing_type[i]
                if (mt == MISSING_NAN_C and np.isnan(v)) or \
                   (mt == MISSING_ZERO_C and abs(v) <= 1e-35):
                    go_left = tree.default_left[i]
                else:
                    go_left = (0.0 if np.isnan(v) else v) <= tree.threshold_real[i]
                tree.threshold_bin[i] = (_FORCE_LEFT_BIN if go_left
                                         else _FORCE_RIGHT_BIN)
                tree.default_left[i] = bool(go_left)
            continue
        tree.split_feature_inner[i] = inner_of[f]
        ds_mt = mt_code[m.missing_type]
        if tree.is_categorical[i]:
            if m.bin_type != BIN_CATEGORICAL:
                log.fatal("Model splits categorically on feature %d but the "
                          "dataset binned it as numerical", f)
            bits = np.zeros(8, dtype=np.uint32)
            real = np.asarray(tree.cat_bitset_real[i], dtype=np.uint32)
            width = len(real) * 32
            for cat, b in m.categorical_2_bin.items():
                if 0 <= cat < width and (real[cat // 32] >> (cat % 32)) & 1:
                    if b < 256:
                        bits[b // 32] |= np.uint32(1 << (b % 32))
                    else:
                        log.warning("Categorical bin %d of feature %d exceeds "
                                    "the 256-bin bitset; dropped in replay", b, f)
            tree.cat_bitset[i] = bits
        else:
            tree.threshold_bin[i] = int(
                m.values_to_bins(np.asarray([tree.threshold_real[i]]))[0])
            # reconcile missing semantics with THIS dataset's bins: the binned
            # traversal derives the NaN bin from the dataset (feature_meta), so
            # a node whose stored type disagrees must be adjusted to route NaN
            # rows exactly like the raw-space decision would
            if tree.missing_type[i] == MISSING_NONE_C and ds_mt == MISSING_NAN_C:
                # raw NumericalDecision converts NaN to 0.0 under MissingType::None
                tree.missing_type[i] = MISSING_NAN_C
                tree.default_left[i] = bool(0.0 <= tree.threshold_real[i])
            elif tree.missing_type[i] == MISSING_NAN_C and ds_mt != MISSING_NAN_C:
                log.debug("Feature %d: model expects NaN missing but dataset "
                          "has none; NaN handling folded away", f)
                tree.missing_type[i] = MISSING_NONE_C


def linear_leaf_outputs(tree: Tree, X_raw: np.ndarray,
                        leaf_idx: np.ndarray) -> np.ndarray:
    """Per-row outputs of a linear tree given each row's leaf index
    (rows with NaN in the leaf's features get the constant leaf value,
    reference: linear_tree_learner.cpp / tree.cpp PredictLinear)."""
    out = np.asarray(tree.leaf_value[leaf_idx], np.float64).copy()
    if not getattr(tree, "is_linear", False):
        return out
    for leaf in range(tree.num_leaves):
        feats = tree.leaf_features[leaf]
        sel = leaf_idx == leaf
        if not sel.any():
            continue
        if not feats:
            out[sel] = tree.leaf_const[leaf]
            continue
        Xs = X_raw[sel][:, feats].astype(np.float64)
        nan = np.isnan(Xs).any(axis=1)
        lin = tree.leaf_const[leaf] + Xs @ tree.leaf_coeff[leaf]
        out[sel] = np.where(nan, tree.leaf_value[leaf], lin)
    return out
