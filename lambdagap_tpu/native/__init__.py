"""Native (C++) helpers, compiled on demand with g++ and loaded via ctypes.

The reference keeps its performance-critical host IO in C++
(reference: src/io/parser.cpp, src/io/dataset_loader.cpp); this package is
the equivalent. Compilation is lazy and cached next to the source; if no
compiler is available the callers fall back to Python parsing.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

from ..utils import log

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[str]:
    here = os.path.dirname(__file__)
    srcs = [os.path.join(here, "parser.cpp"),
            os.path.join(here, "treeshap.cpp")]
    out = os.path.join(here, "_lg_native.so")
    if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs):
        return out
    try:
        subprocess.run(["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        *srcs, "-o", out],
                       check=True, capture_output=True, timeout=120)
        return out
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("Native build failed (%s); using Python fallback", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        path = _build_lib()
        if path is not None:
            lib = ctypes.CDLL(path)
            i64p = ctypes.POINTER(ctypes.c_int64)
            dp = ctypes.POINTER(ctypes.c_double)
            lib.lg_count_libsvm.argtypes = [ctypes.c_char_p, i64p, i64p]
            lib.lg_parse_libsvm.argtypes = [ctypes.c_char_p, dp, dp, i64p,
                                            ctypes.c_int64, ctypes.c_int64]
            lib.lg_count_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, i64p, i64p]
            lib.lg_parse_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, dp,
                                           ctypes.c_int64, ctypes.c_int64]
            i32p = ctypes.POINTER(ctypes.c_int32)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u32p = ctypes.POINTER(ctypes.c_uint32)
            lib.lg_tree_shap.argtypes = [
                ctypes.c_int64, i32p, dp, u8p, i32p, i32p, i32p, u8p,
                u32p, i64p, dp, dp, dp, dp, dp,
                ctypes.c_int64, ctypes.c_int64, dp]
            lib.lg_tree_shap.restype = None
            _LIB = lib
    return _LIB
