"""Native (C++) helpers, compiled on demand with g++ and loaded via ctypes.

The reference keeps its performance-critical host IO in C++
(reference: src/io/parser.cpp, src/io/dataset_loader.cpp); this package is
the equivalent. Compilation is lazy and cached next to the source; if no
compiler is available the callers fall back to Python parsing.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

from ..utils import log

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[str]:
    src = os.path.join(os.path.dirname(__file__), "parser.cpp")
    out = os.path.join(os.path.dirname(__file__), "_lg_native.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    try:
        subprocess.run(["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        src, "-o", out],
                       check=True, capture_output=True, timeout=120)
        return out
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("Native parser build failed (%s); using Python fallback", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        path = _build_lib()
        if path is not None:
            lib = ctypes.CDLL(path)
            i64p = ctypes.POINTER(ctypes.c_int64)
            dp = ctypes.POINTER(ctypes.c_double)
            lib.lg_count_libsvm.argtypes = [ctypes.c_char_p, i64p, i64p]
            lib.lg_parse_libsvm.argtypes = [ctypes.c_char_p, dp, dp, i64p,
                                            ctypes.c_int64, ctypes.c_int64]
            lib.lg_count_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, i64p, i64p]
            lib.lg_parse_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, dp,
                                           ctypes.c_int64, ctypes.c_int64]
            _LIB = lib
    return _LIB
