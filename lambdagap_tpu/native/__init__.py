"""Native (C++) helpers, compiled on demand with g++ and loaded via ctypes.

The reference keeps its performance-critical host IO in C++
(reference: src/io/parser.cpp, src/io/dataset_loader.cpp); this package is
the equivalent. Compilation is lazy and cached next to the source; if no
compiler is available the callers fall back to Python parsing.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Optional

from ..utils import log

_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_lib() -> Optional[str]:
    here = os.path.dirname(__file__)
    srcs = [os.path.join(here, "parser.cpp"),
            os.path.join(here, "treeshap.cpp"),
            os.path.join(here, "binner.cpp"),
            os.path.join(here, "fastpred.cpp"),
            os.path.join(here, "capi.cpp")]
    out = os.path.join(here, "_lg_native.so")
    if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs):
        return out
    try:
        subprocess.run(["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                        "-pthread", *srcs, "-o", out],
                       check=True, capture_output=True, timeout=120)
        return out
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        log.warning("Native build failed (%s); using Python fallback", e)
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is None and not _TRIED:
        _TRIED = True
        path = _build_lib()
        if path is not None:
            lib = ctypes.CDLL(path)
            i64p = ctypes.POINTER(ctypes.c_int64)
            dp = ctypes.POINTER(ctypes.c_double)
            lib.lg_count_libsvm.argtypes = [ctypes.c_char_p, i64p, i64p]
            lib.lg_parse_libsvm.argtypes = [ctypes.c_char_p, dp, dp, i64p,
                                            ctypes.c_int64, ctypes.c_int64]
            lib.lg_count_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, i64p, i64p]
            lib.lg_parse_delim.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                           ctypes.c_int, dp,
                                           ctypes.c_int64, ctypes.c_int64]
            i32p = ctypes.POINTER(ctypes.c_int32)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u32p = ctypes.POINTER(ctypes.c_uint32)
            lib.lg_tree_shap.argtypes = [
                ctypes.c_int64, i32p, dp, u8p, i32p, i32p, i32p, u8p,
                u32p, i64p, dp, dp, dp, dp, dp,
                ctypes.c_int64, ctypes.c_int64, dp]
            lib.lg_tree_shap.restype = None
            i8p = ctypes.POINTER(ctypes.c_int8)
            lib.lg_bin_matrix.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, i64p, dp, i64p, i8p, i32p,
                u8p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
            lib.lg_bin_matrix.restype = None
            fp = ctypes.POINTER(ctypes.c_float)
            lib.lg_fast_predict.argtypes = [
                ctypes.c_int64, i64p, i64p, i32p, fp, u8p, u8p, u8p, i64p,
                i32p, u32p, i32p, i32p, dp, i32p, ctypes.c_int64,
                fp, ctypes.c_int64, ctypes.c_int64, dp]
            lib.lg_fast_predict.restype = None
            _LIB = lib
    return _LIB


class FastForest:
    """Flattened read-only forest for the native low-latency predictor
    (reference: src/c_api.cpp:63 SingleRowPredictorInner). Thread-safe:
    prediction touches only these arrays."""

    def __init__(self, trees, tree_class, n_class: int) -> None:
        import numpy as np
        node_off = [0]
        leaf_off = [0]
        feat, thr, dl, mt, ic = [], [], [], [], []
        left, right = [], []
        cat_off, cat_len, cat_bits = [], [], []
        leaf_val = []
        for t in trees:
            n = t.num_internal
            node_off.append(node_off[-1] + n)
            leaf_off.append(leaf_off[-1] + max(t.num_leaves, 1))
            feat.extend(t.split_feature[:n])
            thr.extend(t.threshold_real[:n])
            dl.extend(t.default_left[:n])
            mt.extend(t.missing_type[:n])
            ic.extend(t.is_categorical[:n])
            left.extend(t.left_child[:n])
            right.extend(t.right_child[:n])
            for i in range(n):
                bits = t.cat_bitset_real[i]
                cat_off.append(len(cat_bits))
                cat_len.append(len(bits))
                cat_bits.extend(int(w) for w in bits)
            leaf_val.extend(float(v) for v in
                            t.leaf_value[:max(t.num_leaves, 1)])
        self.n_trees = len(trees)
        self.node_off = np.asarray(node_off, np.int64)
        self.leaf_off = np.asarray(leaf_off, np.int64)
        self.feat = np.asarray(feat, np.int32)
        self.thr = np.asarray(thr, np.float32)
        self.dl = np.asarray(dl, np.uint8)
        self.mt = np.asarray(mt, np.uint8)
        self.ic = np.asarray(ic, np.uint8)
        self.cat_off = np.asarray(cat_off, np.int64)
        self.cat_len = np.asarray(cat_len, np.int32)
        self.cat_bits = np.asarray(cat_bits if cat_bits else [0], np.uint32)
        self.left = np.asarray(left, np.int32)
        self.right = np.asarray(right, np.int32)
        self.leaf_val = np.asarray(leaf_val, np.float64)
        self.tree_class = np.asarray(tree_class, np.int32)
        self.n_class = int(n_class)
        self.max_feat = int(self.feat.max()) if len(self.feat) else 0

    def predict(self, X) -> "np.ndarray":
        """Raw scores [n_rows, n_class]; X is float32 row-major [n, d]."""
        import numpy as np
        lib = get_lib()
        X = np.ascontiguousarray(X, dtype=np.float32)
        n, d = X.shape
        out = np.zeros((n, self.n_class), dtype=np.float64)
        c = ctypes
        lib.lg_fast_predict(
            self.n_trees,
            self.node_off.ctypes.data_as(c.POINTER(c.c_int64)),
            self.leaf_off.ctypes.data_as(c.POINTER(c.c_int64)),
            self.feat.ctypes.data_as(c.POINTER(c.c_int32)),
            self.thr.ctypes.data_as(c.POINTER(c.c_float)),
            self.dl.ctypes.data_as(c.POINTER(c.c_uint8)),
            self.mt.ctypes.data_as(c.POINTER(c.c_uint8)),
            self.ic.ctypes.data_as(c.POINTER(c.c_uint8)),
            self.cat_off.ctypes.data_as(c.POINTER(c.c_int64)),
            self.cat_len.ctypes.data_as(c.POINTER(c.c_int32)),
            self.cat_bits.ctypes.data_as(c.POINTER(c.c_uint32)),
            self.left.ctypes.data_as(c.POINTER(c.c_int32)),
            self.right.ctypes.data_as(c.POINTER(c.c_int32)),
            self.leaf_val.ctypes.data_as(c.POINTER(c.c_double)),
            self.tree_class.ctypes.data_as(c.POINTER(c.c_int32)),
            self.n_class,
            X.ctypes.data_as(c.POINTER(c.c_float)), n, d,
            out.ctypes.data_as(c.POINTER(c.c_double)))
        return out


def bin_matrix_native(data, used_features, mappers, out) -> bool:
    """Bin the numerical columns of ``data`` into ``out`` via the native
    single-pass loop (reference analog: the multi-threaded push at
    src/io/dataset_loader.cpp:203). Returns False when the native lib is
    unavailable or the dtype is unsupported; categorical columns are always
    left for the caller (``skip`` mask)."""
    import numpy as np
    lib = get_lib()
    if lib is None:
        return False
    if data.dtype == np.float64:
        code = 0
    elif data.dtype == np.float32:
        code = 1
    else:
        return False
    data = np.ascontiguousarray(data)
    n, f_total = data.shape
    n_used = len(used_features)
    used_idx = np.asarray(used_features, dtype=np.int64)
    bounds_list, missing, nan_bins, skip = [], [], [], []
    from ..data.binning import BIN_CATEGORICAL, MISSING_NAN
    for j in used_features:
        m = mappers[j]
        if m.bin_type == BIN_CATEGORICAL:
            bounds_list.append(np.empty(0, np.float64))
            missing.append(0)
            nan_bins.append(0)
            skip.append(1)
            continue
        b = np.asarray([x for x in m.bin_upper_bound if not np.isnan(x)],
                       dtype=np.float64)
        bounds_list.append(b)
        missing.append(2 if m.missing_type == MISSING_NAN else 0)
        nan_bins.append(m.num_bin - 1)
        skip.append(0)
    bounds_flat = (np.concatenate(bounds_list) if bounds_list
                   else np.empty(0, np.float64))
    bounds_off = np.zeros(n_used + 1, dtype=np.int64)
    np.cumsum([len(b) for b in bounds_list], out=bounds_off[1:])
    missing = np.asarray(missing, dtype=np.int8)
    nan_bins_a = np.asarray(nan_bins, dtype=np.int32)
    skip_a = np.asarray(skip, dtype=np.uint8)
    out16 = 1 if out.dtype.itemsize == 2 else 0
    c = ctypes
    lib.lg_bin_matrix(
        data.ctypes.data_as(c.c_void_p), code, n, f_total, n_used,
        used_idx.ctypes.data_as(c.POINTER(c.c_int64)),
        bounds_flat.ctypes.data_as(c.POINTER(c.c_double)),
        bounds_off.ctypes.data_as(c.POINTER(c.c_int64)),
        missing.ctypes.data_as(c.POINTER(c.c_int8)),
        nan_bins_a.ctypes.data_as(c.POINTER(c.c_int32)),
        skip_a.ctypes.data_as(c.POINTER(c.c_uint8)),
        out.ctypes.data_as(c.c_void_p), out16, 0)
    return True
