// Native matrix binner: raw values -> bin indices in one pass.
//
// The TPU-framework analog of the reference's multi-threaded dataset push
// (reference: src/io/dataset_loader.cpp:203 ConstructFromSampleData +
// the OpenMP push loops): binning the full matrix is host-side latency on
// the critical path to the first boosting iteration. The numpy route pays
// ~6 full-size temporaries per column (f64 cast, nan mask, where, bins,
// clip, astype); this loop reads each value once and writes one byte.
//
// Semantics must match BinMapper.values_to_bins (data/binning.py):
//   - NaN -> nan_bin when missing_type == NAN (2), else treated as 0.0
//   - bin = lower_bound(bounds, v)  (numpy searchsorted side='left'),
//     clipped to num_bounds - 1
// Bounds arrays exclude the trailing NaN sentinel, exactly as the python
// path's `bounds` local does.
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

// branchless lower_bound (compiles to cmov): first idx with b[idx] >= v
inline int64_t lower_idx(const double* b, int64_t nb, double v) {
  const double* base = b;
  int64_t len = nb;
  while (len > 1) {
    int64_t half = len >> 1;
    base = (base[half - 1] < v) ? base + half : base;
    len -= half;
  }
  return (base - b) + (base[0] < v ? 1 : 0);
}

template <typename T>
inline int bin_of(T raw, const double* b, int64_t nb, int8_t missing_type,
                  int32_t nan_bin) {
  double v = static_cast<double>(raw);
  if (std::isnan(v)) {
    if (missing_type == 2) return nan_bin;
    v = 0.0;
  }
  int64_t idx = lower_idx(b, nb, v);
  if (idx >= nb) idx = nb - 1;
  return static_cast<int>(idx);
}

// Per-feature acceleration grid: table[c] = lower_bound index of the cell's
// left edge over a uniform grid spanning the finite bound range. A value
// jumps to its cell's start index and advances past the (typically 0-2)
// bounds inside the cell — O(1) average instead of a ~8-step dependent-load
// binary search per value (measured 4x on the bench host).
struct FeatureGrid {
  double lo, inv;          // cell = (v - lo) * inv
  std::vector<int32_t> start;
};

constexpr int kGridCells = 2048;

inline void build_grid(const double* b, int64_t nb, FeatureGrid* g) {
  // finite span: bounds end with +inf; nb >= 2 here
  double lo = b[0];
  double hi = b[nb - 2];
  if (!(hi > lo) || !std::isfinite(lo) || !std::isfinite(hi)) {
    g->start.clear();
    return;
  }
  g->lo = lo;
  g->inv = kGridCells / (hi - lo);
  g->start.resize(kGridCells);
  double width = (hi - lo) / kGridCells;
  for (int c = 0; c < kGridCells; ++c) {
    double edge = lo + c * width;
    g->start[c] = static_cast<int32_t>(lower_idx(b, nb, edge));
  }
}

template <typename T, typename OutT>
inline void bin_col_block(const T* col, int64_t f_total, int64_t b0,
                          int64_t b1, const double* b, int64_t nb, int8_t mt,
                          int32_t nanb, OutT* out, int64_t n_used,
                          const FeatureGrid& g) {
  if (g.start.empty()) {          // degenerate span: plain binary search
    for (int64_t i = b0; i < b1; ++i)
      out[i * n_used] = static_cast<OutT>(bin_of(col[i * f_total], b, nb, mt,
                                                 nanb));
    return;
  }
  const int32_t* start = g.start.data();
  const double lo = g.lo, inv = g.inv;
  for (int64_t i = b0; i < b1; ++i) {
    double v = static_cast<double>(col[i * f_total]);
    int64_t idx;
    if (std::isnan(v)) {
      if (mt == 2) {
        out[i * n_used] = static_cast<OutT>(nanb);
        continue;
      }
      v = 0.0;
    }
    double c = (v - lo) * inv;
    if (c < 0.0) {
      idx = 0;                     // v <= first bound
    } else {
      // the >= compare (not a post-cast clamp) also catches +inf and
      // values past int64 range, where the cast itself would be UB
      int64_t cell = (c >= static_cast<double>(kGridCells - 1))
                         ? kGridCells - 1
                         : static_cast<int64_t>(c);
      idx = start[cell];
      while (idx < nb && b[idx] < v) ++idx;
      // guard the rare rounding case where the cell edge lands above v
      while (idx > 0 && b[idx - 1] >= v) --idx;
    }
    if (idx >= nb) idx = nb - 1;
    out[i * n_used] = static_cast<OutT>(idx);
  }
}

template <typename T, typename OutT>
void bin_matrix(const T* data, int64_t n, int64_t f_total, int64_t n_used,
                const int64_t* used_idx, const double* bounds_flat,
                const int64_t* bounds_off, const int8_t* missing_types,
                const int32_t* nan_bins, const uint8_t* skip, OutT* out,
                int n_threads) {
  std::vector<FeatureGrid> grids(n_used);
  for (int64_t k = 0; k < n_used; ++k) {
    if (skip[k]) continue;
    int64_t nb = bounds_off[k + 1] - bounds_off[k];
    if (nb >= 2) build_grid(bounds_flat + bounds_off[k], nb, &grids[k]);
  }
  // feature-major within row blocks: the block's data stays in L2 across
  // feature passes while each feature's bounds + grid stay hot in L1
  constexpr int64_t kBlock = 1024;
  auto work = [&](int64_t r0, int64_t r1) {
    for (int64_t b0 = r0; b0 < r1; b0 += kBlock) {
      int64_t b1 = std::min(r1, b0 + kBlock);
      for (int64_t k = 0; k < n_used; ++k) {
        if (skip[k]) continue;
        bin_col_block(data + used_idx[k], f_total, b0, b1,
                      bounds_flat + bounds_off[k],
                      bounds_off[k + 1] - bounds_off[k], missing_types[k],
                      nan_bins[k], out + k, n_used, grids[k]);
      }
    }
  };
  if (n_threads <= 0) {
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 1;
  }
  if (n_threads == 1 || n < (int64_t)n_threads * 4096) {
    work(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t r0 = t * chunk;
    int64_t r1 = std::min(n, r0 + chunk);
    if (r0 >= r1) break;
    ts.emplace_back(work, r0, r1);
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" {

// dtype_code: 0 = float64, 1 = float32; out16: 0 = uint8, 1 = uint16
void lg_bin_matrix(const void* data, int dtype_code, int64_t n,
                   int64_t f_total, int64_t n_used, const int64_t* used_idx,
                   const double* bounds_flat, const int64_t* bounds_off,
                   const int8_t* missing_types, const int32_t* nan_bins,
                   const uint8_t* skip, void* out, int out16,
                   int n_threads) {
  if (dtype_code == 0 && !out16)
    bin_matrix(static_cast<const double*>(data), n, f_total, n_used,
               used_idx, bounds_flat, bounds_off, missing_types, nan_bins,
               skip, static_cast<uint8_t*>(out), n_threads);
  else if (dtype_code == 0)
    bin_matrix(static_cast<const double*>(data), n, f_total, n_used,
               used_idx, bounds_flat, bounds_off, missing_types, nan_bins,
               skip, static_cast<uint16_t*>(out), n_threads);
  else if (!out16)
    bin_matrix(static_cast<const float*>(data), n, f_total, n_used,
               used_idx, bounds_flat, bounds_off, missing_types, nan_bins,
               skip, static_cast<uint8_t*>(out), n_threads);
  else
    bin_matrix(static_cast<const float*>(data), n, f_total, n_used,
               used_idx, bounds_flat, bounds_off, missing_types, nan_bins,
               skip, static_cast<uint16_t*>(out), n_threads);
}

}  // extern "C"
