// Standalone C serving ABI: load a saved LambdaGap/LightGBM-format text
// model and predict from C/C++ with no Python or JAX in the process.
//
// This is the TPU framework's answer to the reference's C API surface for
// the serving-side use cases (reference: src/c_api.cpp — model load +
// LGBM_BoosterPredictForMat / the thread-safe single-row fast predictor at
// src/c_api.cpp:63). Training stays behind the Python API (the compute path
// is JAX/XLA); what a C consumer needs at run time is model loading and
// low-latency prediction, which live here with reference-compatible
// function names. Build standalone:
//   g++ -O2 -shared -fPIC -std=c++17 capi.cpp -o liblambdagap_c.so
// (also compiled into the package's _lg_native.so).
//
// Supported: numerical/categorical splits, all three missing types, linear
// trees, binary/multiclass/regression/poisson-family output transforms,
// random-forest average_output. Predict types: 0 = transformed, 1 = raw.
#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <vector>

namespace {

struct CTree {
  int num_leaves = 1;
  std::vector<int32_t> split_feature;
  std::vector<double> threshold;
  std::vector<uint8_t> decision_type;
  std::vector<int32_t> left_child, right_child;
  std::vector<double> leaf_value;
  std::vector<int32_t> cat_boundaries;
  std::vector<uint32_t> cat_threshold;
  bool is_linear = false;
  std::vector<double> leaf_const;
  std::vector<int32_t> leaf_feat_off;      // [L+1]
  std::vector<int32_t> leaf_feat;
  std::vector<double> leaf_coeff;

  int leaf_index(const double* row) const {
    int leaf = 0;
    if (num_leaves > 1) {
      int node = 0;
      while (node >= 0) {
        const uint8_t dt = decision_type[node];
        const double fv = row[split_feature[node]];
        bool go_left;
        if (dt & 1) {  // categorical
          go_left = false;
          if (!std::isnan(fv)) {
            int64_t cat = static_cast<int64_t>(fv);
            int lo = cat_boundaries[static_cast<int>(threshold[node])];
            int hi = cat_boundaries[static_cast<int>(threshold[node]) + 1];
            if (cat >= 0 && cat < (int64_t)(hi - lo) * 32)
              go_left = (cat_threshold[lo + (cat >> 5)] >> (cat & 31)) & 1u;
          }
        } else {
          double v = fv;
          const int mt = (dt >> 2) & 3;
          if (std::isnan(v) && mt != 2) v = 0.0;
          if ((mt == 2 && std::isnan(v)) ||
              (mt == 1 && std::fabs(v) <= 1e-35)) {
            go_left = (dt & 2) != 0;
          } else {
            go_left = v <= threshold[node];
          }
        }
        node = go_left ? left_child[node] : right_child[node];
      }
      leaf = ~node;
    }
    return leaf;
  }

  double predict_row(const double* row) const {
    const int leaf = leaf_index(row);
    if (is_linear) {
      bool ok = true;
      double out = leaf_const[leaf];
      for (int i = leaf_feat_off[leaf]; i < leaf_feat_off[leaf + 1]; ++i) {
        double v = row[leaf_feat[i]];
        if (std::isnan(v)) { ok = false; break; }
        out += v * leaf_coeff[i];
      }
      if (ok) return out;
    }
    return leaf_value[leaf];
  }
};

struct CModel {
  int num_class = 1;
  int max_feature_idx = 0;
  bool average_output = false;
  std::string objective = "regression";
  double sigmoid = 1.0;
  bool sqrt_transform = false;   // "regression sqrt" (reg_sqrt=true)
  // Verbatim loaded text, retained to support SaveModel. Deliberate
  // tradeoff: ~1x the text size of extra resident memory per booster
  // (typically a few MB); consumers that never SaveModel and hold very
  // large ensembles can keep their own copy instead.
  std::string model_text;
  std::vector<CTree> trees;

  // Predict trees [start_tree, end_tree) for one row.
  // predict_type: 0 = transformed, 1 = raw score (C_API_PREDICT_*).
  void predict(const double* row, int predict_type, double* out,
               size_t start_tree, size_t end_tree) const {
    for (int k = 0; k < num_class; ++k) out[k] = 0.0;
    for (size_t t = start_tree; t < end_tree; ++t)
      out[t % num_class] += trees[t].predict_row(row);
    if (average_output && end_tree > start_tree) {
      const double inv =
          static_cast<double>(num_class) / (end_tree - start_tree);
      for (int k = 0; k < num_class; ++k) out[k] *= inv;
    }
    if (predict_type == 1) return;   // raw scores
    if (sqrt_transform) {
      for (int k = 0; k < num_class; ++k)
        out[k] = (out[k] >= 0 ? 1.0 : -1.0) * out[k] * out[k];
      return;
    }
    if (objective == "binary" || objective == "cross_entropy" ||
        objective == "multiclassova") {
      for (int k = 0; k < num_class; ++k)
        out[k] = 1.0 / (1.0 + std::exp(-sigmoid * out[k]));
    } else if (objective == "multiclass") {
      double mx = out[0];
      for (int k = 1; k < num_class; ++k) mx = std::max(mx, out[k]);
      double s = 0.0;
      for (int k = 0; k < num_class; ++k) s += (out[k] = std::exp(out[k] - mx));
      for (int k = 0; k < num_class; ++k) out[k] /= s;
    } else if (objective == "poisson" || objective == "gamma" ||
               objective == "tweedie") {
      for (int k = 0; k < num_class; ++k) out[k] = std::exp(out[k]);
    } else if (objective == "cross_entropy_lambda") {
      for (int k = 0; k < num_class; ++k)
        out[k] = std::log1p(std::exp(out[k]));
    }
  }
};

thread_local std::string g_last_error;

template <typename T, typename F>
std::vector<T> parse_arr(const std::string& s, F conv) {
  std::vector<T> out;
  std::istringstream is(s);
  std::string tok;
  while (is >> tok) out.push_back(conv(tok));
  return out;
}

bool parse_tree(const std::map<std::string, std::string>& kv, CTree* t) {
  auto get = [&](const char* k) -> const std::string& {
    static const std::string empty;
    auto it = kv.find(k);
    return it == kv.end() ? empty : it->second;
  };
  auto to_i = [](const std::string& x) { return (int32_t)std::stol(x); };
  auto to_d = [](const std::string& x) { return std::stod(x); };
  auto to_u8 = [](const std::string& x) { return (uint8_t)std::stoul(x); };
  auto to_u32 = [](const std::string& x) { return (uint32_t)std::stoul(x); };
  t->num_leaves = std::stoi(get("num_leaves"));
  t->split_feature = parse_arr<int32_t>(get("split_feature"), to_i);
  t->threshold = parse_arr<double>(get("threshold"), to_d);
  t->decision_type = parse_arr<uint8_t>(get("decision_type"), to_u8);
  t->left_child = parse_arr<int32_t>(get("left_child"), to_i);
  t->right_child = parse_arr<int32_t>(get("right_child"), to_i);
  t->leaf_value = parse_arr<double>(get("leaf_value"), to_d);
  t->cat_boundaries = parse_arr<int32_t>(get("cat_boundaries"), to_i);
  t->cat_threshold = parse_arr<uint32_t>(get("cat_threshold"), to_u32);
  if ((int)t->leaf_value.size() < t->num_leaves) return false;
  if (get("is_linear") == "1") {
    t->is_linear = true;
    t->leaf_const = parse_arr<double>(get("leaf_const"), to_d);
    auto nf = parse_arr<int32_t>(get("num_features"), to_i);
    t->leaf_feat = parse_arr<int32_t>(get("leaf_features"), to_i);
    t->leaf_coeff = parse_arr<double>(get("leaf_coeff"), to_d);
    t->leaf_feat_off.assign(1, 0);
    for (int32_t n : nf) t->leaf_feat_off.push_back(t->leaf_feat_off.back() + n);
    if ((int)t->leaf_feat_off.size() < t->num_leaves + 1) return false;
  }
  return true;
}

CModel* parse_model(const std::string& text) {
  std::unique_ptr<CModel> m(new CModel());
  std::istringstream is(text);
  std::string line;
  std::map<std::string, std::string> kv;
  bool in_tree = false;
  auto flush_tree = [&]() -> bool {
    if (!in_tree) return true;
    CTree t;
    if (!parse_tree(kv, &t)) return false;
    m->trees.push_back(std::move(t));
    kv.clear();
    return true;
  };
  while (std::getline(is, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.rfind("Tree=", 0) == 0) {
      if (!flush_tree()) return nullptr;
      in_tree = true;
      continue;
    }
    if (line == "end of trees") {
      if (!flush_tree()) return nullptr;
      in_tree = false;
      continue;
    }
    if (line == "average_output") {
      m->average_output = true;
      continue;
    }
    auto eq = line.find('=');
    if (eq == std::string::npos) continue;
    std::string k = line.substr(0, eq), v = line.substr(eq + 1);
    if (in_tree) {
      kv[k] = v;
    } else if (k == "num_class") {
      m->num_class = std::stoi(v);
    } else if (k == "max_feature_idx") {
      m->max_feature_idx = std::stoi(v);
    } else if (k == "objective") {
      std::istringstream ov(v);
      ov >> m->objective;
      std::string tok;
      while (ov >> tok) {
        if (tok.rfind("sigmoid:", 0) == 0)
          m->sigmoid = std::stod(tok.substr(8));
        else if (tok == "sqrt")
          m->sqrt_transform = true;
      }
    }
  }
  if (!flush_tree()) return nullptr;
  if (m->num_class < 1) return nullptr;
  m->model_text = text;
  return m.release();
}

}  // namespace

extern "C" {

typedef void* BoosterHandle;

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  try {
    CModel* m = parse_model(model_str);
    if (m == nullptr) {
      g_last_error = "malformed model string";
      return -1;
    }
    if (out_num_iterations != nullptr)
      *out_num_iterations = (int)(m->trees.size() / m->num_class);
    *out = m;
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  }
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  std::ifstream f(filename);
  if (!f) {
    g_last_error = std::string("cannot open ") + filename;
    return -1;
  }
  std::stringstream ss;
  ss << f.rdbuf();
  return LGBM_BoosterLoadModelFromString(ss.str().c_str(),
                                         out_num_iterations, out);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  delete static_cast<CModel*>(handle);
  return 0;
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out) {
  *out = static_cast<CModel*>(handle)->num_class;
  return 0;
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out) {
  *out = static_cast<CModel*>(handle)->max_feature_idx + 1;
  return 0;
}

namespace {

// Shared matrix-predict core. Signatures of the public entry points below
// match the reference include/LightGBM/c_api.h:1289 / :1327 exactly so a C
// consumer compiling against the real LightGBM header links AND runs
// correctly (data_type C_API_DTYPE_FLOAT32/64, predict_type
// C_API_PREDICT_NORMAL/RAW_SCORE/LEAF_INDEX, start/num_iteration honored,
// *out_len set; `parameter` accepted and ignored).
int predict_mat_impl(BoosterHandle handle, const void* data, int data_type,
                     int32_t nrow, int32_t ncol, int is_row_major,
                     int predict_type, int start_iteration, int num_iteration,
                     int64_t* out_len, double* out_result) {
  const CModel* m = static_cast<const CModel*>(handle);
  if (ncol <= m->max_feature_idx) {
    g_last_error = "matrix has fewer features than the model";
    return -1;
  }
  if (data_type != 0 && data_type != 1) {
    g_last_error = "data_type must be C_API_DTYPE_FLOAT32 or FLOAT64";
    return -1;
  }
  if (predict_type < 0 || predict_type > 2) {
    g_last_error =
        "predict_type must be NORMAL (0), RAW_SCORE (1) or LEAF_INDEX (2); "
        "SHAP contributions are served from Python (models/shap.py)";
    return -1;
  }
  const size_t total_iters = m->trees.size() / m->num_class;
  size_t start = start_iteration < 0 ? 0 : (size_t)start_iteration;
  if (start > total_iters) start = total_iters;
  size_t end = num_iteration <= 0 ? total_iters
                                  : std::min(total_iters,
                                             start + (size_t)num_iteration);
  const size_t start_tree = start * m->num_class;
  const size_t end_tree = end * m->num_class;
  const size_t per_row =
      predict_type == 2 ? (end_tree - start_tree) : (size_t)m->num_class;
  const float* f32 = static_cast<const float*>(data);
  const double* f64 = static_cast<const double*>(data);
  std::vector<double> buf((size_t)ncol);
  for (int32_t r = 0; r < nrow; ++r) {
    const double* row;
    if (data_type == 1 && is_row_major) {
      row = f64 + (int64_t)r * ncol;
    } else {
      for (int32_t c = 0; c < ncol; ++c) {
        const int64_t idx = is_row_major ? (int64_t)r * ncol + c
                                         : (int64_t)c * nrow + r;
        buf[c] = data_type == 0 ? (double)f32[idx] : f64[idx];
      }
      row = buf.data();
    }
    double* out = out_result + (int64_t)r * per_row;
    if (predict_type == 2) {
      for (size_t t = start_tree; t < end_tree; ++t)
        out[t - start_tree] = (double)m->trees[t].leaf_index(row);
    } else {
      m->predict(row, predict_type, out, start_tree, end_tree);
    }
  }
  if (out_len != nullptr) *out_len = (int64_t)nrow * per_row;
  return 0;
}

}  // namespace

// Signature-compatible with reference c_api.h:1327.
int LGBM_BoosterPredictForMatSingleRow(BoosterHandle handle, const void* data,
                                       int data_type, int ncol,
                                       int is_row_major, int predict_type,
                                       int start_iteration, int num_iteration,
                                       const char* /*parameter*/,
                                       int64_t* out_len, double* out_result) {
  return predict_mat_impl(handle, data, data_type, 1, ncol, is_row_major,
                          predict_type, start_iteration, num_iteration,
                          out_len, out_result);
}

// Signature-compatible with reference c_api.h:1289.
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* /*parameter*/, int64_t* out_len,
                              double* out_result) {
  return predict_mat_impl(handle, data, data_type, nrow, ncol, is_row_major,
                          predict_type, start_iteration, num_iteration,
                          out_len, out_result);
}

int LGBM_BoosterGetNumModelPerIteration(BoosterHandle handle, int* out) {
  *out = static_cast<CModel*>(handle)->num_class;
  return 0;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int /*importance_type*/,
                          const char* filename) {
  const CModel* m = static_cast<const CModel*>(handle);
  const int total_iters = static_cast<int>(m->trees.size() / m->num_class);
  if (start_iteration > 0 ||
      (num_iteration > 0 && num_iteration < total_iters)) {
    // loud failure beats silently writing a different model than asked
    g_last_error =
        "this serving-side reader saves the loaded model verbatim; "
        "iteration-range truncation is not supported (re-save from Python)";
    return -1;
  }
  std::ofstream f(filename);
  if (!f) {
    g_last_error = std::string("cannot write ") + filename;
    return -1;
  }
  f << m->model_text;   // the loaded text, verbatim (serving-side reader)
  f.flush();
  if (!f.good()) {
    g_last_error = std::string("write failed for ") + filename;
    return -1;
  }
  return 0;
}

// Signature-compatible with reference c_api.h LGBM_BoosterPredictForFile.
// Parses CSV/TSV (auto-delimiter). Label handling: `parameter` may carry
// "has_label=true" or "has_label=false" to state whether column 0 is a
// label; without it, a file with EXACTLY one more column than the model's
// feature count is treated as the training-file layout (label first).
// When data_has_header=1 the header refines the guess: a label-like first
// column name (label/target/class/y) confirms label-first, a feature-like
// one (Column_*, feat*, f<digit>*) vetoes it. Pass has_label=... to
// override both (documented in README alongside the ABI list).
int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               const char* result_filename) {
  const CModel* m = static_cast<const CModel*>(handle);
  std::ifstream in(data_filename);
  if (!in) {
    g_last_error = std::string("cannot open ") + data_filename;
    return -1;
  }
  std::ofstream outf(result_filename);
  if (!outf) {
    g_last_error = std::string("cannot write ") + result_filename;
    return -1;
  }
  int label_override = -1;             // -1 auto, 0 no label, 1 label
  if (parameter != nullptr) {
    const std::string ps(parameter);
    if (ps.find("has_label=true") != std::string::npos) label_override = 1;
    if (ps.find("has_label=false") != std::string::npos) label_override = 0;
  }
  outf.precision(17);
  std::string line;
  std::string header;
  if (data_has_header) {
    std::getline(in, header);
    if (!header.empty() && header.back() == '\r') header.pop_back();
  }
  std::vector<double> row;
  std::vector<double> out;
  bool first_data_line = true;
  int skip_label = 0;
  while (std::getline(in, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    if (line.empty() || line[0] == '#') continue;
    const char delim = line.find('\t') != std::string::npos ? '\t' : ',';
    row.clear();
    size_t start = 0;
    while (start <= line.size()) {
      size_t end = line.find(delim, start);
      if (end == std::string::npos) end = line.size();
      try {
        row.push_back(std::stod(line.substr(start, end - start)));
      } catch (const std::exception&) {
        row.push_back(std::numeric_limits<double>::quiet_NaN());
      }
      start = end + 1;
      if (end == line.size()) break;
    }
    // a trailing delimiter yields a trailing NaN field, not a column
    if (!line.empty() && line.back() == delim) row.pop_back();
    if (first_data_line) {
      first_data_line = false;
      if (label_override >= 0) {
        skip_label = label_override;
      } else {
        // count heuristic: exactly one column more than the model's feature
        // count reads as the training-file layout (label first)
        skip_label =
            (static_cast<int>(row.size()) == m->max_feature_idx + 2) ? 1 : 0;
        const char* rule = "width-match";
        // a header row is more authoritative than the count: a label-like
        // first column name confirms label-first; a feature-like name in a
        // features+1-wide file means the extra column is a real feature
        if (!header.empty()) {
          size_t hend = header.find(delim);
          std::string h0 = header.substr(
              0, hend == std::string::npos ? header.size() : hend);
          for (auto& c : h0)
            c = static_cast<char>(
                std::tolower(static_cast<unsigned char>(c)));
          // confirm only when the file is actually wider than the model:
          // an exact-width file whose first FEATURE happens to be named
          // "y"/"label" must keep all its columns
          if ((h0 == "label" || h0 == "target" || h0 == "class" ||
               h0 == "y") &&
              static_cast<int>(row.size()) > m->max_feature_idx + 1) {
            skip_label = 1;
            rule = "header-label-name";
          } else if (skip_label == 1 &&
                     (h0.rfind("column_", 0) == 0 ||
                      h0.rfind("feat", 0) == 0 ||
                      (h0.size() >= 2 && h0[0] == 'f' &&
                       std::isdigit(static_cast<unsigned char>(h0[1]))))) {
            skip_label = 0;
            rule = "header-feature-name";
          }
        }
        // heuristics silently changing column handling across files is
        // undiagnosable otherwise; has_label= overrides both rules
        std::fprintf(stderr,
                     "[lambdagap] PredictForFile: column-0 rule '%s' -> %s "
                     "(%d columns, model needs %d)\n",
                     rule,
                     skip_label ? "dropping column 0 as the label"
                                : "keeping every column as a feature",
                     static_cast<int>(row.size()), m->max_feature_idx + 1);
      }
    }
    if (static_cast<int>(row.size()) - skip_label <= m->max_feature_idx) {
      g_last_error = "row has fewer features than the model";
      return -1;
    }
    int64_t out_len = 0;
    out.assign(predict_type == 2 ? m->trees.size()
                                 : (size_t)m->num_class, 0.0);
    int rc = predict_mat_impl(handle, row.data() + skip_label, 1, 1,
                              static_cast<int32_t>(row.size() - skip_label),
                              1, predict_type, start_iteration,
                              num_iteration, &out_len, out.data());
    if (rc != 0) return rc;
    for (int64_t k = 0; k < out_len; ++k) {
      if (k) outf << '\t';
      outf << out[(size_t)k];
    }
    outf << '\n';
  }
  outf.flush();
  if (!outf.good()) {
    g_last_error = std::string("write failed for ") + result_filename;
    return -1;
  }
  return 0;
}

}  // extern "C"
