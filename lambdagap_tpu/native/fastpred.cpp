// Low-latency host forest traversal for small prediction batches.
//
// The TPU batched predictor (ops/predict.py) amortizes a jit dispatch over
// thousands of rows; a serving-style call with 1..few hundred rows pays the
// ~ms dispatch + transfer for microseconds of work. This is the analog of
// the reference's thread-safe single-row fast predictor
// (reference: src/c_api.cpp:63 SingleRowPredictorInner +
// include/LightGBM/tree.h:130-141 Predict/Decision): read-only flat arrays,
// no allocation, safe for concurrent callers.
//
// Decision semantics mirror models/tree.py Tree._decision exactly:
//   numerical: NaN with missing_type != NaN is treated as 0.0; missing
//     (NaN-missing NaN, or Zero-missing |v| <= 1e-35) routes default_left;
//     otherwise v <= threshold goes left. Thresholds arrive as f32 (the
//     device path compares f32), values are f32 — compares are exact.
//   categorical: NaN goes right; bit `cat` of the node's raw-category
//     bitset decides.
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

inline bool go_left(float fv, float thr, uint8_t dl, uint8_t mt,
                    uint8_t is_cat, const uint32_t* bits, int32_t nwords) {
  if (is_cat) {
    if (std::isnan(fv)) return false;
    int64_t cat = static_cast<int64_t>(fv);
    if (cat < 0 || cat >= static_cast<int64_t>(nwords) * 32) return false;
    return (bits[cat >> 5] >> (cat & 31)) & 1u;
  }
  double v = fv;
  if (std::isnan(v) && mt != 2) v = 0.0;
  if ((mt == 2 && std::isnan(v)) || (mt == 1 && std::fabs(v) <= 1e-35))
    return dl != 0;
  return v <= static_cast<double>(thr);
}

}  // namespace

extern "C" {

// Flat forest: nodes/leaves concatenated per tree via tree_node_off /
// tree_leaf_off; child pointers are tree-local (>=0 node, <0 ~leaf).
// out[n_class] per row accumulates raw scores (caller zero-initializes).
void lg_fast_predict(
    int64_t n_trees, const int64_t* tree_node_off,
    const int64_t* tree_leaf_off, const int32_t* feat, const float* thr,
    const uint8_t* default_left, const uint8_t* missing_type,
    const uint8_t* is_cat, const int64_t* cat_off, const int32_t* cat_len,
    const uint32_t* cat_bits, const int32_t* left, const int32_t* right,
    const double* leaf_val, const int32_t* tree_class, int64_t n_class,
    const float* X, int64_t n_rows, int64_t n_cols, double* out) {
  auto run_rows = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      const float* row = X + r * n_cols;
      double* orow = out + r * n_class;
      for (int64_t t = 0; t < n_trees; ++t) {
        const int64_t n0 = tree_node_off[t];
        int64_t leaf = 0;
        if (tree_node_off[t + 1] > n0) {
          int32_t node = 0;
          while (node >= 0) {
            const int64_t g = n0 + node;
            bool gl = go_left(row[feat[g]], thr[g], default_left[g],
                              missing_type[g], is_cat[g],
                              cat_bits + cat_off[g], cat_len[g]);
            node = gl ? left[g] : right[g];
          }
          leaf = ~node;
        }
        orow[tree_class[t]] += leaf_val[tree_leaf_off[t] + leaf];
      }
    }
  };
  // rows are independent: block-parallel for larger batches (the
  // reference's predictor parallelizes with OpenMP; std::thread here)
  const int64_t kMinRowsPerThread = 1024;
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int n_threads = static_cast<int>(
      std::min<int64_t>(std::max(hw, 1), n_rows / kMinRowsPerThread));
  if (n_threads <= 1) {
    run_rows(0, n_rows);
    return;
  }
  std::vector<std::thread> workers;
  const int64_t step = (n_rows + n_threads - 1) / n_threads;
  for (int w = 0; w < n_threads; ++w) {
    const int64_t lo = w * step;
    const int64_t hi = std::min(n_rows, lo + step);
    if (lo < hi) workers.emplace_back(run_rows, lo, hi);
  }
  for (auto& th : workers) th.join();
}

}  // extern "C"
