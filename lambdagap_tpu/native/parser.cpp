// Native text parsers for lambdagap_tpu.
//
// TPU-native equivalent of the reference's C++ data-path host code
// (reference: src/io/parser.cpp CSV/TSV/LibSVM parsers + DatasetLoader's
// two-pass text ingestion, src/io/dataset_loader.cpp:203). Python-side
// loading would be the "slow pure-Python" path SURVEY.md §2 forbids for
// performance-critical IO; this file is compiled once with g++ and loaded
// via ctypes (no pybind dependency).
//
// Exposed C ABI:
//   lg_count_libsvm(path, &rows, &max_feature) -> 0/err
//   lg_parse_libsvm(path, out_matrix, out_label, out_qid, rows, cols) -> 0/err
//     out_matrix is rows*cols row-major float64, pre-filled by caller
//     (absent features stay at the fill value, i.e. 0 for sparse semantics);
//     out_qid is rows int64 (LETOR ``qid:N`` tokens; stays at the caller's
//     fill when a line has no qid). Any other non-``idx:val`` token is a
//     format error (rc=3) — the reference Log::Fatal's on malformed LibSVM
//     (src/io/parser.cpp).
//   lg_count_delim(path, delim, skip_header, &rows, &cols)
//   lg_parse_delim(path, delim, skip_header, out_matrix, rows, cols)

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <cstdint>
#include <vector>

namespace {

// fast locale-independent strtod wrapper; handles na/nan/inf tokens the way
// the reference's Atof does (src/include/LightGBM/utils/common.h Atof)
static inline double parse_double(const char* p, char** end) {
  while (*p == ' ' || *p == '\t') ++p;
  if ((p[0] == 'n' || p[0] == 'N') && (p[1] == 'a' || p[1] == 'A')) {
    *end = const_cast<char*>(p + 2);
    if (**end == 'n' || **end == 'N') ++*end;
    return NAN;
  }
  return strtod(p, end);
}

struct LineReader {
  FILE* f;
  std::vector<char> buf;
  explicit LineReader(const char* path) : f(fopen(path, "rb")), buf(1 << 16) {}
  ~LineReader() { if (f) fclose(f); }
  bool ok() const { return f != nullptr; }
  // reads one line (arbitrary length); returns nullptr at EOF
  char* next() {
    if (!fgets(buf.data(), static_cast<int>(buf.size()), f)) return nullptr;
    size_t len = strlen(buf.data());
    while (len > 0 && buf[len - 1] != '\n' && !feof(f)) {
      buf.resize(buf.size() * 2);
      if (!fgets(buf.data() + len, static_cast<int>(buf.size() - len), f)) break;
      len = strlen(buf.data());
    }
    return buf.data();
  }
};

// true if p points at a LETOR "qid:" token; advances *out past "qid:"
static inline bool is_qid_token(const char* p, const char** out) {
  if ((p[0] == 'q' || p[0] == 'Q') && (p[1] == 'i' || p[1] == 'I') &&
      (p[2] == 'd' || p[2] == 'D') && p[3] == ':') {
    *out = p + 4;
    return true;
  }
  return false;
}

}  // namespace

extern "C" {

int lg_count_libsvm(const char* path, int64_t* rows, int64_t* max_feature) {
  LineReader r(path);
  if (!r.ok()) return 1;
  int64_t n = 0, maxf = -1;
  char* line;
  while ((line = r.next()) != nullptr) {
    if (line[0] == '\n' || line[0] == '\0' || line[0] == '#') continue;
    ++n;
    const char* p = line;
    // skip label
    char* end;
    strtod(p, &end);
    p = end;
    while (*p) {
      while (*p == ' ' || *p == '\t') ++p;
      if (*p == '\n' || *p == '\0' || *p == '\r') break;
      const char* after_qid;
      if (is_qid_token(p, &after_qid)) {
        strtol(after_qid, &end, 10);
        p = end;
        continue;
      }
      char* colon = nullptr;
      long idx = strtol(p, &colon, 10);
      if (colon == p || *colon != ':') return 3;  // malformed token
      if (idx > maxf) maxf = idx;
      p = colon + 1;
      strtod(p, &end);
      p = end;
    }
  }
  *rows = n;
  *max_feature = maxf;
  return 0;
}

int lg_parse_libsvm(const char* path, double* out, double* label,
                    int64_t* qid, int64_t rows, int64_t cols) {
  LineReader r(path);
  if (!r.ok()) return 1;
  int64_t i = 0;
  char* line;
  while ((line = r.next()) != nullptr && i < rows) {
    if (line[0] == '\n' || line[0] == '\0' || line[0] == '#') continue;
    char* end;
    label[i] = parse_double(line, &end);
    const char* p = end;
    double* row = out + i * cols;
    while (*p) {
      while (*p == ' ' || *p == '\t') ++p;
      if (*p == '\n' || *p == '\0' || *p == '\r') break;
      const char* after_qid;
      if (is_qid_token(p, &after_qid)) {
        long q = strtol(after_qid, &end, 10);
        if (qid != nullptr) qid[i] = q;
        p = end;
        continue;
      }
      char* colon = nullptr;
      long idx = strtol(p, &colon, 10);
      if (colon == p || *colon != ':') return 3;  // malformed token
      p = colon + 1;
      double v = parse_double(p, &end);
      p = end;
      if (idx >= 0 && idx < cols) row[idx] = v;
    }
    ++i;
  }
  return i == rows ? 0 : 2;
}

int lg_count_delim(const char* path, char delim, int skip_header,
                   int64_t* rows, int64_t* cols) {
  LineReader r(path);
  if (!r.ok()) return 1;
  int64_t n = 0, c = 0;
  char* line;
  int first = 1;
  while ((line = r.next()) != nullptr) {
    if (line[0] == '\n' || line[0] == '\0') continue;
    if (skip_header && first) { first = 0; continue; }
    first = 0;
    if (c == 0) {
      c = 1;
      for (const char* p = line; *p && *p != '\n'; ++p)
        if (*p == delim) ++c;
    }
    ++n;
  }
  *rows = n;
  *cols = c;
  return 0;
}

int lg_parse_delim(const char* path, char delim, int skip_header,
                   double* out, int64_t rows, int64_t cols) {
  LineReader r(path);
  if (!r.ok()) return 1;
  int64_t i = 0;
  char* line;
  int first = 1;
  while ((line = r.next()) != nullptr && i < rows) {
    if (line[0] == '\n' || line[0] == '\0') continue;
    if (skip_header && first) { first = 0; continue; }
    first = 0;
    const char* p = line;
    double* row = out + i * cols;
    for (int64_t j = 0; j < cols; ++j) {
      char* end;
      row[j] = parse_double(p, &end);
      if (end == p && *p != delim) {  // empty / non-numeric field -> NaN
        row[j] = NAN;
      }
      p = end;
      while (*p && *p != delim && *p != '\n') ++p;
      if (*p == delim) ++p;
    }
    ++i;
  }
  return i == rows ? 0 : 2;
}

}  // extern "C"
