// TreeSHAP path-attribution (native, called via ctypes).
//
// Re-implementation of the reference's Tree::TreeSHAP recursion
// (reference: src/io/tree.cpp TreeSHAP / include/LightGBM/tree.h
// PredictContrib): the Lundberg unique-path algorithm, O(depth^2 * leaves)
// per row, with the reference's decision semantics (NaN/zero missing,
// categorical bitsets).
//
// Flat-array tree layout matches lambdagap_tpu.models.tree.Tree: child
// pointers >= 0 are internal nodes, < 0 encode ~leaf_index.
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct PathElem {
  int feature_index;
  double zero_fraction;
  double one_fraction;
  double pweight;
};

struct TreeView {
  int64_t num_internal;
  const int32_t* split_feature;
  const double* threshold;
  const uint8_t* default_left;
  const int32_t* missing_type;  // 0 none, 1 zero, 2 nan
  const int32_t* left;
  const int32_t* right;
  const uint8_t* is_cat;
  const uint32_t* cat_bits;     // concatenated bitset words
  const int64_t* cat_offs;      // [num_internal+1] word offsets
  const double* internal_value;
  const double* internal_count;
  const double* leaf_value;
  const double* leaf_count;
};

const double kZeroThreshold = 1e-35;

inline double node_cover(const TreeView& t, int node) {
  return node >= 0 ? t.internal_count[node] : t.leaf_count[~node];
}

inline bool decide_left(const TreeView& t, int node, const double* row) {
  double v = row[t.split_feature[node]];
  if (t.is_cat[node]) {
    if (std::isnan(v)) return false;
    int64_t c = static_cast<int64_t>(v);
    if (c < 0) return false;
    int64_t w0 = t.cat_offs[node], w1 = t.cat_offs[node + 1];
    int64_t word = c / 32;
    if (word >= w1 - w0) return false;
    return (t.cat_bits[w0 + word] >> (c % 32)) & 1u;
  }
  int mt = t.missing_type[node];
  if (std::isnan(v) && mt != 2) v = 0.0;
  if ((mt == 2 && std::isnan(v)) || (mt == 1 && std::fabs(v) <= kZeroThreshold))
    return t.default_left[node];
  return v <= t.threshold[node];
}

void extend_path(PathElem* path, int depth, double zero_fraction,
                 double one_fraction, int feature_index) {
  path[depth] = {feature_index, zero_fraction, one_fraction,
                 depth == 0 ? 1.0 : 0.0};
  for (int i = depth - 1; i >= 0; --i) {
    path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) /
                           static_cast<double>(depth + 1);
    path[i].pweight = zero_fraction * path[i].pweight * (depth - i) /
                      static_cast<double>(depth + 1);
  }
}

void unwind_path(PathElem* path, int depth, int path_index) {
  const double one_fraction = path[path_index].one_fraction;
  const double zero_fraction = path[path_index].zero_fraction;
  double next_one_portion = path[depth].pweight;
  for (int i = depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      const double tmp = path[i].pweight;
      path[i].pweight = next_one_portion * (depth + 1) /
                        ((i + 1) * one_fraction);
      next_one_portion = tmp - path[i].pweight * zero_fraction * (depth - i) /
                         static_cast<double>(depth + 1);
    } else {
      path[i].pweight = path[i].pweight * (depth + 1) /
                        (zero_fraction * (depth - i));
    }
  }
  for (int i = path_index; i < depth; ++i) {
    path[i].feature_index = path[i + 1].feature_index;
    path[i].zero_fraction = path[i + 1].zero_fraction;
    path[i].one_fraction = path[i + 1].one_fraction;
  }
}

double unwound_path_sum(const PathElem* path, int depth,
                        int path_index) {
  const double one_fraction = path[path_index].one_fraction;
  const double zero_fraction = path[path_index].zero_fraction;
  double next_one_portion = path[depth].pweight;
  double total = 0;
  for (int i = depth - 1; i >= 0; --i) {
    if (one_fraction != 0) {
      const double tmp = next_one_portion * (depth + 1) /
                         ((i + 1) * one_fraction);
      total += tmp;
      next_one_portion = path[i].pweight - tmp * zero_fraction * (depth - i) /
                         static_cast<double>(depth + 1);
    } else {
      total += path[i].pweight / (zero_fraction * (depth - i) /
                                  static_cast<double>(depth + 1));
    }
  }
  return total;
}

// parent_path points into a per-row arena (reference layout: each depth
// level gets its own copy window, tree.cpp Tree::TreeSHAP) — no allocator
// traffic in the hot recursion.
void shap_rec(const TreeView& t, const double* row, double* phi, int node,
              int depth, PathElem* parent_path, double parent_zero_fraction,
              double parent_one_fraction, int parent_feature_index) {
  PathElem* path = parent_path + depth;
  std::memcpy(path, parent_path, sizeof(PathElem) * depth);
  extend_path(path, depth, parent_zero_fraction, parent_one_fraction,
              parent_feature_index);
  if (node < 0) {  // leaf
    const double v = t.leaf_value[~node];
    for (int i = 1; i <= depth; ++i) {
      const double w = unwound_path_sum(path, depth, i);
      phi[path[i].feature_index] +=
          w * (path[i].one_fraction - path[i].zero_fraction) * v;
    }
    return;
  }
  const int hot = decide_left(t, node, row) ? t.left[node] : t.right[node];
  const int cold = decide_left(t, node, row) ? t.right[node] : t.left[node];
  const double w = node_cover(t, node);
  const double hot_zero_fraction = node_cover(t, hot) / w;
  const double cold_zero_fraction = node_cover(t, cold) / w;
  double incoming_zero_fraction = 1.0;
  double incoming_one_fraction = 1.0;

  // undo any previous split on the same feature along this path
  int f = t.split_feature[node];
  int path_index = 0;
  for (; path_index <= depth; ++path_index)
    if (path[path_index].feature_index == f) break;
  if (path_index != depth + 1) {
    incoming_zero_fraction = path[path_index].zero_fraction;
    incoming_one_fraction = path[path_index].one_fraction;
    unwind_path(path, depth, path_index);
    depth -= 1;
  }
  shap_rec(t, row, phi, hot, depth + 1, path,
           hot_zero_fraction * incoming_zero_fraction, incoming_one_fraction,
           f);
  shap_rec(t, row, phi, cold, depth + 1, path,
           cold_zero_fraction * incoming_zero_fraction, 0.0, f);
}

}  // namespace

extern "C" {

// Accumulate one tree's SHAP values for all rows into phi [N, F+1]
// (last column receives the tree's expected value).
void lg_tree_shap(int64_t num_internal, const int32_t* split_feature,
                  const double* threshold, const uint8_t* default_left,
                  const int32_t* missing_type, const int32_t* left,
                  const int32_t* right, const uint8_t* is_cat,
                  const uint32_t* cat_bits, const int64_t* cat_offs,
                  const double* internal_value, const double* internal_count,
                  const double* leaf_value, const double* leaf_count,
                  const double* X, int64_t n_rows, int64_t n_features,
                  double* phi) {
  TreeView t{num_internal, split_feature, threshold,    default_left,
             missing_type, left,          right,        is_cat,
             cat_bits,     cat_offs,      internal_value, internal_count,
             leaf_value,   leaf_count};
  // cover-weighted mean of leaf outputs: the recursion's phi sums to
  // f(x) - E_cover[f], so this exact E keeps sum(contribs) == prediction
  // (reference: Tree::ExpectedValue, include/LightGBM/tree.h)
  double expected = leaf_value[0];
  if (num_internal > 0) {
    double num = 0, den = 0;
    for (int64_t l = 0; l <= num_internal; ++l) {
      num += leaf_value[l] * leaf_count[l];
      den += leaf_count[l];
    }
    expected = den > 0 ? num / den : 0.0;
  }
  // one arena reused across rows: level d starts at offset
  // d*(d+1)/2 <= (D+1)(D+2)/2 elements for max depth D <= num_internal
  const int64_t max_d = num_internal + 2;
  std::vector<PathElem> arena((max_d + 1) * (max_d + 2) / 2);
  for (int64_t r = 0; r < n_rows; ++r) {
    double* phi_r = phi + r * (n_features + 1);
    phi_r[n_features] += expected;
    if (num_internal == 0) continue;
    shap_rec(t, X + r * n_features, phi_r, 0, 0, arena.data(), 1.0, 1.0, -1);
  }
}

}  // extern "C"
