from .base import ObjectiveFunction, create_objective, register_objective
from . import regression, binary, multiclass, xentropy, rank  # noqa: F401 — register

__all__ = ["ObjectiveFunction", "create_objective", "register_objective"]
