"""Objective function interface + factory.

TPU analog of the reference's ``ObjectiveFunction`` + ``CreateObjectiveFunction``
(reference: include/LightGBM/objective_function.h:19,98,
src/objective/objective_function.cpp:20-108). Objectives hold device-resident
label/weight arrays and expose a jit-compiled gradient computation; scores are
laid out class-major ``[K, N]`` like the reference's flat ``score[class*N+i]``.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import Metadata
from ..utils import log

K_EPSILON = 1e-15


class ObjectiveFunction:
    name = "base"
    num_model_per_iteration = 1

    def __init__(self, config: Config) -> None:
        self.config = config
        self.num_data = 0
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None
        self.label_np: Optional[np.ndarray] = None
        self.weight_np: Optional[np.ndarray] = None

    # -- lifecycle -----------------------------------------------------
    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        if metadata.label is None:
            log.fatal("Objective %s requires labels", self.name)
        self.label_np = np.asarray(metadata.label, dtype=np.float32)
        self.label = jnp.asarray(self.label_np)
        if metadata.weight is not None:
            self.weight_np = np.asarray(metadata.weight, dtype=np.float32)
            self.weight = jnp.asarray(self.weight_np)

    # -- core ----------------------------------------------------------
    def get_gradients(self, scores: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """scores: [K, N] -> (grad, hess) each [K, N]."""
        raise NotImplementedError

    # jnp-array attributes read by get_gradients; subclasses declare them
    # so the jitted wrapper can pass them as ARGUMENTS (closing over device
    # arrays would inline them into the HLO as constants — at 10M rows that
    # payload breaks the remote-compile transport, see fused_learner notes)
    _GRAD_ARRAY_FIELDS: Tuple[str, ...] = ()

    def get_gradients_fast(self, scores: jax.Array
                           ) -> Tuple[jax.Array, jax.Array]:
        """Jitted gradient computation for the boosting loop: eager
        ``get_gradients`` pays one dispatch per jnp op, which at ~1 ms per
        op over a remote-device link dwarfs the arithmetic. Falls back to
        the eager path for objectives that don't declare their array
        fields (e.g. the rank family, which jits internally)."""
        fields = tuple(f for f in self._GRAD_ARRAY_FIELDS
                       if getattr(self, f, None) is not None)
        if not fields:
            return self.get_gradients(scores)
        if getattr(self, "_grad_jit", None) is None:
            def fn(scores, *arrs):
                saved = [getattr(self, f) for f in fields]
                for f, a in zip(fields, arrs):
                    setattr(self, f, a)
                try:
                    return self.get_gradients(scores)
                finally:
                    for f, s in zip(fields, saved):
                        setattr(self, f, s)
            self._grad_jit = jax.jit(fn)
        return self._grad_jit(scores, *[getattr(self, f) for f in fields])

    def boost_from_score(self, class_id: int) -> float:
        """Initial score (reference: BoostFromScore per objective)."""
        return 0.0

    def convert_output(self, scores: jax.Array) -> jax.Array:
        """Raw score -> output space (e.g. sigmoid/exp/softmax)."""
        return scores

    def convert_output_np(self, scores):
        """Host (numpy) transform for serving-size batches — must match
        ``convert_output`` (the fast-predict path avoids any device
        dispatch, like the reference's single-row predictor). The default
        delegates to the jax version so a subclass overriding only
        ``convert_output`` can never diverge; subclasses with non-identity
        transforms provide a pure-numpy override."""
        if type(self).convert_output is ObjectiveFunction.convert_output:
            return scores
        import numpy as _np
        return _np.asarray(jax.device_get(
            self.convert_output(jax.numpy.asarray(scores))))

    # -- leaf renewal (L1 family) ---------------------------------------
    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def renew_tree_output(self, leaf_rows: np.ndarray, score: np.ndarray) -> float:
        """Recompute one leaf's output from its rows (host-side; reference:
        RenewTreeOutput with residual_getter + weighted percentile)."""
        raise NotImplementedError

    # -- misc ----------------------------------------------------------
    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def num_class(self) -> int:
        return 1

    def to_string(self) -> str:
        return self.name


_REGISTRY: Dict[str, Type[ObjectiveFunction]] = {}


def register_objective(cls: Type[ObjectiveFunction]) -> Type[ObjectiveFunction]:
    _REGISTRY[cls.name] = cls
    return cls


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """(reference: ObjectiveFunction::CreateObjectiveFunction,
    src/objective/objective_function.cpp:20)"""
    name = config.objective
    if name == "none":
        return None
    if name not in _REGISTRY:
        log.fatal("Unknown objective: %s", name)
    return _REGISTRY[name](config)


def weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                        alpha: float) -> float:
    """Weighted percentile matching the reference's PercentileFun /
    WeightedPercentileFun (reference: src/objective/regression_objective.hpp:23-87)."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        pos = alpha * (n - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        return float(v[lo] * (1 - frac) + v[hi] * frac)
    w = weights[order].astype(np.float64)
    cum = np.cumsum(w) - w[0]
    total = float(np.sum(w))
    threshold = alpha * (total - w[0])
    idx = int(np.searchsorted(cum, threshold, side="right")) - 1
    idx = max(0, min(idx, n - 2))
    if cum[idx + 1] - cum[idx] > 0:
        frac = (threshold - cum[idx]) / (cum[idx + 1] - cum[idx])
    else:
        frac = 0.0
    return float(v[idx] * (1 - frac) + v[idx + 1] * frac)


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "ObjectiveFunction.get_gradients_fast.fn", collective_free=True,
    notes="jitted gradient wrapper shared by the array-field objectives; "
          "one trace per boosting run")
