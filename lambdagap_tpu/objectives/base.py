"""Objective function interface + factory.

TPU analog of the reference's ``ObjectiveFunction`` + ``CreateObjectiveFunction``
(reference: include/LightGBM/objective_function.h:19,98,
src/objective/objective_function.cpp:20-108). Objectives hold device-resident
label/weight arrays and expose a jit-compiled gradient computation; scores are
laid out class-major ``[K, N]`` like the reference's flat ``score[class*N+i]``.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import Metadata
from ..utils import log

K_EPSILON = 1e-15


class ObjectiveFunction:
    name = "base"
    num_model_per_iteration = 1

    def __init__(self, config: Config) -> None:
        self.config = config
        self.num_data = 0
        self.label: Optional[jax.Array] = None
        self.weight: Optional[jax.Array] = None
        self.label_np: Optional[np.ndarray] = None
        self.weight_np: Optional[np.ndarray] = None

    # -- lifecycle -----------------------------------------------------
    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        if metadata.label is None:
            log.fatal("Objective %s requires labels", self.name)
        self.label_np = np.asarray(metadata.label, dtype=np.float32)
        self.label = jnp.asarray(self.label_np)
        if metadata.weight is not None:
            self.weight_np = np.asarray(metadata.weight, dtype=np.float32)
            self.weight = jnp.asarray(self.weight_np)

    # -- core ----------------------------------------------------------
    def get_gradients(self, scores: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """scores: [K, N] -> (grad, hess) each [K, N]."""
        raise NotImplementedError

    def boost_from_score(self, class_id: int) -> float:
        """Initial score (reference: BoostFromScore per objective)."""
        return 0.0

    def convert_output(self, scores: jax.Array) -> jax.Array:
        """Raw score -> output space (e.g. sigmoid/exp/softmax)."""
        return scores

    # -- leaf renewal (L1 family) ---------------------------------------
    @property
    def is_renew_tree_output(self) -> bool:
        return False

    def renew_tree_output(self, leaf_rows: np.ndarray, score: np.ndarray) -> float:
        """Recompute one leaf's output from its rows (host-side; reference:
        RenewTreeOutput with residual_getter + weighted percentile)."""
        raise NotImplementedError

    # -- misc ----------------------------------------------------------
    @property
    def is_constant_hessian(self) -> bool:
        return False

    @property
    def num_class(self) -> int:
        return 1

    def to_string(self) -> str:
        return self.name


_REGISTRY: Dict[str, Type[ObjectiveFunction]] = {}


def register_objective(cls: Type[ObjectiveFunction]) -> Type[ObjectiveFunction]:
    _REGISTRY[cls.name] = cls
    return cls


def create_objective(config: Config) -> Optional[ObjectiveFunction]:
    """(reference: ObjectiveFunction::CreateObjectiveFunction,
    src/objective/objective_function.cpp:20)"""
    name = config.objective
    if name == "none":
        return None
    if name not in _REGISTRY:
        log.fatal("Unknown objective: %s", name)
    return _REGISTRY[name](config)


def weighted_percentile(values: np.ndarray, weights: Optional[np.ndarray],
                        alpha: float) -> float:
    """Weighted percentile matching the reference's PercentileFun /
    WeightedPercentileFun (reference: src/objective/regression_objective.hpp:23-87)."""
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    order = np.argsort(values)
    v = values[order]
    if weights is None:
        pos = alpha * (n - 1)
        lo = int(np.floor(pos))
        hi = min(lo + 1, n - 1)
        frac = pos - lo
        return float(v[lo] * (1 - frac) + v[hi] * frac)
    w = weights[order].astype(np.float64)
    cum = np.cumsum(w) - w[0]
    total = float(np.sum(w))
    threshold = alpha * (total - w[0])
    idx = int(np.searchsorted(cum, threshold, side="right")) - 1
    idx = max(0, min(idx, n - 2))
    if cum[idx + 1] - cum[idx] > 0:
        frac = (threshold - cum[idx]) / (cum[idx + 1] - cum[idx])
    else:
        frac = 0.0
    return float(v[idx] * (1 - frac) + v[idx + 1] * frac)
