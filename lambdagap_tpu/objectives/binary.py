"""Binary classification objective.

(reference: src/objective/binary_objective.hpp BinaryLogloss — sigmoid-scaled
logistic loss with unbalanced-label weighting and scale_pos_weight.)
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..config import Config
from ..utils import log
from .base import K_EPSILON, ObjectiveFunction, register_objective


@register_objective
class BinaryLogloss(ObjectiveFunction):
    name = "binary"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.is_unbalance = config.is_unbalance
        self.scale_pos_weight = config.scale_pos_weight
        self.need_train = True

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        y = self.label_np
        if not np.all((y == 0) | (y == 1)):
            log.fatal("[binary]: labels must be 0 or 1")
        cnt_pos = int(np.sum(y == 1))
        cnt_neg = num_data - cnt_pos
        self.cnt_pos, self.cnt_neg = cnt_pos, cnt_neg
        if cnt_pos == 0 or cnt_neg == 0:
            log.warning("[binary]: contains only one class")
            self.need_train = False
        # label weights (reference: binary_objective.hpp:85-101)
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        self.w_pos, self.w_neg = w_pos, w_neg
        self.label_signed = jnp.asarray(np.where(y == 1, 1.0, -1.0).astype(np.float32))
        lw = np.where(y == 1, w_pos, w_neg).astype(np.float32)
        if self.weight_np is not None:
            lw = lw * self.weight_np
        self.label_weight = jnp.asarray(lw)

    _GRAD_ARRAY_FIELDS = ("label_signed", "label_weight")

    def get_gradients(self, scores):
        """(reference: binary_objective.hpp:105-134)"""
        s = self.sigmoid
        ls = self.label_signed[None, :]
        response = -ls * s / (1.0 + jnp.exp(ls * s * scores))
        abs_r = jnp.abs(response)
        grad = response * self.label_weight[None, :]
        hess = abs_r * (s - abs_r) * self.label_weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        """(reference: binary_objective.hpp:139-164)"""
        if not self.config.boost_from_average or not self.need_train:
            return 0.0
        if self.weight_np is not None:
            suml = float(np.sum((self.label_np == 1) * self.weight_np))
            sumw = float(np.sum(self.weight_np))
        else:
            suml = float(np.sum(self.label_np == 1))
            sumw = float(self.num_data)
        pavg = min(max(suml / max(sumw, K_EPSILON), K_EPSILON), 1.0 - K_EPSILON)
        init = np.log(pavg / (1.0 - pavg)) / self.sigmoid
        log.info("[binary:BoostFromScore]: pavg=%.6f -> initscore=%.6f", pavg, init)
        return float(init)

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * scores))

    def convert_output_np(self, scores):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * scores))

    @property
    def is_constant_hessian(self) -> bool:
        return False
