"""Multiclass objectives: softmax and one-vs-all.

(reference: src/objective/multiclass_objective.hpp MulticlassSoftmax with the
K/(K-1) hessian rescale factor, MulticlassOVA wrapping per-class BinaryLogloss.)
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..config import Config
from ..utils import log
from .base import K_EPSILON, ObjectiveFunction, register_objective
from .binary import BinaryLogloss


@register_objective
class MulticlassSoftmax(ObjectiveFunction):
    name = "multiclass"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self._num_class = config.num_class
        if self._num_class < 2:
            log.fatal("[multiclass]: num_class must be >= 2, got %d", self._num_class)
        self.factor = self._num_class / (self._num_class - 1.0)

    @property
    def num_class(self) -> int:
        return self._num_class

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        y = self.label_np.astype(np.int32)
        if np.any((y < 0) | (y >= self._num_class)):
            log.fatal("[multiclass]: label must be in [0, num_class)")
        self.label_int = jnp.asarray(y)
        # class priors for init score (reference: multiclass_objective.hpp:56-76)
        probs = np.zeros(self._num_class)
        for k in range(self._num_class):
            if self.weight_np is not None:
                probs[k] = np.sum((y == k) * self.weight_np) / np.sum(self.weight_np)
            else:
                probs[k] = np.mean(y == k)
        self.class_init_probs = probs

    _GRAD_ARRAY_FIELDS = ("label_int", "weight")

    def get_gradients(self, scores):
        """scores [K, N] -> softmax over K
        (reference: multiclass_objective.hpp:85-130)."""
        p = _softmax0(scores)
        onehot = (jnp.arange(self._num_class, dtype=jnp.int32)[:, None]
                  == self.label_int[None, :])
        grad = p - onehot.astype(p.dtype)
        hess = self.factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        if not self.config.boost_from_average:
            return 0.0
        return float(np.log(max(K_EPSILON, self.class_init_probs[class_id])))

    def convert_output(self, scores):
        return _softmax0(scores)

    def convert_output_np(self, scores):
        m = scores - np.max(scores, axis=0, keepdims=True)
        e = np.exp(m)
        return e / np.sum(e, axis=0, keepdims=True)


def _softmax0(scores):
    m = jnp.max(scores, axis=0, keepdims=True)
    e = jnp.exp(scores - m)
    return e / jnp.sum(e, axis=0, keepdims=True)


@register_objective
class MulticlassOVA(ObjectiveFunction):
    """One-vs-all: K independent sigmoid classifiers
    (reference: multiclass_objective.hpp:180-270)."""
    name = "multiclassova"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self._num_class = config.num_class
        self.sigmoid = config.sigmoid
        self.binary = [BinaryLogloss(config) for _ in range(self._num_class)]

    @property
    def num_class(self) -> int:
        return self._num_class

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        from ..data.dataset import Metadata as MD
        y = self.label_np.astype(np.int32)
        for k in range(self._num_class):
            md_k = MD(label=(y == k).astype(np.float32), weight=self.weight_np)
            self.binary[k].init(md_k, num_data)

    def get_gradients(self, scores):
        grads, hesses = [], []
        for k in range(self._num_class):
            g, h = self.binary[k].get_gradients(scores[k][None, :])
            grads.append(g[0])
            hesses.append(h[0])
        return jnp.stack(grads), jnp.stack(hesses)

    def boost_from_score(self, class_id: int) -> float:
        return self.binary[class_id].boost_from_score(0)

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-self.sigmoid * scores))

    def convert_output_np(self, scores):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * scores))
