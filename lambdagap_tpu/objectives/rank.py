"""Ranking objectives: the extended LambdaRank family and RankXENDCG.

This is the fork's namesake delta: ``lambdarank_target`` selects one of 18
pairwise gradient targets — ranknet / bin-ranknet / ndcg / bndcg /
lambdaloss-{ndcg,bndcg}[-plus-plus] / precision / arpk /
lambdaloss-arp{1,2} / lambdagap-{s,x}[-plus[-plus]] — with the
``lambdagap_weight`` hybrid knob
(reference: src/objective/rank_objective.hpp:22-41 target enum, :253-524
pairwise loop with per-target pair windows and delta_pair formulas,
include/LightGBM/config.h:989-1013).

TPU design: queries are bucketed by padded power-of-2 length; per bucket one
jitted, query-vmapped kernel sorts by score, forms the [L, L] pair lattice
with the target's (i_end, start, end) window as masks, and accumulates
lambdas/hessians by row+column reduction — O(ΣL²) dense VPU work instead of
the reference's per-query OMP loops (rank_objective.hpp:82-116) or the CUDA
bitonic-sort kernel (src/objective/cuda/cuda_rank_objective.cu). The sigmoid
lookup table (:526-552) is unnecessary — the VPU computes sigmoids directly.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..utils import log
from .base import K_EPSILON, ObjectiveFunction, register_objective

K_MIN_SCORE = -1e30

# targets using the binarized pair filter (skip pairs with both labels > 0)
# (reference: rank_objective.hpp:365-380)
_BINARY_TARGETS = frozenset({
    "precision", "bndcg", "lambdaloss-bndcg", "lambdaloss-bndcg-plus-plus",
    "arpk", "bin-ranknet", "lambdagap-s", "lambdagap-x", "lambdagap-s-plus",
    "lambdagap-x-plus", "lambdagap-s-plus-plus", "lambdagap-x-plus-plus"})

# targets whose outer loop stops at the truncation level
# (reference: rank_objective.hpp:306-321)
_TRUNCATED_I_TARGETS = frozenset({
    "ndcg", "lambdaloss-ndcg", "lambdaloss-ndcg-plus-plus", "bndcg",
    "lambdaloss-bndcg", "lambdaloss-bndcg-plus-plus", "precision"})


def _discount(rank):
    """1/log2(2+rank) (reference: dcg_calculator.cpp GetDiscount)."""
    return 1.0 / jnp.log2(2.0 + rank)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def max_dcg_at_k(labels: np.ndarray, k: int, label_gain: np.ndarray) -> float:
    """(reference: dcg_calculator.cpp CalMaxDCGAtK)"""
    top = np.sort(labels)[::-1][:k]
    disc = 1.0 / np.log2(2.0 + np.arange(len(top)))
    return float(np.sum(label_gain[top.astype(np.int64)] * disc))


def max_bdcg_at_k(labels: np.ndarray, k: int) -> float:
    """Binarized max DCG (fork-added; reference: dcg_calculator.cpp:82
    CalMaxBDCGAtK): sum of top-min(k, #relevant) discounts."""
    relevant = int(np.sum(labels > 0))
    kk = min(k, len(labels), relevant)
    if kk <= 0:
        return 0.0
    return float(np.sum(1.0 / np.log2(2.0 + np.arange(kk))))


class _QueryBuckets:
    """Queries grouped by padded length for shape-stable jitted kernels.

    No length cap: arbitrarily long queries are exact (the reference handles
    any query length, rank_objective.hpp:253-524) — buckets past the dense
    lattice limit route to the row-tiled pairwise kernel, whose memory is
    O(L·T) instead of O(L²)."""

    def __init__(self, query_boundaries: np.ndarray, num_data: int) -> None:
        self.num_data = num_data
        qb = np.asarray(query_boundaries, dtype=np.int64)
        lengths = np.diff(qb)
        self.num_queries = len(lengths)
        buckets: Dict[int, List[int]] = {}
        for qi, ln in enumerate(lengths):
            L = max(_next_pow2(int(ln)), 8)
            buckets.setdefault(L, []).append(qi)
        self.buckets = []
        for L, qids in sorted(buckets.items()):
            nq = len(qids)
            idx = np.full((nq, L), num_data, dtype=np.int32)   # num_data = pad
            for r, qi in enumerate(qids):
                ln = min(int(lengths[qi]), L)
                idx[r, :ln] = np.arange(qb[qi], qb[qi] + ln, dtype=np.int32)
            self.buckets.append((L, np.asarray(qids, np.int32), idx))


_LOOP_CACHE: dict = {}


class RankingBase(ObjectiveFunction):
    """Shared query plumbing (reference: rank_objective.hpp:45-147
    RankingObjective): per-query gradient kernels + position-bias Newton
    updates + effective-pair-rate logging."""

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.position_bias_regularization = \
            config.lambdarank_position_bias_regularization
        self.learning_rate = config.learning_rate
        self.iter_count = 0
        self.last_effective_pair_rate = None

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        self.num_queries = metadata.num_queries
        self.bucketing = _QueryBuckets(self.query_boundaries, num_data)
        # positions for unbiased LTR
        if metadata.position is not None:
            pos = np.asarray(metadata.position, np.int32)
            self.positions = jnp.asarray(pos)
            self.num_position_ids = int(pos.max()) + 1
            self.pos_biases = jnp.zeros(self.num_position_ids, jnp.float32)
        else:
            self.positions = None
            self.num_position_ids = 0

    # per-bucket kernel; subclasses implement
    def _bucket_gradients(self, scores_b, labels_b, valid_b, aux_b):
        raise NotImplementedError

    def _bucket_gradients_k(self, scores_b, labels_b, valid_b, aux_b, key):
        """Keyed variant for randomized objectives (xendcg); the default
        ignores the key."""
        return self._bucket_gradients(scores_b, labels_b, valid_b, aux_b)

    def _bucket_aux(self, qids: np.ndarray) -> tuple:
        return ()

    def _next_key(self):
        """Per-iteration PRNG key for randomized subclasses."""
        return jnp.zeros(2, jnp.uint32)

    def _loop_statics(self) -> tuple:
        """Hashable tuple of EVERY self-dependency the jitted loop body
        reads (kernel config + label gains): two objectives with equal
        statics may share one compiled loop."""
        return ()

    def _make_loop(self):
        """Compile the WHOLE bucket loop into one program. The eager loop
        paid ~6 dispatches per bucket per iteration (gathers, the kernel,
        two scatter-adds) — real latency on a remote device link. Bucket
        index/aux arrays are passed as pytree ARGUMENTS, not closed over:
        captured device arrays would inline into the HLO as constants
        (N-scale payloads break the remote-compile transport)."""
        num_data = self.num_data
        has_pos = self.positions is not None

        def loop(s, label, positions, pos_biases, key, idxs, auxs):
            if has_pos:
                s = s + pos_biases[positions]
            grad = jnp.zeros(num_data + 1, jnp.float32)
            hess = jnp.zeros(num_data + 1, jnp.float32)
            pad_s = jnp.concatenate([s, jnp.asarray([K_MIN_SCORE], s.dtype)])
            pad_l = jnp.concatenate([label,
                                     jnp.asarray([0.0], label.dtype)])
            eff_sum = jnp.float32(0.0)
            for idx_d, aux in zip(idxs, auxs):
                sb = pad_s[idx_d]
                lb = pad_l[idx_d]
                vb = idx_d < num_data
                lam, hes, eff = self._bucket_gradients_k(sb, lb, vb, aux,
                                                         key)
                grad = grad.at[idx_d.reshape(-1)].add(lam.reshape(-1),
                                                      mode="drop")
                hess = hess.at[idx_d.reshape(-1)].add(hes.reshape(-1),
                                                      mode="drop")
                eff_sum = eff_sum + jnp.sum(eff)
            return grad[:-1], hess[:-1], eff_sum

        idxs = tuple(jnp.asarray(idx) for (_, _, idx)
                     in self.bucketing.buckets)
        auxs = tuple(self._bucket_aux(qids) for (_, qids, _)
                     in self.bucketing.buckets)
        # share compiled loops across instances (cv folds, repeated
        # sweeps): the closure captures `self`, so the cache key must list
        # every self-dependency of the body — num_data, position use, and
        # the kernel statics. The cached closure pins its first objective
        # alive; the cache is small and bounded.
        key = (type(self).__qualname__, num_data, has_pos,
               self._loop_statics())
        fn = _LOOP_CACHE.get(key)
        if fn is None:
            if len(_LOOP_CACHE) > 16:
                _LOOP_CACHE.clear()
            _LOOP_CACHE[key] = fn = jax.jit(loop)
        return fn, idxs, auxs

    def get_gradients(self, scores):
        s = scores[0]
        if getattr(self, "_loop_jit", None) is None:
            self._loop_jit, self._loop_idxs, self._loop_auxs = \
                self._make_loop()
        pos = self.positions if self.positions is not None \
            else jnp.zeros(1, jnp.int32)
        pb = self.pos_biases if self.positions is not None \
            else jnp.zeros(1, jnp.float32)
        g, h, eff_sum = self._loop_jit(s, self.label, pos, pb,
                                       self._next_key(), self._loop_idxs,
                                       self._loop_auxs)
        if self.weight is not None:
            g = g * self.weight
            h = h * self.weight
        if self.positions is not None:
            self._update_position_bias(g, h)
        # the fork's per-iteration effective-pair-rate line
        # (reference: src/objective/rank_objective.hpp:108-116) — the D2H
        # sync is only paid when debug logging is on
        if log.debug_enabled():
            rate = float(eff_sum) / max(self.num_queries, 1)
            self.last_effective_pair_rate = rate
            log.debug("iteration %d: effective pair rate %.4f "
                      "(mean over %d queries)",
                      self.iter_count + 1, rate, self.num_queries)
        self.iter_count += 1
        return g[None, :], h[None, :]

    def _update_position_bias(self, grad, hess) -> None:
        """Newton-Raphson on per-position utility derivatives
        (reference: rank_objective.hpp:554-591 UpdatePositionBiasFactors)."""
        npos = self.num_position_ids
        first = -jax.ops.segment_sum(grad, self.positions, num_segments=npos)
        second = -jax.ops.segment_sum(hess, self.positions, num_segments=npos)
        counts = jax.ops.segment_sum(jnp.ones_like(grad), self.positions,
                                     num_segments=npos)
        first = first - self.pos_biases * self.position_bias_regularization * counts
        second = second - self.position_bias_regularization * counts
        self.pos_biases = self.pos_biases + \
            self.learning_rate * first / (jnp.abs(second) + 0.001)


@register_objective
class LambdarankNDCG(RankingBase):
    """The 18-target LambdaRank
    (reference: rank_objective.hpp:174-648 LambdarankNDCG)."""
    name = "lambdarank"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        self.target = config.lambdarank_target
        self.lambdagap_weight = config.lambdagap_weight

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        max_label = int(self.label_np.max())
        if np.any(self.label_np < 0) or np.any(self.label_np != np.floor(self.label_np)):
            log.fatal("[lambdarank]: labels must be non-negative integers")
        gains = np.asarray(self.config.label_gain_or_default(max_label))
        if max_label >= len(gains):
            log.fatal("Label %d exceeds label_gain size %d", max_label, len(gains))
        self.label_gain = jnp.asarray(gains, jnp.float32)
        # per-query inverse max (B)DCG at the truncation level
        # (reference: rank_objective.hpp:250-266)
        inv_dcg = np.zeros(self.num_queries)
        inv_bdcg = np.zeros(self.num_queries)
        qb = self.query_boundaries
        for qi in range(self.num_queries):
            ql = self.label_np[qb[qi]:qb[qi + 1]]
            d = max_dcg_at_k(ql, self.truncation_level, gains)
            b = max_bdcg_at_k(ql, self.truncation_level)
            inv_dcg[qi] = 1.0 / d if d > 0 else 0.0
            inv_bdcg[qi] = 1.0 / b if b > 0 else 0.0
        self.inv_max_dcg = inv_dcg
        self.inv_max_bdcg = inv_bdcg
        log.info("Using lambdarank objective with target '%s'", self.target)

    def _loop_statics(self) -> tuple:
        import numpy as _np
        return (self.target, self.sigmoid, self.norm,
                self.truncation_level, self.lambdagap_weight,
                tuple(_np.asarray(self.label_gain).tolist()))

    def _bucket_aux(self, qids):
        return (jnp.asarray(self.inv_max_dcg[qids], jnp.float32),
                jnp.asarray(self.inv_max_bdcg[qids], jnp.float32))

    def _bucket_gradients(self, scores_b, labels_b, valid_b, aux_b):
        inv_dcg, inv_bdcg = aux_b
        L = scores_b.shape[1]
        tile = None if L <= _DENSE_PAIR_L else max(
            (_DENSE_PAIR_L * _DENSE_PAIR_L) // L, 64)
        return _lambdarank_bucket(
            scores_b, labels_b, valid_b, inv_dcg, inv_bdcg, self.label_gain,
            target=self.target, sigmoid=self.sigmoid, norm=self.norm,
            truncation_level=self.truncation_level,
            lambdagap_weight=self.lambdagap_weight, tile=tile)


# queries up to this padded length use the dense [L, L] lattice; longer ones
# route to the row-tiled sweep (same math, O(L*tile) memory) — the TPU-shaped
# answer to the reference's arbitrary-length per-query loops
# (rank_objective.hpp:253-524)
_DENSE_PAIR_L = 4096


@functools.partial(
    jax.jit,
    static_argnames=("target", "sigmoid", "norm", "truncation_level",
                     "lambdagap_weight", "tile"))
def _lambdarank_bucket(scores, labels, valid, inv_dcg, inv_bdcg, label_gain,
                       *, target: str, sigmoid: float, norm: bool,
                       truncation_level: int, lambdagap_weight: float,
                       tile: Optional[int] = None):
    """Vectorized per-query lambda computation for one padded bucket.

    scores/labels/valid: [nq, L]; inv_dcg/inv_bdcg: [nq].
    Returns (lambdas [nq, L], hessians [nq, L], effective_pair_rate [nq]).

    ``tile=None``: one dense [L, L] pair lattice per query. ``tile=T``:
    the row axis is swept in blocks of T under the same window masks —
    peak memory O(L*T), identical arithmetic per pair — so arbitrarily
    long queries stay exact."""
    from jax import lax
    tl = truncation_level
    if tile is not None and scores.shape[1] % tile != 0:
        # lax.dynamic_slice clamps out-of-range starts, so a non-divisor
        # tile would silently misalign rank indices against the sliced
        # score/label rows and produce wrong lambdas
        raise ValueError(
            f"tile={tile} must divide the padded bucket length "
            f"{scores.shape[1]}")

    def pair_block(i, j, si, sj, li, lj, vij, imd, imb, best, worst):
        """All pair quantities for one [bi, bj] block of the sorted
        lattice. i/j are rank indices ([bi,1] / [1,bj]); s/l are the
        score/label slices at those ranks; vij the validity product.
        Returns (lam_to_row [bi,bj] signed lambda for the ROW doc,
        p_hessian [bi,bj], sum_p_lambda scalar, pair_count scalar); the
        COLUMN doc's lambda is minus the row's (accumulated by the
        caller), per reference :505-512."""
        pair_valid = vij & (i < j) & (li != lj)
        if target in _BINARY_TARGETS:
            pair_valid &= ~((li > 0) & (lj > 0))

        # outer-loop truncation (i_end) and per-target (start, end) windows
        if target in _TRUNCATED_I_TARGETS:
            pair_valid &= i < tl
        if target == "precision":
            pair_valid &= j >= tl
        elif target in ("arpk", "lambdagap-s-plus", "lambdagap-x-plus",
                        "lambdagap-s-plus-plus", "lambdagap-x-plus-plus"):
            pair_valid &= j >= tl              # j >= max(i+1, tl); i<j holds
        elif target == "lambdagap-s":
            pair_valid &= j == i + tl
        elif target == "lambdagap-x":
            pair_valid &= j >= i + tl

        # orient the pair: high = larger label
        hi_is_i = li > lj
        hs = jnp.where(hi_is_i, si, sj)
        lo_s = jnp.where(hi_is_i, sj, si)
        hl = jnp.where(hi_is_i, li, lj).astype(jnp.int32)
        ll = jnp.where(hi_is_i, lj, li).astype(jnp.int32)
        hr = jnp.where(hi_is_i, i, j)          # rank of the high-label doc
        lr = jnp.where(hi_is_i, j, i)
        delta_score = hs - lo_s

        rank_diff = (j - i).astype(jnp.float32)
        disc_hr = _discount(hr.astype(jnp.float32))
        disc_lr = _discount(lr.astype(jnp.float32))
        paired_lambdarank = jnp.abs(disc_hr - disc_lr)
        paired_lambdaloss = _discount(rank_diff) - _discount(rank_diff + 1.0)
        gain_gap = label_gain[hl] - label_gain[ll]

        # delta_pair per target (reference: rank_objective.hpp:398-489)
        if target == "ndcg":
            delta = gain_gap * paired_lambdarank * imd
        elif target == "lambdaloss-ndcg":
            delta = gain_gap * paired_lambdaloss * imd
        elif target == "lambdaloss-ndcg-plus-plus":
            delta = gain_gap * (paired_lambdarank
                                + lambdagap_weight * paired_lambdaloss) * imd
        elif target == "bndcg":
            delta = paired_lambdarank * imb
        elif target == "lambdaloss-bndcg":
            delta = paired_lambdaloss * imb
        elif target == "lambdaloss-bndcg-plus-plus":
            delta = (paired_lambdarank
                     + lambdagap_weight * paired_lambdaloss) * imb
        elif target in ("precision", "lambdagap-s", "lambdagap-x",
                        "bin-ranknet", "ranknet"):
            delta = jnp.ones_like(delta_score)
        elif target == "lambdagap-s-plus":
            delta = ((j - i == tl) * lambdagap_weight
                     + (i < tl)).astype(jnp.float32)
        elif target == "lambdagap-x-plus":
            delta = ((j - i >= tl) * lambdagap_weight
                     + (i < tl)).astype(jnp.float32)
        elif target == "lambdagap-s-plus-plus":
            delta = ((j - i == tl) * lambdagap_weight + (j + 1 - tl)
                     - (i >= tl) * (i + 1 - tl)).astype(jnp.float32)
        elif target == "lambdagap-x-plus-plus":
            delta = ((j - i >= tl) * lambdagap_weight + (j + 1 - tl)
                     - (i >= tl) * (i + 1 - tl)).astype(jnp.float32)
        elif target == "arpk":
            delta = ((j + 1 - tl)
                     - (i >= tl) * (i + 1 - tl)).astype(jnp.float32)
        elif target == "lambdaloss-arp1":
            delta = jnp.where(hi_is_i, li, lj)
        elif target == "lambdaloss-arp2":
            delta = jnp.where(hi_is_i, li, lj) - jnp.where(hi_is_i, lj, li)
        else:
            raise ValueError(f"unknown lambdarank target {target!r}")

        pair_valid &= delta != 0

        # score-distance normalization (reference: :495-498)
        if norm:
            delta = jnp.where(best != worst,
                              delta / (0.01 + jnp.abs(delta_score)), delta)

        p = 1.0 / (1.0 + jnp.exp(sigmoid * delta_score))
        p_lambda = -sigmoid * delta * p
        p_hessian = sigmoid * sigmoid * delta * p * (1.0 - p)
        p_lambda = jnp.where(pair_valid, p_lambda, 0.0)
        p_hessian = jnp.where(pair_valid, p_hessian, 0.0)
        lam_to_row = jnp.where(hi_is_i, p_lambda, -p_lambda)
        # pair count in f32: int32 would wrap past ~2^31 pairs, reachable
        # now that query length is uncapped (a 66k-doc query alone has 2^31)
        return (lam_to_row, p_hessian, jnp.sum(p_lambda),
                jnp.sum(pair_valid, dtype=jnp.float32))

    def one_query(s, l, v, imd, imb):
        L = s.shape[0]
        neg = jnp.where(v, s, K_MIN_SCORE)
        order = jnp.argsort(-neg)              # stable: ranks by score desc
        ss = neg[order]
        ls = l[order].astype(jnp.float32)
        vs = v[order]
        ranks = jnp.arange(L, dtype=jnp.int32)
        nv = jnp.sum(vs)
        best = ss[0]
        worst = ss[jnp.maximum(nv - 1, 0)]

        if tile is None:
            lam_to_row, p_hessian, sum_pl, count_lambdas = pair_block(
                ranks[:, None], ranks[None, :], ss[:, None], ss[None, :],
                ls[:, None], ls[None, :], vs[:, None] & vs[None, :],
                imd, imb, best, worst)
            lam_sorted = (jnp.sum(lam_to_row, axis=1)
                          - jnp.sum(lam_to_row, axis=0))
            hes_sorted = (jnp.sum(p_hessian, axis=1)
                          + jnp.sum(p_hessian, axis=0))
        else:
            T = tile
            # truncated-i targets zero every row past the truncation level:
            # their row sweep stops at ceil(tl / T) blocks (exact — those
            # rows' pair_valid is identically False)
            i_limit = min(L, tl) if target in _TRUNCATED_I_TARGETS else L
            nb = -(-i_limit // T)
            jr = ranks[None, :]
            sj = ss[None, :]
            lj = ls[None, :]
            vj = vs[None, :]

            def body(b, carry):
                lam_row, col_lam, hes_row, col_hes, spl, cnt = carry
                off = b * T
                ir = (off + jnp.arange(T, dtype=jnp.int32))[:, None]
                si = lax.dynamic_slice(ss, (off,), (T,))[:, None]
                li = lax.dynamic_slice(ls, (off,), (T,))[:, None]
                vi = lax.dynamic_slice(vs, (off,), (T,))[:, None]
                ltr, ph, s1, c1 = pair_block(ir, jr, si, sj, li, lj,
                                             vi & vj, imd, imb, best, worst)
                lam_row = lax.dynamic_update_slice(
                    lam_row,
                    lax.dynamic_slice(lam_row, (off,), (T,))
                    + jnp.sum(ltr, axis=1), (off,))
                hes_row = lax.dynamic_update_slice(
                    hes_row,
                    lax.dynamic_slice(hes_row, (off,), (T,))
                    + jnp.sum(ph, axis=1), (off,))
                col_lam = col_lam + jnp.sum(ltr, axis=0)
                col_hes = col_hes + jnp.sum(ph, axis=0)
                return (lam_row, col_lam, hes_row, col_hes,
                        spl + s1, cnt + c1)

            z = jnp.zeros(L, jnp.float32)
            lam_row, col_lam, hes_row, col_hes, sum_pl, count_lambdas = \
                lax.fori_loop(0, nb, body,
                              (z, z, z, z, jnp.float32(0.0),
                               jnp.float32(0.0)))
            lam_sorted = lam_row - col_lam
            hes_sorted = hes_row + col_hes

        sum_lambdas = -2.0 * sum_pl
        if norm:
            norm_factor = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas)
                / jnp.maximum(sum_lambdas, K_EPSILON),
                1.0)
            lam_sorted = lam_sorted * norm_factor
            hes_sorted = hes_sorted * norm_factor

        # unsort back to document order
        inv = jnp.argsort(order)
        lam = lam_sorted[inv]
        hes = hes_sorted[inv]
        nvf = nv.astype(jnp.float32)           # int32 nv*(nv-1) would wrap
        eff = 2.0 * count_lambdas.astype(jnp.float32) / \
            jnp.maximum(nvf * (nvf - 1.0), 1.0)
        return lam, hes, eff

    return jax.vmap(one_query)(scores, labels, valid, inv_dcg, inv_bdcg)


@register_objective
class RankXENDCG(RankingBase):
    """Cross-entropy NDCG surrogate
    (reference: rank_objective.hpp:650-724 RankXENDCG): per-query softmax
    with Gumbel-perturbed gains and third-order gradient correction."""
    name = "rank_xendcg"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.seed = config.seed

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        self.key = jax.random.PRNGKey(self.seed)

    def _bucket_aux(self, qids):
        return (len(qids),)

    def _next_key(self):
        # fresh per-iteration randomness (reference uses per-query Random
        # streams; a split PRNG key is the JAX analog)
        self.key, sub = jax.random.split(self.key)
        return sub

    def _bucket_gradients_k(self, scores_b, labels_b, valid_b, aux_b, key):
        return _xendcg_bucket(scores_b, labels_b, valid_b,
                              jax.random.fold_in(key, scores_b.shape[1]))


@jax.jit
def _xendcg_bucket(scores, labels, valid, key):
    def one_query(s, l, v, k):
        L = s.shape[0]
        nv = jnp.sum(v)
        sm = jnp.where(v, s, K_MIN_SCORE)
        m = jnp.max(sm)
        e = jnp.where(v, jnp.exp(sm - m), 0.0)
        rho = e / jnp.maximum(jnp.sum(e), K_EPSILON)

        u = jax.random.uniform(k, (L,))
        phi = jnp.where(v, jnp.power(2.0, l.astype(jnp.float32)) - u, 0.0)
        inv_denominator = 1.0 / jnp.maximum(jnp.sum(phi), K_EPSILON)

        # third-order expansion (reference: rank_objective.hpp:695-719)
        term1 = -phi * inv_denominator + rho
        lam = term1
        params = jnp.where(v, term1 / jnp.maximum(1.0 - rho, K_EPSILON), 0.0)
        sum_l1 = jnp.sum(params)
        term2 = rho * (sum_l1 - params)
        lam = lam + term2
        params = jnp.where(v, term2 / jnp.maximum(1.0 - rho, K_EPSILON), 0.0)
        sum_l2 = jnp.sum(params)
        lam = lam + rho * (sum_l2 - params)
        hes = rho * (1.0 - rho)
        lam = jnp.where(v & (nv > 1), lam, 0.0)
        hes = jnp.where(v & (nv > 1), hes, 0.0)
        return lam, hes, jnp.float32(0.0)

    nq = scores.shape[0]
    keys = jax.random.split(key, nq)
    return jax.vmap(one_query)(scores, labels, valid, keys)
