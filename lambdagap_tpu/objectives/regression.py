"""Regression objectives.

Re-implementations of the reference's regression loss family
(reference: src/objective/regression_objective.hpp:100-763): L2 (+sqrt), L1,
Huber, Fair, Poisson, Quantile, MAPE, Gamma, Tweedie. Formulas match the
reference line-for-line in math (not code); see per-class citations.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from .base import (K_EPSILON, ObjectiveFunction, register_objective,
                   weighted_percentile)


def _w(x, weight):
    return x if weight is None else x * weight


@register_objective
class RegressionL2(ObjectiveFunction):
    """(reference: regression_objective.hpp:127-143 RegressionL2loss)"""
    name = "regression"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sqrt = config.reg_sqrt

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        if self.sqrt:
            self.label_np = (np.sign(self.label_np)
                             * np.sqrt(np.abs(self.label_np))).astype(np.float32)
            self.label = jnp.asarray(self.label_np)

    _GRAD_ARRAY_FIELDS = ("label", "weight")

    def get_gradients(self, scores):
        grad = _w(scores - self.label[None, :], self.weight)
        hess = (jnp.ones_like(scores) if self.weight is None
                else jnp.broadcast_to(self.weight[None, :], scores.shape))
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        if not self.config.boost_from_average:
            return 0.0
        if self.weight_np is not None:
            return float(np.sum(self.label_np * self.weight_np)
                         / max(np.sum(self.weight_np), K_EPSILON))
        return float(np.mean(self.label_np))

    def convert_output_np(self, scores):
        if self.sqrt:
            return np.sign(scores) * scores * scores
        return scores

    def convert_output(self, scores):
        if self.sqrt:
            return jnp.sign(scores) * scores * scores
        return scores

    @property
    def is_constant_hessian(self) -> bool:
        return self.weight is None


@register_objective
class RegressionL1(RegressionL2):
    """(reference: regression_objective.hpp:210-290 RegressionL1loss)"""
    name = "regression_l1"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, scores):
        diff = scores - self.label[None, :]
        grad = _w(jnp.sign(diff), self.weight)
        hess = (jnp.ones_like(scores) if self.weight is None
                else jnp.broadcast_to(self.weight[None, :], scores.shape))
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        # initial score = weighted median (reference: RegressionL1loss::BoostFromScore)
        return weighted_percentile(self.label_np, self.weight_np, 0.5)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def renew_tree_output(self, leaf_rows, score) -> float:
        resid = self.label_np[leaf_rows] - score[leaf_rows]
        w = None if self.weight_np is None else self.weight_np[leaf_rows]
        return weighted_percentile(resid, w, 0.5)


@register_objective
class RegressionHuber(RegressionL2):
    """(reference: regression_objective.hpp:292-350 RegressionHuberLoss)"""
    name = "huber"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.alpha = config.alpha
        self.sqrt = False

    def get_gradients(self, scores):
        diff = scores - self.label[None, :]
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        grad = _w(grad, self.weight)
        hess = (jnp.ones_like(scores) if self.weight is None
                else jnp.broadcast_to(self.weight[None, :], scores.shape))
        return grad, hess


@register_objective
class RegressionFair(RegressionL2):
    """(reference: regression_objective.hpp:353-395 RegressionFairLoss)"""
    name = "fair"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.c = config.fair_c
        self.sqrt = False

    def get_gradients(self, scores):
        x = scores - self.label[None, :]
        c = self.c
        grad = _w(c * x / (jnp.abs(x) + c), self.weight)
        hess = _w(c * c / ((jnp.abs(x) + c) ** 2),
                  self.weight)
        if self.weight is None:
            hess = c * c / ((jnp.abs(x) + c) ** 2)
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    @property
    def is_constant_hessian(self) -> bool:
        return False


@register_objective
class RegressionPoisson(RegressionL2):
    """Log-link Poisson (reference: regression_objective.hpp:398-478)."""
    name = "poisson"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.max_delta = config.poisson_max_delta_step
        self.sqrt = False

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        if np.any(self.label_np < 0):
            from ..utils import log
            log.fatal("[poisson]: at least one target label is negative")

    def get_gradients(self, scores):
        exp_score = jnp.exp(scores)
        grad = _w(exp_score - self.label[None, :], self.weight)
        hess = _w(exp_score * np.exp(self.max_delta), self.weight)
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        mean = super().boost_from_score(0) if self.config.boost_from_average else \
            float(np.mean(self.label_np))
        return float(np.log(max(mean, K_EPSILON)))

    def convert_output(self, scores):
        return jnp.exp(scores)

    def convert_output_np(self, scores):
        return np.exp(scores)

    @property
    def is_constant_hessian(self) -> bool:
        return False


@register_objective
class RegressionQuantile(RegressionL2):
    """Pinball loss (reference: regression_objective.hpp:481-560)."""
    name = "quantile"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.alpha = config.alpha
        self.sqrt = False

    def get_gradients(self, scores):
        diff = scores - self.label[None, :]
        grad = jnp.where(diff >= 0, 1.0 - self.alpha, -self.alpha)
        grad = _w(grad, self.weight)
        hess = (jnp.ones_like(scores) if self.weight is None
                else jnp.broadcast_to(self.weight[None, :], scores.shape))
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        return weighted_percentile(self.label_np, self.weight_np, self.alpha)

    @property
    def is_renew_tree_output(self) -> bool:
        return True

    def renew_tree_output(self, leaf_rows, score) -> float:
        resid = self.label_np[leaf_rows] - score[leaf_rows]
        w = None if self.weight_np is None else self.weight_np[leaf_rows]
        return weighted_percentile(resid, w, self.alpha)


@register_objective
class RegressionMAPE(RegressionL1):
    """(reference: regression_objective.hpp:563-637 RegressionMAPELOSS):
    L1 on residuals weighted by 1/max(1, |label|)."""
    name = "mape"

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        lw = 1.0 / np.maximum(1.0, np.abs(self.label_np))
        if self.weight_np is not None:
            lw = lw * self.weight_np
        self.label_weight_np = lw.astype(np.float32)
        self.label_weight = jnp.asarray(self.label_weight_np)

    _GRAD_ARRAY_FIELDS = ("label", "label_weight")

    def get_gradients(self, scores):
        diff = scores - self.label[None, :]
        grad = jnp.sign(diff) * self.label_weight[None, :]
        hess = jnp.ones_like(scores)
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        return weighted_percentile(self.label_np, self.label_weight_np, 0.5)

    def renew_tree_output(self, leaf_rows, score) -> float:
        resid = self.label_np[leaf_rows] - score[leaf_rows]
        return weighted_percentile(resid, self.label_weight_np[leaf_rows], 0.5)

    @property
    def is_constant_hessian(self) -> bool:
        return True


@register_objective
class RegressionGamma(RegressionPoisson):
    """(reference: regression_objective.hpp:678-717 RegressionGammaLoss)"""
    name = "gamma"

    def get_gradients(self, scores):
        exp_neg = jnp.exp(-scores)
        grad = _w(1.0 - self.label[None, :] * exp_neg, self.weight)
        hess = _w(self.label[None, :] * exp_neg, self.weight)
        return grad, hess


@register_objective
class RegressionTweedie(RegressionPoisson):
    """(reference: regression_objective.hpp:720-763 RegressionTweedieLoss)"""
    name = "tweedie"

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self.rho = config.tweedie_variance_power

    def get_gradients(self, scores):
        rho = self.rho
        e1 = jnp.exp((1 - rho) * scores)
        e2 = jnp.exp((2 - rho) * scores)
        y = self.label[None, :]
        grad = _w(-y * e1 + e2, self.weight)
        hess = _w(-y * (1 - rho) * e1 + (2 - rho) * e2, self.weight)
        return grad, hess
