"""Cross-entropy objectives for probabilistic labels in [0, 1].

(reference: src/objective/xentropy_objective.hpp:316 — CrossEntropy and
CrossEntropyLambda, the weight-as-Bernoulli-trials variant.)
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..config import Config
from ..utils import log
from .base import K_EPSILON, ObjectiveFunction, register_objective


@register_objective
class CrossEntropy(ObjectiveFunction):
    """(reference: xentropy_objective.hpp:30-160 CrossEntropy)"""
    name = "cross_entropy"

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        if np.any((self.label_np < 0) | (self.label_np > 1)):
            log.fatal("[cross_entropy]: labels must be in [0, 1]")

    _GRAD_ARRAY_FIELDS = ("label", "weight")

    def get_gradients(self, scores):
        p = 1.0 / (1.0 + jnp.exp(-scores))
        grad = p - self.label[None, :]
        hess = p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        if self.weight_np is not None:
            pavg = float(np.sum(self.label_np * self.weight_np)
                         / max(np.sum(self.weight_np), K_EPSILON))
        else:
            pavg = float(np.mean(self.label_np))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, scores):
        return 1.0 / (1.0 + jnp.exp(-scores))

    def convert_output_np(self, scores):
        return 1.0 / (1.0 + np.exp(-scores))


@register_objective
class CrossEntropyLambda(ObjectiveFunction):
    """(reference: xentropy_objective.hpp:165-310 CrossEntropyLambda):
    weights act as Bernoulli trial counts via z = 1 - exp(-w*log1p(exp(s)))."""
    name = "cross_entropy_lambda"

    def init(self, metadata, num_data) -> None:
        super().init(metadata, num_data)
        if np.any((self.label_np < 0) | (self.label_np > 1)):
            log.fatal("[cross_entropy_lambda]: labels must be in [0, 1]")

    _GRAD_ARRAY_FIELDS = ("label", "weight")

    def get_gradients(self, scores):
        y = self.label[None, :]
        if self.weight is None:
            z = 1.0 / (1.0 + jnp.exp(-scores))
            grad = z - y
            hess = z * (1.0 - z)
        else:
            w = self.weight[None, :]
            epf = jnp.exp(scores)
            enf = 1.0 / epf
            hhat = jnp.log1p(epf)
            z = 1.0 - jnp.exp(-w * hhat)
            grad = (1.0 - y / jnp.maximum(z, K_EPSILON)) * w / (1.0 + enf)
            c = 1.0 / (1.0 - jnp.maximum(z, K_EPSILON))
            b = 1.0 - c * enf * (z - w * hhat * (1.0 - z))
            b = b / jnp.maximum(z * z, K_EPSILON)
            a = w * epf / ((1.0 + epf) * (1.0 + epf))
            hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id: int) -> float:
        pavg = float(np.mean(self.label_np))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, scores):
        return jnp.log1p(jnp.exp(scores))

    def convert_output_np(self, scores):
        return np.log1p(np.exp(scores))
