"""lambdagap_tpu.obs — unified training/serving observability (graftscope).

The telemetry subsystem the perf work reports through (docs/observability.md):

- :mod:`.telemetry` — :class:`TrainTelemetry`: named per-iteration phase
  spans (gradients, sampling, histogram, split, partition, tree,
  score_update, eval) with exclusive-time accounting, a bounded ring buffer
  of per-iteration records, and aggregate reservoirs. Device-complete
  timing is taken ONCE per iteration boundary (a single
  ``block_until_ready``), so no host sync lands inside hot paths.
- :mod:`.events` — JSONL structured run log (run header, one record per
  iteration, compile/swap/error events): the artifact BENCH runs diff.
- :mod:`.xla_watch` — recompile & transfer watchdog over ``jax.monitoring``
  events; warns when a steady-state iteration triggers a fresh compile
  (the graftlint-R2 hazard class, caught at runtime).
- :mod:`.profile` — ``jax.profiler`` capture windows driven by the
  ``profile_start_iter`` / ``profile_n_iters`` / ``profile_dir`` knobs.
- :mod:`.prom` — Prometheus text exposition for ``TrainTelemetry``, the
  serve layer's ``ServeStats``, and the merged fleet plane.
- :mod:`.reservoir` — the bounded uniform sample shared by training and
  serving percentiles, with the lifted-aggregate merge the fleet plane
  sums distributions with.
- :mod:`.trace` — distributed request tracing (graftscope v2): trace
  contexts minted at the frontend, one span per hop of the serve stack,
  parent-linked trees that tile the client-observed wall, and the
  per-process flight recorder (bounded span/event ring, atomic dumps on
  fault/SIGTERM/interval).
- :mod:`.fleet` — the fleet metric plane: scrape every replica's stats,
  merge counters exactly and latency reservoirs weight-correctly into
  one fleet snapshot + one ``prometheus fleet`` exposition.
- :mod:`.signals` — derived control signals (online goodput-knee,
  residency/eviction pressure, per-replica health timeline): the inputs
  ROADMAP item 2's revival/placement/autoscaling loop consumes.
- :mod:`.costplane` — the analytic cost plane (graftmeter): a
  per-executable FLOP/byte/HBM ledger captured at lowering time for every
  jit entry point (the three learners, the three predict engines, the
  ``predict_stream`` window scorer, SHAP), joined with measured phase
  walls into per-phase fraction-of-roofline, persisted as ``COSTS.json``
  and gated in CI by ``tools/cost_gate.py``.

Everything is inert unless enabled (``telemetry=true`` / ``telemetry_out=``
/ ``LAMBDAGAP_TIMETAG``; ``serve_trace_sample>0`` for tracing;
``cost_plane=true`` / ``cost_plane_out=`` for the cost ledger): the off
path records nothing and registers no ``jax.monitoring`` hooks.
"""
from __future__ import annotations

from .costplane import CostPlane  # noqa: F401
from .reservoir import MergedReservoir, Reservoir, merge_states  # noqa: F401
from .telemetry import NULL_TELEMETRY, TrainTelemetry  # noqa: F401
from .trace import (RECORDER, FlightRecorder, SpanRecorder,  # noqa: F401
                    TraceContext, start_trace, validate_tree)

__all__ = ["Reservoir", "MergedReservoir", "merge_states",
           "TrainTelemetry", "NULL_TELEMETRY", "TraceContext",
           "SpanRecorder", "FlightRecorder", "RECORDER", "start_trace",
           "validate_tree", "CostPlane"]
