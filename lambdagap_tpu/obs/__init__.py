"""lambdagap_tpu.obs — unified training/serving observability (graftscope).

The telemetry subsystem the perf work reports through (docs/observability.md):

- :mod:`.telemetry` — :class:`TrainTelemetry`: named per-iteration phase
  spans (gradients, sampling, histogram, split, partition, tree,
  score_update, eval) with exclusive-time accounting, a bounded ring buffer
  of per-iteration records, and aggregate reservoirs. Device-complete
  timing is taken ONCE per iteration boundary (a single
  ``block_until_ready``), so no host sync lands inside hot paths.
- :mod:`.events` — JSONL structured run log (run header, one record per
  iteration, compile/swap/error events): the artifact BENCH runs diff.
- :mod:`.xla_watch` — recompile & transfer watchdog over ``jax.monitoring``
  events; warns when a steady-state iteration triggers a fresh compile
  (the graftlint-R2 hazard class, caught at runtime).
- :mod:`.profile` — ``jax.profiler`` capture windows driven by the
  ``profile_start_iter`` / ``profile_n_iters`` / ``profile_dir`` knobs.
- :mod:`.prom` — Prometheus text exposition for both ``TrainTelemetry``
  and the serve layer's ``ServeStats``.
- :mod:`.reservoir` — the bounded uniform sample shared by training and
  serving percentiles.

Everything is inert unless enabled (``telemetry=true`` / ``telemetry_out=``
/ ``LAMBDAGAP_TIMETAG``): the off path records nothing and registers no
``jax.monitoring`` hooks.
"""
from __future__ import annotations

from .reservoir import Reservoir  # noqa: F401
from .telemetry import NULL_TELEMETRY, TrainTelemetry  # noqa: F401

__all__ = ["Reservoir", "TrainTelemetry", "NULL_TELEMETRY"]
