"""Analytic cost plane (graftmeter): per-executable FLOP/byte/HBM ledger.

Every BENCH/MULTICHIP number this repo produces is CPU-shaped, so nothing
hardware-independent says whether a PR regressed a hot program's compute
or memory traffic. XLA already knows: ``Lowered.cost_analysis()`` reports
analytic flops / transcendentals / bytes-accessed for the lowered program
and ``Compiled.memory_analysis()`` reports argument/output/temp/code HBM —
exact on any backend, at compile time, with zero steady-state cost. This
module captures both, once per (program, padding bucket), at the jit
entry points the repo actually dispatches:

- the three learners — ``train.serial.{histogram,split,partition}``
  (models/learner.py), ``train.fused`` (models/fused_learner.py),
  ``train.fused2d`` (parallel/fused_parallel.py, with its mesh spec);
- the three predict engines — ``predict.scan`` (ops/predict.py),
  ``predict.tensor`` (ops/predict_tensor.py), ``predict.compiled``
  (infer/engine.py);
- the out-of-core window scorer — ``predict_stream.window``
  (infer/stream.py, captured at bucket pre-warm);
- SHAP — ``predict.shap`` (models/gbdt.py): a host numpy loop, recorded
  from an analytic traffic model instead of an XLA lowering.

The ledger joins measured wall-time (``note_wall`` — fed by
``TrainTelemetry.close`` per phase, by ``GBDT.predict_raw`` and the serve
cache per dispatch window) against a per-backend peak table to report
achieved fraction-of-roofline per phase and whether the phase is flop- or
byte-bound. It exports through ``prom.render_costplane``, rides flight
recorder dumps, persists as ``COSTS.json`` (``cost_plane_out=``), and
``tools/cost_gate.py`` diffs it against ``tools/cost_budget.json`` in CI.

Everything is inert unless armed (``cost_plane=true`` / ``cost_plane_out=``):
the off path is one attribute test per observed dispatch. Capture failures
never propagate — a program that refuses to lower is logged at debug and
skipped, and each (program, bucket) is attempted at most once.
"""
from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..utils import log

SCHEMA_VERSION = 1

# Per-backend peak tables: dense-matmul FLOP/s, HBM bandwidth B/s, HBM
# capacity bytes. TPU rows are the published per-chip peaks (v5e bf16
# 197 TFLOP/s / 819 GB/s / 16 GiB; v4 275/1228/32; v5p 459/2765/95);
# ``measured`` False marks placeholders (the CPU container) whose
# roofline fractions are indicative only — the ledger's flops/bytes stay
# exact there, which is all the CI gate consumes.
_PEAK_TABLE: Tuple[Tuple[Tuple[str, ...], Dict[str, Any]], ...] = (
    (("v5 lite", "v5e"), {"name": "tpu-v5e", "flops": 197e12,
                          "bandwidth": 819e9, "hbm": 16 * 2**30,
                          "measured": True}),
    (("v5p", "v5"), {"name": "tpu-v5p", "flops": 459e12,
                     "bandwidth": 2765e9, "hbm": 95 * 2**30,
                     "measured": True}),
    (("v4",), {"name": "tpu-v4", "flops": 275e12, "bandwidth": 1228e9,
               "hbm": 32 * 2**30, "measured": True}),
    (("cpu",), {"name": "cpu-container", "flops": 1e11, "bandwidth": 2e10,
                "hbm": 8 * 2**30, "measured": False}),
)


def _leaf_nbytes(x: Any) -> int:
    """Bytes of one argument leaf (array, tracer or ShapeDtypeStruct);
    0 for statics/scalars without shape+dtype."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(math.prod(shape)) * int(dtype.itemsize)
    except Exception:
        return 0


class _WallSpan:
    """Context manager feeding one measured wall into the plane; inert
    when the plane is disarmed. The caller is responsible for device
    completion inside the bracket (a terminal ``device_get`` /
    ``block_until_ready``), so the noted wall is device-complete."""

    __slots__ = ("_plane", "_phase", "_t0")

    def __init__(self, plane: "CostPlane", phase: str) -> None:
        self._plane = plane
        self._phase = phase
        self._t0 = 0.0

    def __enter__(self) -> "_WallSpan":
        if self._plane.enabled:
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        if self._plane.enabled and exc[0] is None:
            self._plane.note_wall(self._phase,
                                  time.perf_counter() - self._t0)


class CostPlane:
    """Process-global analytic cost ledger (module singleton ``PLANE``).

    ``observed_call`` wraps a jitted callable's dispatch: bookkeeping under
    the lock is O(1), the one-time capture (trace -> lower ->
    cost_analysis, optionally compile -> memory_analysis) runs OUTSIDE the
    lock (graftlint R9: never compile under a lock), and the actual
    dispatch is returned unchanged — bit-identical results, zero
    steady-state overhead beyond a dict increment."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self.memory_mode = "compiled"
        self.out_path = ""
        self._peaks_override = ""
        # "program|bucket" -> captured entry (static facts)
        self.entries: Dict[str, Dict[str, Any]] = {}
        # "program|bucket" -> observed dispatch count
        self.calls: Dict[str, int] = {}
        # phase -> {"seconds": float, "calls": int} measured wall joins
        self.walls: Dict[str, Dict[str, float]] = {}
        self._attempted: set = set()

    # -- lifecycle ------------------------------------------------------
    def configure(self, config: Any) -> None:
        """Arm/disarm from the ``cost_plane*`` knobs. Does NOT clear the
        ledger: one process can accumulate several scenarios (the CI gate
        trains every learner and predicts through every engine into one
        ledger). Last configure wins, matching the telemetry knobs."""
        out = getattr(config, "cost_plane_out", "") or ""
        self.enabled = bool(getattr(config, "cost_plane", False)) or bool(out)
        if out:
            self.out_path = out
        self.memory_mode = getattr(config, "cost_plane_memory", "compiled")
        self._peaks_override = getattr(config, "cost_plane_peaks", "") or ""

    def reset(self) -> None:
        with self._lock:
            self.entries.clear()
            self.calls.clear()
            self.walls.clear()
            self._attempted.clear()

    # -- capture --------------------------------------------------------
    def observed_call(self, program: str, fn: Any, args: tuple,
                      kwargs: Optional[dict] = None, *, bucket: Any = "",
                      phase: str = "", shard_spec: str = "") -> Any:
        """Dispatch ``fn(*args, **kwargs)``, recording its analytic cost
        once per (program, bucket). The disarmed path is one attribute
        test; capture failures are swallowed (debug-logged) so the plane
        can never break a training or serving run."""
        kwargs = kwargs or {}
        if not self.enabled:
            return fn(*args, **kwargs)
        key = f"{program}|{bucket}"
        capture = False
        with self._lock:
            self.calls[key] = self.calls.get(key, 0) + 1
            if key not in self._attempted:
                # mark BEFORE trying: a capture that fails must not retry
                # on every subsequent dispatch of a hot program
                self._attempted.add(key)
                capture = True
        if capture and self._trace_clean():
            try:
                entry = self._capture(fn, args, kwargs)
            except Exception as e:  # pragma: no cover - backend-dependent
                log.debug("cost plane: capture of %s failed: %s", key, e)
            else:
                entry.update(program=program, bucket=str(bucket),
                             phase=phase, shard_spec=shard_spec)
                with self._lock:
                    self.entries[key] = entry
        elif capture:
            with self._lock:
                # under a tracer (e.g. an engine dispatched inside the
                # predict_stream scorer) the abstract args cannot be
                # re-traced; allow a later concrete call to capture
                self._attempted.discard(key)
        return fn(*args, **kwargs)

    @staticmethod
    def _trace_clean() -> bool:
        try:
            import jax
            return bool(jax.core.trace_state_clean())
        except Exception:  # pragma: no cover - jax internals moved
            return True

    def _capture(self, fn: Any, args: tuple, kwargs: dict) -> Dict[str, Any]:
        """AOT-inspect one dispatch: analytic cost from the lowering; HBM
        from the compiled executable (``cost_plane_memory=compiled``) or
        from aval arithmetic (``analytic`` — no second backend compile)."""
        import jax

        lowered = fn.trace(*args, **kwargs).lower()
        cost = lowered.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):  # some backends return a list
            cost = cost[0] if cost else {}
        flops = float(cost.get("flops", 0.0) or 0.0)
        transcendentals = float(cost.get("transcendentals", 0.0) or 0.0)
        bytes_accessed = float(cost.get("bytes accessed", 0.0) or 0.0)
        arg_bytes = sum(_leaf_nbytes(a) for a in jax.tree_util.tree_leaves(
            (args, kwargs)))
        out_bytes = sum(_leaf_nbytes(a) for a in jax.tree_util.tree_leaves(
            lowered.out_info))
        temp_bytes = 0
        code_bytes = 0
        source = "analytic"
        if self.memory_mode == "compiled":
            try:
                ma = lowered.compile().memory_analysis()
                arg_bytes = int(ma.argument_size_in_bytes)
                out_bytes = int(ma.output_size_in_bytes)
                temp_bytes = int(ma.temp_size_in_bytes)
                code_bytes = int(ma.generated_code_size_in_bytes)
                source = "compiled"
            except Exception as e:  # pragma: no cover - backend-dependent
                log.debug("cost plane: memory_analysis unavailable (%s); "
                          "falling back to aval arithmetic", e)
        if source == "analytic":
            # XLA's bytes-accessed counts operand + output + intermediate
            # traffic; what is neither argument nor output bounds the
            # temporaries a fused program touches
            temp_bytes = int(max(0.0, bytes_accessed - arg_bytes
                                 - out_bytes))
        peak_hbm = int(arg_bytes + out_bytes + temp_bytes + code_bytes)
        dev = jax.devices()[0]
        return {
            "flops": flops,
            "transcendentals": transcendentals,
            "bytes_accessed": bytes_accessed,
            "arg_bytes": int(arg_bytes),
            "out_bytes": int(out_bytes),
            "temp_bytes": int(temp_bytes),
            "code_bytes": int(code_bytes),
            "peak_hbm_bytes": peak_hbm,
            "memory_source": source,
            "arithmetic_intensity": round(flops / bytes_accessed, 4)
            if bytes_accessed > 0 else None,
            "backend": dev.platform,
            "device_kind": dev.device_kind,
            "num_devices": jax.device_count(),
        }

    def record_host(self, program: str, *, flops: float,
                    bytes_accessed: float, peak_hbm_bytes: int,
                    phase: str = "", bucket: Any = "") -> None:
        """Ledger entry for a host-evaluated program (SHAP's numpy loop):
        same schema, ``memory_source="host_analytic"``, counted once per
        (program, bucket) like a captured executable."""
        if not self.enabled:
            return
        key = f"{program}|{bucket}"
        with self._lock:
            self.calls[key] = self.calls.get(key, 0) + 1
            if key in self.entries:
                return
            self._attempted.add(key)
            self.entries[key] = {
                "program": program, "bucket": str(bucket), "phase": phase,
                "shard_spec": "", "flops": float(flops),
                "transcendentals": 0.0,
                "bytes_accessed": float(bytes_accessed),
                "arg_bytes": int(bytes_accessed), "out_bytes": 0,
                "temp_bytes": 0, "code_bytes": 0,
                "peak_hbm_bytes": int(peak_hbm_bytes),
                "memory_source": "host_analytic",
                "arithmetic_intensity": round(flops / bytes_accessed, 4)
                if bytes_accessed > 0 else None,
                "backend": "host", "device_kind": "host", "num_devices": 0,
            }

    # -- wall joins ------------------------------------------------------
    def note_wall(self, phase: str, seconds: float, calls: int = 1) -> None:
        """Accumulate measured device-complete wall for ``phase``; joined
        against the ledger's analytic totals by :meth:`attribution`."""
        if not self.enabled or seconds < 0:
            return
        with self._lock:
            w = self.walls.setdefault(phase, {"seconds": 0.0, "calls": 0})
            w["seconds"] += float(seconds)
            w["calls"] += int(calls)

    def wall(self, phase: str) -> _WallSpan:
        """``with PLANE.wall("predict"): ...`` measured-wall bracket; the
        body must end device-complete (see _WallSpan)."""
        return _WallSpan(self, phase)

    # -- attribution -----------------------------------------------------
    def peaks(self) -> Dict[str, Any]:
        """The active peak row: ``cost_plane_peaks="flops:bw:hbm"``
        override, else the table row matched on device_kind."""
        if self._peaks_override:
            try:
                f, bw, hbm = (float(x) for x in
                              self._peaks_override.split(":"))
                return {"name": "override", "flops": f, "bandwidth": bw,
                        "hbm": hbm, "measured": True}
            except ValueError:
                log.warning("cost plane: bad cost_plane_peaks %r (want "
                            "'flops:bandwidth:hbm_bytes'); using the "
                            "table", self._peaks_override)
        kind = "cpu"
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception as e:  # pragma: no cover - backendless process
            log.debug("cost plane: no device kind (%s); using cpu row", e)
        for needles, row in _PEAK_TABLE:
            if any(n in kind for n in needles):
                return dict(row)
        return dict(_PEAK_TABLE[-1][1])

    def attribution(self) -> Dict[str, Any]:
        """Per-phase roofline join: total analytic flops/bytes (entry x
        observed calls) vs the peak table, against the measured wall.
        ``bound`` says which roofline arm dominates; ``roofline_s`` is the
        attainable floor; ``fraction_of_roofline`` = floor / wall (1.0 =
        the phase runs at the machine's analytic limit)."""
        peaks = self.peaks()
        with self._lock:
            entries = {k: dict(v) for k, v in self.entries.items()}
            calls = dict(self.calls)
            walls = {k: dict(v) for k, v in self.walls.items()}
        phases: Dict[str, Dict[str, float]] = {}
        for key, e in entries.items():
            ph = e.get("phase") or "unattributed"
            n = calls.get(key, 1)
            agg = phases.setdefault(ph, {"flops": 0.0, "bytes": 0.0,
                                         "calls": 0})
            agg["flops"] += e["flops"] * n
            agg["bytes"] += e["bytes_accessed"] * n
            agg["calls"] += n
        out: Dict[str, Any] = {"peaks": peaks, "phases": {}}
        for ph, agg in sorted(phases.items()):
            t_flop = agg["flops"] / peaks["flops"]
            t_byte = agg["bytes"] / peaks["bandwidth"]
            roofline_s = max(t_flop, t_byte)
            rec: Dict[str, Any] = {
                "flops_total": agg["flops"],
                "bytes_total": agg["bytes"],
                "calls": int(agg["calls"]),
                "bound": "flop" if t_flop >= t_byte else "byte",
                "roofline_s": round(roofline_s, 6),
            }
            wall = walls.get(ph, {}).get("seconds", 0.0)
            if wall > 0:
                rec["wall_s"] = round(wall, 6)
                rec["fraction_of_roofline"] = round(
                    min(roofline_s / wall, 1.0), 4)
                rec["fraction_of_roofline_uncapped"] = round(
                    roofline_s / wall, 4)
            out["phases"][ph] = rec
        return out

    # -- export ----------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        """The COSTS.json document (schema in docs/observability.md)."""
        backend, kind, n_dev = "unknown", "unknown", 0
        try:
            import jax
            d = jax.devices()[0]
            backend, kind = d.platform, d.device_kind
            n_dev = jax.device_count()
        except Exception as e:  # pragma: no cover - backendless process
            log.debug("cost plane: no backend identity for the ledger "
                      "header (%s)", e)
        with self._lock:
            entries = {k: dict(v, calls=self.calls.get(k, 0))
                       for k, v in sorted(self.entries.items())}
            walls = {k: {"seconds": round(v["seconds"], 6),
                         "calls": int(v["calls"])}
                     for k, v in sorted(self.walls.items())}
        return {
            "schema_version": SCHEMA_VERSION,
            "backend": backend,
            "device_kind": kind,
            "num_devices": n_dev,
            "peaks": self.peaks(),
            "entries": entries,
            "walls": walls,
            "attribution": self.attribution(),
        }

    def write(self, path: Optional[str] = None) -> Optional[str]:
        """Persist the ledger (atomic replace — the file on disk is always
        a complete document, like flight-recorder dumps)."""
        path = path or self.out_path
        if not path or not self.enabled:
            return None
        from ..guard.snapshot import atomic_write_text
        atomic_write_text(path, json.dumps(self.to_json(), indent=1,
                                           sort_keys=True) + "\n")
        return path

    def by_program(self) -> Dict[str, Dict[str, float]]:
        """Program-level maxima over padding buckets (what the budget file
        records: the hot bucket is the binding one)."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for key, e in self.entries.items():
                p = e["program"]
                agg = out.setdefault(p, {"bytes_accessed": 0.0,
                                         "peak_hbm_bytes": 0.0,
                                         "flops": 0.0, "calls": 0})
                agg["bytes_accessed"] = max(agg["bytes_accessed"],
                                            e["bytes_accessed"])
                agg["peak_hbm_bytes"] = max(agg["peak_hbm_bytes"],
                                            e["peak_hbm_bytes"])
                agg["flops"] = max(agg["flops"], e["flops"])
                agg["calls"] += self.calls.get(key, 0)
        return out

    def train_traffic(self, iterations: int) -> Optional[Dict[str, Any]]:
        """Measured train-side traffic per iteration for bench.py's
        roofline: total bytes/flops of the train-phase entries scaled by
        observed calls, divided by the iteration count. None when the
        ledger holds no train programs."""
        train_phases = ("histogram", "split", "partition", "tree",
                        "layout_apply")
        flops = bytes_a = 0.0
        n = 0
        with self._lock:
            for key, e in self.entries.items():
                if e.get("phase") in train_phases:
                    c = self.calls.get(key, 1)
                    flops += e["flops"] * c
                    bytes_a += e["bytes_accessed"] * c
                    n += 1
        if n == 0 or iterations <= 0:
            return None
        return {"programs": n,
                "bytes_per_iter": bytes_a / iterations,
                "flops_per_iter": flops / iterations}


#: the process-global ledger every capture site feeds
PLANE = CostPlane()


def observed_call(program: str, fn: Any, args: tuple,
                  kwargs: Optional[dict] = None, *, bucket: Any = "",
                  phase: str = "", shard_spec: str = "") -> Any:
    """Module-level convenience over ``PLANE.observed_call`` (the form the
    capture sites use; keeps their import surface to one name)."""
    return PLANE.observed_call(program, fn, args, kwargs, bucket=bucket,
                               phase=phase, shard_spec=shard_spec)
