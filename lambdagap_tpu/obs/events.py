"""Structured JSONL run log: the machine-readable training artifact.

One ``telemetry_out=`` file per run; every line is one JSON object. This is
the artifact BENCH_r0N trajectories and regression triage diff against, so
the schema is versioned and validated (``validate_record`` /
``validate_file`` — used by tests/test_obs.py and the run_full_suite.sh
telemetry gate).

Record types (``"type"`` field; full table in docs/observability.md):

- ``run_header`` — first line: schema version, wall time, the resolved
  training params, device topology, package versions.
- ``iteration`` — one per boosting iteration: ``iter``, device-complete
  ``wall_s``, the per-phase exclusive-seconds map ``phases``, ``compiles``
  (total / steady-state / per-phase) and ``transfers`` counters.
- ``event`` — anything punctual: steady-state recompile warnings, profiler
  window start/stop, serve swaps, errors, and the guard layer's
  ``guard_nonfinite`` diagnostics (lambdagap_tpu.guard: policy + iteration
  when gradients/hessians/scores went non-finite — the last record a
  ``guard_nonfinite=raise`` run writes before failing).

Writes flush per line: a crashed run keeps every completed record (the
whole point of a flight recorder).
"""
from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# iteration-record required keys -> validator (docs/observability.md schema)
_ITER_REQUIRED = {
    "iter": lambda v: isinstance(v, int) and v >= 0,
    "wall_s": lambda v: isinstance(v, (int, float)) and v >= 0,
    "phases": lambda v: isinstance(v, dict) and all(
        isinstance(k, str) and isinstance(x, (int, float))
        for k, x in v.items()),
    "compiles": lambda v: isinstance(v, dict) and "total" in v
    and "steady" in v,
    "transfers": lambda v: isinstance(v, dict) and "total" in v,
}


def run_header(params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The run-identity record: enough to reproduce and to diff two runs'
    environments without parsing logs."""
    header: Dict[str, Any] = {
        "type": "run_header",
        "schema_version": SCHEMA_VERSION,
        "time_unix": time.time(),
        "params": params or {},
        "versions": {"python": sys.version.split()[0]},
    }
    try:
        import jax
        header["device"] = {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "devices": [str(d) for d in jax.devices()],
        }
        # the registry mesh axes (parallel/sharding.py): run logs of
        # distributed trainings are diffable on mesh geometry — the
        # actual placement rides params (tpu_num_devices/mesh_shape)
        from ..parallel.sharding import MESH_AXES
        header["device"]["mesh_axes"] = list(MESH_AXES)
        header["versions"]["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax import is repo-wide
        header["device"] = {}
    try:
        import numpy
        header["versions"]["numpy"] = numpy.__version__
    except Exception:  # pragma: no cover
        pass
    return header


class RunLog:
    """Line-per-record JSONL writer with per-line flush."""

    def __init__(self, path: str,
                 params: Optional[Dict[str, Any]] = None) -> None:
        self.path = path
        self._f = open(path, "w", encoding="utf-8")
        self.write(run_header(params))

    def write(self, record: Dict[str, Any]) -> None:
        if self._f is None:
            return
        self._f.write(json.dumps(record, separators=(",", ":"),
                                 default=_json_default) + "\n")
        self._f.flush()

    def event(self, event: str, **fields: Any) -> None:
        self.write({"type": "event", "event": event,
                    "time_unix": time.time(), **fields})

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def _json_default(o):
    """Last-resort coercion for numpy scalars riding in records."""
    for attr in ("item",):
        if hasattr(o, attr):
            return o.item()
    return str(o)


# ---------------------------------------------------------------------------
# schema validation (tests + the run_full_suite.sh telemetry gate)
# ---------------------------------------------------------------------------
def validate_record(obj: Any) -> List[str]:
    """Errors for one parsed JSONL record; empty when valid."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not an object"]
    rtype = obj.get("type")
    if rtype not in ("run_header", "iteration", "event"):
        return [f"unknown record type {rtype!r}"]
    if rtype == "run_header":
        if obj.get("schema_version") != SCHEMA_VERSION:
            errs.append(f"schema_version {obj.get('schema_version')!r} != "
                        f"{SCHEMA_VERSION}")
        if not isinstance(obj.get("params"), dict):
            errs.append("run_header.params must be an object")
    elif rtype == "iteration":
        for key, check in _ITER_REQUIRED.items():
            if key not in obj:
                errs.append(f"iteration record missing {key!r}")
            elif not check(obj[key]):
                errs.append(f"iteration.{key} failed validation: "
                            f"{obj[key]!r}")
    elif rtype == "event":
        if not isinstance(obj.get("event"), str):
            errs.append("event record missing 'event' name")
    return errs


def validate_file(path: str) -> List[str]:
    """Validate a whole JSONL run log. Returns a list of
    ``"line N: problem"`` strings; empty means the file conforms (non-empty,
    parses line-by-line, leads with a run_header, every record valid)."""
    errs: List[str] = []
    n_lines = 0
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n_lines += 1
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                errs.append(f"line {i}: not JSON ({e})")
                continue
            if n_lines == 1 and (not isinstance(obj, dict)
                                 or obj.get("type") != "run_header"):
                errs.append(f"line {i}: first record must be a run_header")
            for e in validate_record(obj):
                errs.append(f"line {i}: {e}")
    if n_lines == 0:
        errs.append("empty run log")
    return errs
