"""Structured JSONL run log: the machine-readable training artifact.

One ``telemetry_out=`` file per run; every line is one JSON object. This is
the artifact BENCH_r0N trajectories and regression triage diff against, so
the schema is versioned and validated (``validate_record`` /
``validate_file`` — used by tests/test_obs.py and the run_full_suite.sh
telemetry gate).

Record types (``"type"`` field; full table in docs/observability.md):

- ``run_header`` — first line: schema version, wall time, the resolved
  training params, device topology, package versions.
- ``iteration`` — one per boosting iteration: ``iter``, device-complete
  ``wall_s``, the per-phase exclusive-seconds map ``phases``, ``compiles``
  (total / steady-state / per-phase) and ``transfers`` counters.
- ``event`` — anything punctual: steady-state recompile warnings, profiler
  window start/stop, serve swaps, errors, and the guard layer's
  ``guard_nonfinite`` diagnostics (lambdagap_tpu.guard: policy + iteration
  when gradients/hessians/scores went non-finite — the last record a
  ``guard_nonfinite=raise`` run writes before failing).
- ``span`` — one hop of a distributed request trace (obs/trace.py): trace
  / span / parent ids, span name, recording process, epoch start ``t0``
  and duration ``dur`` — the record type trace logs and flight-recorder
  dumps are made of.
- ``signals`` — one tick of the derived control-signal plane
  (obs/signals.py): goodput-knee, residency/eviction-pressure, and
  per-replica health signals, validated by that module's own schema.

Writes flush per line (or on a small bounded interval for high-rate span
logs): a crashed run keeps every completed record — the whole point of a
flight recorder. Reading tolerates the complement: a process SIGKILLed
mid-write leaves a final line without its newline, which
:func:`validate_file` / :func:`read_file` report as truncation, not as a
corrupt file.
"""
from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

SCHEMA_VERSION = 1

# iteration-record required keys -> validator (docs/observability.md schema)
_ITER_REQUIRED = {
    "iter": lambda v: isinstance(v, int) and v >= 0,
    "wall_s": lambda v: isinstance(v, (int, float)) and v >= 0,
    "phases": lambda v: isinstance(v, dict) and all(
        isinstance(k, str) and isinstance(x, (int, float))
        for k, x in v.items()),
    "compiles": lambda v: isinstance(v, dict) and "total" in v
    and "steady" in v,
    "transfers": lambda v: isinstance(v, dict) and "total" in v,
}

# span-record required keys (obs/trace.py; docs/observability.md span table)
_SPAN_REQUIRED = {
    "trace": lambda v: isinstance(v, str) and v != "",
    "span": lambda v: isinstance(v, str) and v != "",
    "name": lambda v: isinstance(v, str) and v != "",
    "t0": lambda v: isinstance(v, (int, float)) and v >= 0,
    "dur": lambda v: isinstance(v, (int, float)) and v >= 0,
}


def run_header(params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The run-identity record: enough to reproduce and to diff two runs'
    environments without parsing logs."""
    header: Dict[str, Any] = {
        "type": "run_header",
        "schema_version": SCHEMA_VERSION,
        "time_unix": time.time(),
        "params": params or {},
        "versions": {"python": sys.version.split()[0]},
    }
    try:
        import jax
        header["device"] = {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "devices": [str(d) for d in jax.devices()],
        }
        # the registry mesh axes (parallel/sharding.py): run logs of
        # distributed trainings are diffable on mesh geometry — the
        # actual placement rides params (tpu_num_devices/mesh_shape)
        from ..parallel.sharding import MESH_AXES
        header["device"]["mesh_axes"] = list(MESH_AXES)
        header["versions"]["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax import is repo-wide
        header["device"] = {}
    try:
        import numpy
        header["versions"]["numpy"] = numpy.__version__
    except Exception:  # pragma: no cover
        pass
    return header


class RunLog:
    """Line-per-record JSONL writer. Flushes per record by default;
    ``flush_every > 1`` batches flushes for high-rate writers (span logs)
    while a ``flush_interval_s`` clock bounds the worst-case data loss a
    SIGKILL can cause — the reader side tolerates the torn final line."""

    def __init__(self, path: str,
                 params: Optional[Dict[str, Any]] = None,
                 flush_every: int = 1,
                 flush_interval_s: float = 0.25) -> None:
        self.path = path
        self._f = open(path, "w", encoding="utf-8")
        self._flush_every = max(int(flush_every), 1)
        self._flush_interval = float(flush_interval_s)
        self._unflushed = 0
        self._last_flush = time.perf_counter()
        self.write(run_header(params))

    def write(self, record: Dict[str, Any]) -> None:
        if self._f is None:
            return
        self._f.write(json.dumps(record, separators=(",", ":"),
                                 default=_json_default) + "\n")
        self._unflushed += 1
        now = time.perf_counter()
        if (self._unflushed >= self._flush_every
                or now - self._last_flush >= self._flush_interval):
            self._f.flush()
            self._unflushed = 0
            self._last_flush = now

    def event(self, event: str, **fields: Any) -> None:
        self.write({"type": "event", "event": event,
                    "time_unix": time.time(), **fields})

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def _json_default(o):
    """Last-resort coercion for numpy scalars riding in records."""
    for attr in ("item",):
        if hasattr(o, attr):
            return o.item()
    return str(o)


# ---------------------------------------------------------------------------
# schema validation (tests + the run_full_suite.sh telemetry gate)
# ---------------------------------------------------------------------------
def validate_record(obj: Any) -> List[str]:
    """Errors for one parsed JSONL record; empty when valid."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not an object"]
    rtype = obj.get("type")
    if rtype not in ("run_header", "iteration", "event", "span", "signals"):
        return [f"unknown record type {rtype!r}"]
    if rtype == "run_header":
        if obj.get("schema_version") != SCHEMA_VERSION:
            errs.append(f"schema_version {obj.get('schema_version')!r} != "
                        f"{SCHEMA_VERSION}")
        if not isinstance(obj.get("params"), dict):
            errs.append("run_header.params must be an object")
    elif rtype == "iteration":
        for key, check in _ITER_REQUIRED.items():
            if key not in obj:
                errs.append(f"iteration record missing {key!r}")
            elif not check(obj[key]):
                errs.append(f"iteration.{key} failed validation: "
                            f"{obj[key]!r}")
    elif rtype == "event":
        if not isinstance(obj.get("event"), str):
            errs.append("event record missing 'event' name")
    elif rtype == "span":
        for key, check in _SPAN_REQUIRED.items():
            if key not in obj:
                errs.append(f"span record missing {key!r}")
            elif not check(obj[key]):
                errs.append(f"span.{key} failed validation: {obj[key]!r}")
        parent = obj.get("parent")
        if parent is not None and not isinstance(parent, str):
            errs.append(f"span.parent must be a string or null, "
                        f"got {parent!r}")
    elif rtype == "signals":
        if not isinstance(obj.get("time_unix"), (int, float)):
            errs.append("signals record missing 'time_unix'")
        if not isinstance(obj.get("goodput"), dict):
            errs.append("signals record missing 'goodput' block")
    return errs


def _scan_file(path: str) -> Tuple[List[Tuple[int, Any]], List[str], bool]:
    """Shared reader: ((line_no, parsed), errors, truncated). A final
    line with NO trailing newline that fails to parse is a SIGKILL-torn
    tail: reported as truncation, never as an error — the flight-recorder
    / postmortem path reads logs from hard-killed processes."""
    errs: List[str] = []
    records: List[Tuple[int, Any]] = []
    truncated = False
    # errors="replace": a dump torn mid-byte-sequence (SIGKILL during a
    # non-atomic copy, a half-recovered disk) must degrade to a torn/
    # garbage LINE — which the per-line parse below already tolerates —
    # not to a UnicodeDecodeError that loses the whole file's evidence
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        content = f.read()
    lines = content.split("\n")
    last_complete = len(lines) - 1       # split leaves "" after a final \n
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            if i > last_complete:        # the newline-less final line
                truncated = True
                continue
            errs.append(f"line {i}: not JSON ({e})")
            continue
        records.append((i, obj))
    return records, errs, truncated


def validate_file(path: str) -> List[str]:
    """Validate a whole JSONL run log. Returns a list of
    ``"line N: problem"`` strings; empty means the file conforms (non-empty,
    parses line-by-line, leads with a run_header, every record valid). A
    torn final line — one cut mid-write, without its newline — is
    tolerated: everything before it still validates (SIGKILLed serve
    processes leave exactly this shape)."""
    records, errs, _truncated = _scan_file(path)
    for n, (i, obj) in enumerate(records):
        if n == 0 and (not isinstance(obj, dict)
                       or obj.get("type") != "run_header"):
            errs.append(f"line {i}: first record must be a run_header")
        for e in validate_record(obj):
            errs.append(f"line {i}: {e}")
    if not records:
        errs.append("empty run log")
    return errs


def read_file(path: str) -> Tuple[List[Dict[str, Any]], bool]:
    """(records, truncated): every parseable record in file order, plus
    whether a torn final line was dropped. The lenient reader the
    postmortem tooling uses — unparseable interior lines are skipped, not
    fatal (a half-recovered disk is still evidence)."""
    records, _errs, truncated = _scan_file(path)
    return [obj for _i, obj in records if isinstance(obj, dict)], truncated
