"""Fleet metric plane: scrape every replica, merge into ONE snapshot.

PR 9 built the fleet but its metrics stayed per-process: each replica
answers ``stats json`` / ``prometheus`` for itself, and an operator sizing
the fleet had to eyeball N expositions. This module is the merge:

- :func:`merge_snapshots` folds N ``ServeStats.snapshot()`` dicts into one
  fleet-shaped snapshot with the SAME schema — counter sums are exact,
  latency quantiles are weight-correct reservoir merges
  (:func:`obs.reservoir.merge_states`: each replica's sample weighted by
  its true stream size), and the per-model / per-tenant label breakdowns
  roll up label-preservingly (the per-tenant view an operator bills from
  survives the merge).
- :class:`FleetScraper` pulls the per-replica snapshots over the existing
  surfaces — ``ForestServer.stats_snapshot`` in-process,
  ``FrontendClient.stats`` over the wire — strictly OUTSIDE any router
  lock (a blocking scrape under a dispatch lock would convoy the request
  path; graftlint R5/R9 watch this file for exactly that), optionally on
  a background interval, feeding every scrape to the signal plane
  (obs/signals.py) that ROADMAP item 2's autonomics consume.

The router exposes the result as ``Router.fleet_snapshot()`` and the
``prometheus fleet`` verb (docs/serving.md): one exposition for the whole
fleet, served from the frontend that fronts it.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils import log
from .reservoir import merge_states, valid_state

# plain summable counters of the ServeStats snapshot schema
_SUM_KEYS = ("requests", "rows", "errors", "timeouts", "rejected",
             "swap_failures", "swaps", "evictions", "readmissions",
             "throughput_rps", "throughput_rows_per_s")
_RES_KEYS = ("latency_ms", "queue_wait_ms", "device_ms")
_GROUP_SUM_KEYS = ("requests", "rows", "shed", "rejected", "evictions",
                   "readmissions")


def _merge_quantiles(snaps: List[Dict], key: str) -> Dict[str, float]:
    """Reservoir-merge one latency distribution across snapshots. Falls
    back to a request-weighted mean of the published percentiles when a
    snapshot carries no reservoir state (an old replica mid-rolling-
    restart must not break the fleet view) — flagged ``"approx"``."""
    states = [s.get("reservoirs", {}).get(key) for s in snaps]
    if any(valid_state(st) for st in states):
        return merge_states(states).percentiles()
    out: Dict[str, float] = {}
    total = sum(s.get("requests", 0) for s in snaps) or 1
    for s in snaps:
        w = s.get("requests", 0) / total
        for q, v in (s.get(key) or {}).items():
            out[q] = out.get(q, 0.0) + w * float(v)
    if out:
        out["approx"] = 1.0
    return out


def _merge_groups(snaps: List[Dict], block_key: str) -> Dict[str, Dict]:
    """Label-preserving rollup of ``per_model`` / ``per_tenant`` blocks:
    union of keys, counter sums, reservoir-merged latency per key."""
    names: List[str] = []
    for s in snaps:
        for k in (s.get(block_key) or {}):
            if k not in names:
                names.append(k)
    out: Dict[str, Dict] = {}
    for name in sorted(names):
        groups = [s.get(block_key, {}).get(name) for s in snaps]
        groups = [g for g in groups if g]
        merged: Dict[str, Any] = {k: sum(g.get(k, 0) for g in groups)
                                  for k in _GROUP_SUM_KEYS}
        states = [g.get("latency_state") for g in groups]
        if any(valid_state(st) for st in states):
            merged["latency_ms"] = merge_states(states).percentiles()
        else:
            lats = [g.get("latency_ms") or {} for g in groups]
            total = sum(g.get("requests", 0) for g in groups) or 1
            merged["latency_ms"] = {}
            for g, lat in zip(groups, lats):
                w = g.get("requests", 0) / total
                for q, v in lat.items():
                    merged["latency_ms"][q] = (
                        merged["latency_ms"].get(q, 0.0) + w * float(v))
        out[name] = merged
    return out


def _merge_registry(snaps: List[Dict]) -> Optional[Dict]:
    regs = [s.get("registry") for s in snaps if s.get("registry")]
    if not regs:
        return None
    names: List[str] = []
    for r in regs:
        for k in (r.get("models") or {}):
            if k not in names:
                names.append(k)
    models: Dict[str, Dict] = {}
    for name in sorted(names):
        entries = [r.get("models", {}).get(name) for r in regs]
        entries = [e for e in entries if e]
        models[name] = {
            "replicas": len(entries),
            "resident_replicas": sum(1 for e in entries
                                     if e.get("resident")),
            "resident": any(e.get("resident") for e in entries),
            "builds": sum(e.get("builds", 0) for e in entries),
            "hbm_bytes": sum(e.get("hbm_bytes", 0) for e in entries),
        }
    return {
        "models": models,
        "registered_models": len(models),
        "resident_models": sum(1 for m in models.values()
                               if m["resident"]),
        "hbm_bytes_resident": sum(r.get("hbm_bytes_resident", 0)
                                  for r in regs),
        "hbm_budget_bytes": sum(r.get("hbm_budget_bytes", 0)
                                for r in regs),
    }


def merge_snapshots(snaps: List[Dict]) -> Dict:
    """N per-replica ``ServeStats.snapshot()`` dicts -> ONE snapshot of
    the same schema, counters summed exactly and quantiles merged
    weight-correctly. Unreachable-replica placeholders (``{"unreachable":
    ...}``) are skipped but counted."""
    live = [s for s in snaps if isinstance(s, dict)
            and "unreachable" not in s]
    out: Dict[str, Any] = {k: sum(s.get(k, 0) for s in live)
                           for k in _SUM_KEYS}
    out["elapsed_s"] = max([s.get("elapsed_s", 0.0) for s in live],
                           default=0.0)
    n_batches = sum(s.get("batches", {}).get("count", 0) for s in live)
    batch_rows = sum(s.get("batches", {}).get("count", 0)
                     * s.get("batches", {}).get("mean_rows", 0.0)
                     for s in live)
    out["batches"] = {"count": n_batches,
                      "mean_rows": batch_rows / n_batches
                      if n_batches else 0.0}
    rows = sum(s.get("rows", 0) for s in live)
    out["device_us_per_row"] = (
        sum(s.get("device_us_per_row", 0.0) * s.get("rows", 0)
            for s in live) / rows if rows else 0.0)
    for key in _RES_KEYS:
        out[key] = _merge_quantiles(live, key)
    cache: Dict[str, Any] = {}
    for k in ("hits", "misses", "forest_builds", "bucket_compiles"):
        cache[k] = sum(s.get("cache", {}).get(k, 0) for s in live)
    total = cache["hits"] + cache["misses"]
    cache["hit_rate"] = cache["hits"] / total if total else 0.0
    per_bucket: Dict[str, Dict[str, int]] = {}
    for s in live:
        for b, counts in (s.get("cache", {}).get("per_bucket") or {}).items():
            dst = per_bucket.setdefault(str(b), {"hits": 0, "misses": 0})
            dst["hits"] += counts.get("hits", 0)
            dst["misses"] += counts.get("misses", 0)
    cache["per_bucket"] = per_bucket
    out["cache"] = cache
    out["per_model"] = _merge_groups(live, "per_model")
    out["per_tenant"] = _merge_groups(live, "per_tenant")
    registry = _merge_registry(live)
    if registry is not None:
        out["registry"] = registry
    out["replica_count"] = len(live)
    out["unreachable_replicas"] = len(snaps) - len(live)
    return out


def fleet_snapshot(router_stats: Dict) -> Dict:
    """``Router.stats_snapshot(reservoirs=True)`` -> the fleet snapshot:
    the router's own dispatch counters plus the merged per-replica stats
    (schema: docs/observability.md "Fleet metric plane")."""
    replicas = router_stats.get("replicas") or {}
    return {
        "type": "fleet_snapshot",
        "time_unix": time.time(),
        "replicas": sorted(replicas),
        "router": router_stats.get("router") or {},
        "merged": merge_snapshots(list(replicas.values())),
        "per_replica_requests": {name: s.get("requests", 0)
                                 for name, s in sorted(replicas.items())
                                 if isinstance(s, dict)},
    }


class FleetScraper:
    """Periodic (or on-demand) fleet scrape -> merged snapshot -> signal
    plane.

    ``target`` is anything with ``stats_snapshot(reservoirs=True)``
    returning the router shape (a :class:`~lambdagap_tpu.serve.router.
    Router`; a single ForestServer works too via :func:`merge_snapshots`
    of one). The scrape happens entirely on the scraper's thread and
    never inside the target's dispatch locks — the router fetches each
    replica's stats outside its own lock by construction, and this class
    adds none of its own around the RPC. A failed scrape logs + records a
    flight-recorder event and keeps the previous snapshot: the signal
    plane prefers stale signals over a convoyed request path.
    """

    def __init__(self, target, interval_s: float = 0.0,
                 timeout_s: float = 2.0,
                 signals=None, recorder=None,
                 on_snapshot: Optional[Callable[[Dict], None]] = None
                 ) -> None:
        from ..guard.backoff import Backoff
        self.target = target
        self.interval_s = max(float(interval_s), 0.0)
        self.timeout_s = float(timeout_s)
        self.signals = signals
        self.on_snapshot = on_snapshot
        if recorder is None:
            from . import trace as _trace
            recorder = _trace.RECORDER
        self.recorder = recorder
        self.scrapes = 0
        self.scrape_errors = 0
        # re-scrape-after-error cadence: bounded exponential (guard/
        # backoff.py) so a fleet that is DOWN is probed gently instead of
        # hammered every interval; one good scrape resets to full rate
        base = max(self.interval_s, 0.1)
        self._err_backoff = Backoff(base_s=base, factor=2.0,
                                    max_s=max(30.0, base), jitter=0.0)
        self._latest: Optional[Dict] = None
        self._latest_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def scrape(self) -> Dict:
        """One scrape: fetch + merge + (when attached) signal update."""
        t0 = time.perf_counter()
        stats = self.target.stats_snapshot(reservoirs=True,
                                           timeout_s=self.timeout_s)
        if "replicas" not in stats:      # a bare ForestServer snapshot
            stats = {"router": {}, "replicas": {"local": stats}}
        snap = fleet_snapshot(stats)
        snap["scrape_s"] = round(time.perf_counter() - t0, 6)
        with self._latest_lock:
            self._latest = snap
            self.scrapes += 1
        if self.signals is not None:
            self.signals.update(snap)
        if self.on_snapshot is not None:
            self.on_snapshot(snap)
        return snap

    def latest(self, max_age_s: float = 0.0) -> Dict:
        """The latest merged snapshot; scrapes on demand when none exists
        yet or the cached one is older than ``max_age_s`` (0 = any cached
        snapshot is fine — the background thread keeps it fresh)."""
        with self._latest_lock:
            snap = self._latest
        if snap is not None and (max_age_s <= 0
                                 or time.time() - snap["time_unix"]
                                 <= max_age_s):
            return snap
        return self.scrape()

    # -- background loop -------------------------------------------------
    def start(self) -> "FleetScraper":
        if self.interval_s <= 0:
            raise ValueError("FleetScraper.start needs interval_s > 0 "
                             "(fleet_scrape_interval_s)")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="lambdagap-fleet-scraper")
        self._thread.start()
        log.info("fleet scraper up: every %.1fs%s", self.interval_s,
                 " -> signal plane" if self.signals is not None else "")
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if not self._err_backoff.ready():
                continue                 # backing off after failed scrapes
            try:
                self.scrape()
                self._err_backoff.note_success()
            except Exception as e:
                # a dying replica mid-scrape is expected fleet weather:
                # keep the last snapshot, note the miss, keep going —
                # at the backoff's pace, not the full scrape rate
                self.scrape_errors += 1
                delay = self._err_backoff.note_failure()
                self.recorder.event("scrape_error", error=str(e),
                                    retry_in_s=round(delay, 3))
                log.warning("fleet scraper: scrape failed (%s); keeping "
                            "the previous snapshot, next attempt in "
                            "%.1fs", e, delay)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
