"""``jax.profiler`` capture windows keyed to boosting iterations.

The coarse phase spans of :mod:`.telemetry` answer "which phase is slow";
a profiler trace answers "why". This module turns the
``profile_start_iter`` / ``profile_n_iters`` / ``profile_dir`` config knobs
into a bounded ``jax.profiler`` trace window: the trace starts when the
configured iteration begins and stops ``profile_n_iters`` iterations later,
so a 500-iteration run captures exactly the requested steady-state slice
instead of an unboundedly large trace. The fused learner's program sections
carry ``jax.named_scope`` annotations (histogram / partition / split_scan),
so the captured trace shows the same phase structure the telemetry reports.

Recipe (docs/observability.md): ``telemetry=true profile_start_iter=10
profile_n_iters=3 profile_dir=/tmp/trace`` then
``tensorboard --logdir /tmp/trace``.
"""
from __future__ import annotations

from typing import Optional

from ..utils import log


class ProfileWindow:
    """One bounded trace window; inert when ``profile_dir`` is empty or
    ``start_iter`` is negative. Exceptions from the profiler never
    propagate into training."""

    def __init__(self, start_iter: int = -1, n_iters: int = 1,
                 out_dir: str = "") -> None:
        self.start_iter = int(start_iter)
        self.n_iters = max(int(n_iters), 1)
        self.out_dir = out_dir
        self.active = False
        self.done = False

    @property
    def enabled(self) -> bool:
        return bool(self.out_dir) and self.start_iter >= 0

    def on_iteration_start(self, iteration: int) -> Optional[str]:
        """Drive the window from iteration boundaries. Returns
        "start"/"stop" when the window toggled (for the run-log event),
        else None."""
        if not self.enabled or self.done:
            return None
        if not self.active and iteration >= self.start_iter:
            try:
                import jax.profiler
                jax.profiler.start_trace(self.out_dir)
            except Exception as e:  # pragma: no cover - backend-dependent
                log.warning("profiler window could not start: %s", e)
                self.done = True
                return None
            self.active = True
            log.info("profiler trace started at iteration %d -> %s",
                     iteration, self.out_dir)
            return "start"
        if self.active and iteration >= self.start_iter + self.n_iters:
            return self._stop(iteration)
        return None

    def _stop(self, iteration: int) -> Optional[str]:
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            log.warning("profiler window could not stop cleanly: %s", e)
        self.active = False
        self.done = True
        log.info("profiler trace stopped at iteration %d (%d iterations "
                 "captured)", iteration, self.n_iters)
        return "stop"

    def close(self, iteration: int = -1) -> None:
        """Stop a window left open by a short run."""
        if self.active:
            self._stop(iteration)
