"""``jax.profiler`` capture windows keyed to training, serving or streaming.

The coarse phase spans of :mod:`.telemetry` answer "which phase is slow";
a profiler trace answers "why". This module turns the
``profile_start_iter`` / ``profile_n_iters`` / ``profile_dir`` config knobs
into a bounded ``jax.profiler`` trace window: the trace starts when the
configured iteration begins and stops ``profile_n_iters`` iterations later,
so a 500-iteration run captures exactly the requested steady-state slice
instead of an unboundedly large trace. The fused learner's program sections
carry ``jax.named_scope`` annotations (histogram / partition / split_scan),
so the captured trace shows the same phase structure the telemetry reports.

The window is unit-agnostic: training drives it per boosting iteration,
``ForestServer`` per submitted request (``profile_serve_start_req`` /
``profile_serve_n_req``) and ``predict_stream`` per scoring window
(``profile_stream_start_window`` / ``profile_stream_n_windows``), so the
"why is this phase slow" recipe works on the inference paths too. Serve
submissions arrive from many client threads, so the tick path is
lock-guarded.

Recipe (docs/observability.md): ``telemetry=true profile_start_iter=10
profile_n_iters=3 profile_dir=/tmp/trace`` then
``tensorboard --logdir /tmp/trace``.
"""
from __future__ import annotations

import threading
from typing import Optional

from ..utils import log


class ProfileWindow:
    """One bounded trace window; inert when ``out_dir`` is empty or
    ``start_iter`` is negative. Exceptions from the profiler never
    propagate into training or serving."""

    def __init__(self, start_iter: int = -1, n_iters: int = 1,
                 out_dir: str = "", unit: str = "iteration") -> None:
        self.start_iter = int(start_iter)
        self.n_iters = max(int(n_iters), 1)
        self.out_dir = out_dir
        self.unit = unit
        self.active = False
        self.done = False
        self._ticks = 0
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(self.out_dir) and self.start_iter >= 0

    def on_iteration_start(self, iteration: int) -> Optional[str]:
        """Training-loop entry point (kept for the telemetry driver):
        identical to :meth:`on_tick` with the boosting iteration as the
        count."""
        return self.on_tick(iteration)

    def tick(self) -> Optional[str]:
        """Self-counting tick for callers without a natural index (the
        serve submit path): the Nth call behaves like ``on_tick(N-1)``."""
        with self._lock:
            count = self._ticks
            self._ticks += 1
        return self.on_tick(count)

    def on_tick(self, count: int) -> Optional[str]:
        """Drive the window from unit boundaries (iteration, serve
        request, stream window — per :attr:`unit`). Returns "start"/"stop"
        when the window toggled (for the run-log event), else None.
        Thread-safe: concurrent serve submits race on the same window."""
        if not self.enabled or self.done:
            return None
        with self._lock:
            if not self.active and not self.done and count >= self.start_iter:
                return self._start_locked(count)
            if self.active and count >= self.start_iter + self.n_iters:
                return self._stop_locked(count)
            return None

    def _start_locked(self, count: int) -> Optional[str]:
        try:
            import jax.profiler
            jax.profiler.start_trace(self.out_dir)
        except Exception as e:  # pragma: no cover - backend-dependent
            log.warning("profiler window could not start: %s", e)
            self.done = True
            return None
        self.active = True
        log.info("profiler trace started at %s %d -> %s",
                 self.unit, count, self.out_dir)
        return "start"

    def _stop_locked(self, count: int) -> Optional[str]:
        try:
            import jax.profiler
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            log.warning("profiler window could not stop cleanly: %s", e)
        self.active = False
        self.done = True
        log.info("profiler trace stopped at %s %d (%d %ss captured)",
                 self.unit, count, self.n_iters, self.unit)
        return "stop"

    # back-compat name used by pre-existing callers/tests
    def _stop(self, count: int) -> Optional[str]:
        with self._lock:
            if not self.active:
                return None
            return self._stop_locked(count)

    def close(self, count: int = -1) -> None:
        """Stop a window left open by a short run."""
        self._stop(count)
