"""Prometheus text exposition for training and serving metrics.

Pure text rendering — no client library, no HTTP server: the ``task=serve``
CLI answers a ``stats`` request line with this exposition (docs/serving.md
line protocol), and anything that can scrape a file or a pipe can ingest
it. Format follows the Prometheus exposition format v0.0.4: ``# HELP`` /
``# TYPE`` headers and ``name{label="v"} value`` samples, one per line
(tests/test_obs.py parses every line against the grammar).

Metric names (full table in docs/observability.md):

- ``lambdagap_serve_*`` — rendered from a ``ServeStats.snapshot()`` dict.
- ``lambdagap_train_*`` — rendered from a :class:`~.telemetry.TrainTelemetry`.
"""
from __future__ import annotations

from typing import Dict, List, Optional


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Writer:
    def __init__(self) -> None:
        self.lines: List[str] = []

    def metric(self, name: str, value, help_: str, type_: str = "gauge",
               labels: Optional[Dict[str, str]] = None) -> None:
        self.sample_header(name, help_, type_)
        self.sample(name, value, labels)

    def sample_header(self, name: str, help_: str, type_: str) -> None:
        self.lines.append(f"# HELP {name} {help_}")
        self.lines.append(f"# TYPE {name} {type_}")

    def sample(self, name: str, value,
               labels: Optional[Dict[str, str]] = None) -> None:
        lab = ""
        if labels:
            inner = ",".join(f'{k}="{_escape(v)}"'
                             for k, v in sorted(labels.items()))
            lab = "{" + inner + "}"
        self.lines.append(f"{name}{lab} {_num(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def render_serve(snapshot: Dict) -> str:
    """``ServeStats.snapshot()`` (plus the ForestServer extras when
    present) -> Prometheus text."""
    w = _Writer()
    p = "lambdagap_serve_"
    w.metric(p + "requests_total", snapshot.get("requests", 0),
             "Served requests", "counter")
    w.metric(p + "rows_total", snapshot.get("rows", 0),
             "Served feature rows", "counter")
    w.metric(p + "errors_total", snapshot.get("errors", 0),
             "Failed requests", "counter")
    w.metric(p + "timeouts_total", snapshot.get("timeouts", 0),
             "Requests shed before dispatch (deadline expired)", "counter")
    w.metric(p + "rejected_total", snapshot.get("rejected", 0),
             "Submits rejected by full-queue backpressure", "counter")
    w.metric(p + "swap_failures_total", snapshot.get("swap_failures", 0),
             "Hot-swaps that failed and rolled back", "counter")
    w.metric(p + "throughput_rps", snapshot.get("throughput_rps", 0.0),
             "Requests per second since start")
    w.metric(p + "throughput_rows_per_s",
             snapshot.get("throughput_rows_per_s", 0.0),
             "Rows per second since start")
    for key, help_ in (("latency_ms", "End-to-end request latency (ms)"),
                       ("queue_wait_ms", "Batcher queue wait (ms)"),
                       ("device_ms", "Device dispatch share (ms)")):
        dist = snapshot.get(key, {})
        name = p + key
        w.sample_header(name, help_, "gauge")
        for q, v in sorted(dist.items()):
            w.sample(name, v, {"quantile": q})
    batches = snapshot.get("batches", {})
    w.metric(p + "batches_total", batches.get("count", 0),
             "Device batches dispatched", "counter")
    w.metric(p + "batch_mean_rows", batches.get("mean_rows", 0.0),
             "Mean rows per batch")
    w.metric(p + "device_us_per_row",
             snapshot.get("device_us_per_row", 0.0),
             "Per-dispatch device microseconds per row")
    cache = snapshot.get("cache", {})
    w.metric(p + "cache_hits_total", cache.get("hits", 0),
             "Padding-bucket executable cache hits", "counter")
    w.metric(p + "cache_misses_total", cache.get("misses", 0),
             "Padding-bucket executable cache misses", "counter")
    w.metric(p + "cache_hit_rate", cache.get("hit_rate", 0.0),
             "Cache hit fraction")
    w.metric(p + "forest_builds_total", cache.get("forest_builds", 0),
             "Device forest (re)builds", "counter")
    w.metric(p + "bucket_compiles_total", cache.get("bucket_compiles", 0),
             "Bucket executable compiles", "counter")
    w.metric(p + "compile_local_total", cache.get("compiles_local", 0),
             "Forest artifacts lowered by the local infer compiler",
             "counter")
    w.metric(p + "compile_shared_total", cache.get("compiles_shared", 0),
             "Forest builds satisfied by a fleet-shipped artifact "
             "(sha256 admission instead of a local compile)", "counter")
    w.metric(p + "packed_dispatches_total",
             cache.get("packed_dispatches", 0),
             "Cross-model pack dispatches (serve_pack_models)", "counter")
    w.metric(p + "swaps_total", snapshot.get("swaps", 0),
             "Model hot-swaps", "counter")
    w.metric(p + "evictions_total", snapshot.get("evictions", 0),
             "Registry forests evicted under the HBM budget", "counter")
    w.metric(p + "readmissions_total", snapshot.get("readmissions", 0),
             "Evicted models recompiled on first use", "counter")
    # per-model / per-tenant labeled breakdowns (docs/serving.md)
    for block_key, label in (("per_model", "model"),
                             ("per_tenant", "tenant")):
        block = snapshot.get(block_key) or {}
        if not block:
            continue
        for metric, help_, type_ in (
                ("requests_total", "Requests served", "counter"),
                ("rows_total", "Feature rows served", "counter"),
                ("shed_total", "Requests shed before dispatch", "counter"),
                ("rejected_total", "Submits rejected at admission",
                 "counter")):
            name = f"{p}{label}_{metric}"
            w.sample_header(name, f"{help_} per {label}", type_)
            key = metric.rsplit("_", 1)[0]
            for k, g in block.items():
                w.sample(name, g.get(key, 0), {label: k})
        name = f"{p}{label}_latency_ms"
        w.sample_header(name, f"End-to-end latency per {label} (ms)",
                        "gauge")
        for k, g in block.items():
            for q, v in sorted((g.get("latency_ms") or {}).items()):
                w.sample(name, v, {label: k, "quantile": q})
    registry = snapshot.get("registry")
    if registry:
        w.metric(p + "registry_models", registry.get("registered_models", 0),
                 "Models registered in the serve registry")
        w.metric(p + "registry_resident_models",
                 registry.get("resident_models", 0),
                 "Models with a resident compiled forest")
        w.metric(p + "registry_hbm_bytes",
                 registry.get("hbm_bytes_resident", 0),
                 "Resident compiled-forest bytes")
        w.metric(p + "registry_hbm_budget_bytes",
                 registry.get("hbm_budget_bytes", 0),
                 "Registry HBM byte budget (0 = unlimited)")
        name = p + "registry_model_resident"
        w.sample_header(name, "Per-model residency (1 = compiled forest "
                        "in HBM)", "gauge")
        for k, m in (registry.get("models") or {}).items():
            w.sample(name, 1 if m.get("resident") else 0, {"model": k})
    if "generation" in snapshot:
        w.metric(p + "generation", snapshot["generation"],
                 "Active model generation")
    health = snapshot.get("health")
    if health:
        # enum-as-labeled-gauge: exactly one state samples 1
        name = p + "health"
        w.sample_header(name, "Serving health state (ok/degraded/draining)",
                        "gauge")
        for state in ("ok", "degraded", "draining"):
            w.sample(name, 1 if health.get("state") == state else 0,
                     {"state": state})
        if "swap_breaker" in health:
            name = p + "swap_breaker_open"
            w.metric(name, 0 if health["swap_breaker"] == "closed" else 1,
                     "Swap circuit breaker tripped (open or probing)")
    return w.text()


def render_router(snapshot: Dict) -> str:
    """``Router.snapshot()`` -> Prometheus text: fleet-level dispatch
    counters plus per-replica routed/inflight/health labels."""
    w = _Writer()
    p = "lambdagap_router_"
    w.metric(p + "failovers_total", snapshot.get("failovers", 0),
             "Requests failed over to another replica", "counter")
    w.metric(p + "rejected_no_replica_total",
             snapshot.get("rejected_no_replica", 0),
             "Requests rejected with no live replica", "counter")
    replicas = snapshot.get("replicas") or {}
    for metric, help_, type_ in (
            ("routed_total", "Requests routed to the replica", "counter"),
            ("inflight", "Requests currently in flight", "gauge")):
        name = p + "replica_" + metric
        w.sample_header(name, help_, type_)
        key = metric.rsplit("_", 1)[0] if metric.endswith("_total") \
            else metric
        for rname, info in sorted(replicas.items()):
            w.sample(name, info.get(key, 0), {"replica": rname})
    name = p + "replica_health"
    w.sample_header(name, "Replica health (ok/degraded/draining/dead)",
                    "gauge")
    for rname, info in sorted(replicas.items()):
        for state in ("ok", "degraded", "draining", "dead"):
            w.sample(name, 1 if info.get("health") == state else 0,
                     {"replica": rname, "state": state})
    return w.text()


def render_fleet(merged: Dict, router: Optional[Dict] = None) -> str:
    """Fleet exposition (the ``prometheus fleet`` verb, docs/serving.md):
    the MERGED per-replica stats rendered through the same serve metric
    names (obs/fleet.merge_snapshots keeps the snapshot schema, so one
    scrape config covers a replica and a fleet), plus fleet-level gauges
    and — when the router's own snapshot is passed — the per-replica
    routing/health labels. Label values (model/tenant/replica names are
    user-supplied strings) go through the same exposition-format escaping
    as every other sample."""
    w = _Writer()
    p = "lambdagap_fleet_"
    w.metric(p + "replicas", merged.get("replica_count", 0),
             "Replicas merged into this exposition")
    w.metric(p + "unreachable_replicas",
             merged.get("unreachable_replicas", 0),
             "Replicas that failed the scrape (stats missing from the "
             "merge)")
    registry = merged.get("registry") or {}
    name = p + "model_resident_replicas"
    w.sample_header(name, "Replicas holding the model's compiled forest "
                    "resident", "gauge")
    for k, m in (registry.get("models") or {}).items():
        w.sample(name, m.get("resident_replicas", 0), {"model": k})
    parts = [w.text(), render_serve(merged)]
    if router:
        parts.append(render_router(router))
    return "".join(parts)


def render_train(telemetry) -> str:
    """:class:`TrainTelemetry` aggregates -> Prometheus text."""
    w = _Writer()
    p = "lambdagap_train_"
    s = telemetry.summary()
    w.metric(p + "iterations_total", s.get("iterations", 0),
             "Boosting iterations recorded", "counter")
    if not s.get("enabled"):
        return w.text()
    name = p + "phase_seconds_total"
    w.sample_header(name, "Exclusive seconds spent per phase", "counter")
    for phase, secs in s["phase_seconds_total"].items():
        w.sample(name, secs, {"phase": phase})
    name = p + "iter_wall_seconds"
    w.sample_header(name, "Device-complete per-iteration wall (s)", "gauge")
    for q, v in sorted(s["iter_wall_s"].items()):
        w.sample(name, v, {"quantile": q})
    w.metric(p + "compiles_total", s.get("compiles", 0),
             "XLA backend compiles observed", "counter")
    w.metric(p + "steady_compiles_total", s.get("steady_compiles", 0),
             "Compiles after the warmup window (R2 hazard)", "counter")
    w.metric(p + "transfers_total", s.get("transfers", 0),
             "Device transfers observed via jax.monitoring", "counter")
    w.metric(p + "compile_seconds_total", s.get("compile_secs", 0.0),
             "Seconds spent in XLA backend compiles", "counter")
    return w.text()


def render_costplane(plane=None) -> str:
    """Cost-plane ledger (obs/costplane.py) -> Prometheus text: one
    labeled sample per (program, bucket) executable for the analytic
    flops / bytes-accessed / peak-HBM facts and observed dispatch counts,
    plus the per-phase roofline join. Empty string when the plane is
    disarmed (the scrape stays byte-identical with the knob off)."""
    if plane is None:
        from .costplane import PLANE as plane
    if not plane.enabled or not plane.entries:
        return ""
    w = _Writer()
    p = "lambdagap_cost_"
    doc = plane.to_json()
    per_exec = (
        ("program_flops", "flops", "Analytic FLOPs per dispatch of the "
         "executable", "gauge"),
        ("program_bytes_accessed", "bytes_accessed", "Analytic bytes "
         "accessed per dispatch", "gauge"),
        ("program_peak_hbm_bytes", "peak_hbm_bytes", "Peak HBM of the "
         "compiled executable (arg+out+temp+code)", "gauge"),
        ("program_calls_total", "calls", "Observed dispatches of the "
         "executable", "counter"),
    )
    for name, field, help_, type_ in per_exec:
        full = p + name
        w.sample_header(full, help_, type_)
        for e in doc["entries"].values():
            w.sample(full, e.get(field, 0) or 0,
                     {"program": e["program"], "bucket": e["bucket"]})
    attr = doc["attribution"]
    name = p + "phase_roofline_seconds"
    w.sample_header(name, "Analytic roofline floor per phase (s)", "gauge")
    for phase, rec in attr["phases"].items():
        w.sample(name, rec["roofline_s"], {"phase": phase,
                                           "bound": rec["bound"]})
    name = p + "phase_roofline_fraction"
    w.sample_header(name, "Achieved fraction of the analytic roofline "
                    "(wall-joined phases only)", "gauge")
    for phase, rec in attr["phases"].items():
        if "fraction_of_roofline" in rec:
            w.sample(name, rec["fraction_of_roofline"], {"phase": phase})
    name = p + "phase_wall_seconds"
    w.sample_header(name, "Measured device-complete wall per phase (s)",
                    "counter")
    for phase, rec in doc["walls"].items():
        w.sample(name, rec["seconds"], {"phase": phase})
    return w.text()


def render(telemetry=None, serve_snapshot: Optional[Dict] = None) -> str:
    """Combined exposition; either side may be absent. The cost-plane
    section rides along whenever the ledger is armed and non-empty."""
    parts = []
    if telemetry is not None:
        parts.append(render_train(telemetry))
    if serve_snapshot is not None:
        parts.append(render_serve(serve_snapshot))
    cost = render_costplane()
    if cost:
        parts.append(cost)
    return "".join(parts)
