"""Bounded uniform reservoir sample — the percentile backbone shared by the
serve layer's latency stats and the training telemetry's iteration walls.

Lifted out of ``serve/stats.py`` (which now imports it from here) so both
sides of the system report percentiles with identical semantics: O(cap)
memory over unbounded streams, uniform replacement, exact-ish quantiles.
"""
from __future__ import annotations

import random
from typing import Dict, List


class Reservoir:
    """Bounded latency sample with uniform reservoir replacement, so
    million-request streams keep O(cap) memory but exact-ish percentiles."""

    __slots__ = ("cap", "seen", "vals", "_rng")

    def __init__(self, cap: int = 100_000, seed: int = 0) -> None:
        self.cap = cap
        self.seen = 0
        self.vals: List[float] = []
        self._rng = random.Random(seed)

    def add(self, v: float) -> None:
        self.seen += 1
        if len(self.vals) < self.cap:
            self.vals.append(v)
        else:
            j = self._rng.randrange(self.seen)
            if j < self.cap:
                self.vals[j] = v

    def percentiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
        if not self.vals:
            return {f"p{int(q * 100)}": 0.0 for q in qs} | {
                "mean": 0.0, "max": 0.0}
        s = sorted(self.vals)
        out = {}
        for q in qs:
            k = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"p{int(q * 100)}"] = s[k]
        out["mean"] = sum(s) / len(s)
        out["max"] = s[-1]
        return out
