"""Bounded uniform reservoir sample — the percentile backbone shared by the
serve layer's latency stats and the training telemetry's iteration walls.

Lifted out of ``serve/stats.py`` (which now imports it from here) so both
sides of the system report percentiles with identical semantics: O(cap)
memory over unbounded streams, uniform replacement, exact-ish quantiles.

The reservoir is a LIFTED aggregate: each kept value stands for
``seen / len(vals)`` stream items, which is exactly what makes fleet
merging possible (obs/fleet.py). :meth:`Reservoir.state` exports that
aggregate form for the wire (bounded, quantile-preserving downsample) and
:func:`merge_states` recombines N replicas' states into one
weight-correct quantile view — no resampling, no randomness, so the
merged fleet quantiles are a deterministic function of the per-replica
snapshots (the ISSUE-12 fleet-plane consistency contract).
"""
from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple


class Reservoir:
    """Bounded latency sample with uniform reservoir replacement, so
    million-request streams keep O(cap) memory but exact-ish percentiles."""

    __slots__ = ("cap", "seen", "vals", "_rng")

    def __init__(self, cap: int = 100_000, seed: int = 0) -> None:
        self.cap = cap
        self.seen = 0
        self.vals: List[float] = []
        self._rng = random.Random(seed)

    def add(self, v: float) -> None:
        self.seen += 1
        if len(self.vals) < self.cap:
            self.vals.append(v)
        else:
            j = self._rng.randrange(self.seen)
            if j < self.cap:
                self.vals[j] = v

    def percentiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
        if not self.vals:
            return {f"p{int(q * 100)}": 0.0 for q in qs} | {
                "mean": 0.0, "max": 0.0}
        s = sorted(self.vals)
        out = {}
        for q in qs:
            k = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"p{int(q * 100)}"] = s[k]
        out["mean"] = sum(s) / len(s)
        out["max"] = s[-1]
        return out

    # -- the lifted aggregate form (fleet merging, obs/fleet.py) --------
    def state(self, scale: float = 1.0, max_vals: int = 2048) -> Dict:
        """Wire form: ``{"seen": N, "vals": [...]}``. ``vals`` is the
        kept sample (optionally unit-scaled, e.g. s -> ms), downsampled
        past ``max_vals`` by evenly spaced picks from the SORTED sample —
        the downsample that moves quantiles least."""
        vals = sorted(self.vals)
        if len(vals) > max_vals:
            step = (len(vals) - 1) / (max_vals - 1)
            vals = [vals[int(round(i * step))] for i in range(max_vals)]
        return {"seen": self.seen,
                "vals": [v * scale for v in vals]}


def valid_state(s) -> bool:
    return (isinstance(s, dict) and isinstance(s.get("seen"), int)
            and isinstance(s.get("vals"), list))


class MergedReservoir:
    """Weight-correct quantile view over N reservoir states: each state's
    values carry weight ``seen / len(vals)``, so a replica that saw 10x
    the traffic moves the merged quantiles 10x as much — summing the
    underlying streams, not averaging the summaries."""

    __slots__ = ("seen", "_pairs")

    def __init__(self, pairs: Sequence[Tuple[float, float]],
                 seen: int) -> None:
        self._pairs = sorted(pairs)      # (value, weight)
        self.seen = seen

    def percentiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[str, float]:
        if not self._pairs:
            return {f"p{int(q * 100)}": 0.0 for q in qs} | {
                "mean": 0.0, "max": 0.0}
        total = sum(w for _v, w in self._pairs)
        out: Dict[str, float] = {}
        for q in qs:
            target = q * total
            cum = 0.0
            val = self._pairs[-1][0]
            for v, w in self._pairs:
                cum += w
                if cum >= target - 1e-12:
                    val = v
                    break
            out[f"p{int(q * 100)}"] = val
        out["mean"] = sum(v * w for v, w in self._pairs) / total
        out["max"] = self._pairs[-1][0]
        return out

    def state(self) -> Dict:
        """Re-export in the wire form (weights folded back by repeating
        nothing — vals keep their weights via ``seen``); good enough for
        a second-level merge of already-merged snapshots."""
        return {"seen": self.seen, "vals": [v for v, _w in self._pairs]}


def merge_states(states: Sequence[Optional[Dict]]) -> MergedReservoir:
    """Merge N ``Reservoir.state()`` dicts (Nones and malformed states
    contribute nothing — a half-scraped fleet still merges)."""
    pairs: List[Tuple[float, float]] = []
    seen = 0
    for s in states:
        if not valid_state(s) or not s["vals"]:
            continue
        w = max(s["seen"], len(s["vals"])) / len(s["vals"])
        seen += s["seen"]
        pairs.extend((float(v), w) for v in s["vals"])
    return MergedReservoir(pairs, seen)
