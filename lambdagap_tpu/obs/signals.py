"""Derived control signals: the signal plane fleet autonomics consume.

BENCH_serve priced two cliffs as one-shot bench artifacts — the open-loop
goodput knee (12.7k rps raw throughput at 0.12 goodput) and the 174x
readmission cost — and ROADMAP item 2's control loop (revival, placement,
autoscaling) is blocked on exactly those numbers being *continuously
computed online*. This module turns the fleet metric plane's scrape
stream (obs/fleet.py) into three documented signals:

``goodput`` — an online knee estimator. Each scrape yields an interval
    offered rate (Δ accepted+shed requests / Δt) and a deadline-met
    fraction (1 − Δ(timeouts+rejected+errors)/Δoffered — the server-side
    proxy for loadgen's goodput ratio; requests the server itself shed or
    failed are by definition not good). Both are EWMA-smoothed; the knee
    is the highest smoothed offered rate recently sustained at
    ``good_ratio`` goodput, decayed toward the current rate so a stale
    peak cannot hide saturation. ``knee_margin`` = (knee − offered)/knee:
    positive = headroom, near 0 = at the knee, negative = past it — the
    autoscaler's scale-out trigger.

``residency`` — per-model placement pressure from the registry counters:
    resident-replica counts, readmission and eviction rates over the
    scrape interval, ``eviction_pressure`` (evictions/s per resident
    model — how hard the HBM budget is churning), and the measured
    ``readmit_cost_ms`` (p50 of ``registry_get`` spans that paid a
    readmission, straight from the trace recorder's aggregates) — the
    input the placement loop bin-packs against.

``health`` — a bounded per-replica health timeline ring
    (:class:`HealthTimeline`): state transitions with timestamps, the
    revival loop's evidence of who died when and whether a degraded
    replica is recovering or flapping.

Every signal tick is a ``signals`` record (obs/events.py schema), so the
flight recorder and run logs carry them, and :func:`validate_signals`
checks the documented schema (docs/observability.md "Signal plane").
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

SIGNALS_VERSION = 1


class KneeEstimator:
    """Online goodput-knee estimation over the scrape stream (EWMA of
    deadline-met fraction vs offered rate over a sliding window)."""

    def __init__(self, alpha: float = 0.3, good_ratio: float = 0.9,
                 knee_decay: float = 0.02) -> None:
        self.alpha = float(alpha)
        self.good_ratio = float(good_ratio)
        self.knee_decay = float(knee_decay)
        self.offered_rps = 0.0           # EWMA
        self.good_fraction = 1.0         # EWMA
        self.knee_rps = 0.0
        self.ticks = 0

    def observe(self, offered_rps: float, good_fraction: float) -> None:
        a = self.alpha if self.ticks else 1.0
        self.offered_rps += a * (offered_rps - self.offered_rps)
        self.good_fraction += a * (good_fraction - self.good_fraction)
        self.ticks += 1
        if self.good_fraction >= self.good_ratio:
            # sustained-at-goodput rate raises the knee immediately...
            self.knee_rps = max(self.knee_rps, self.offered_rps)
        # ...and the knee decays toward the current offered rate, so a
        # long-gone traffic peak stops vouching for capacity it no longer
        # demonstrates (a knee is evidence, not a constant)
        self.knee_rps += self.knee_decay * (self.offered_rps
                                            - self.knee_rps)

    @property
    def knee_margin(self) -> float:
        """(knee − offered)/knee in [−inf, 1]; 0 when no knee is known
        yet (no headroom has been demonstrated)."""
        if self.knee_rps <= 0:
            return 0.0
        return (self.knee_rps - self.offered_rps) / self.knee_rps

    def snapshot(self) -> Dict[str, float]:
        return {
            "offered_rps": round(self.offered_rps, 3),
            "good_fraction": round(self.good_fraction, 6),
            "knee_rps": round(self.knee_rps, 3),
            "knee_margin": round(self.knee_margin, 6),
            "good_ratio": self.good_ratio,
            "ticks": self.ticks,
        }


class HealthTimeline:
    """Bounded per-replica health history: one ring of (t, replica,
    state) transitions — repeated identical states collapse, so the ring
    holds N state CHANGES, not N scrapes."""

    def __init__(self, ring: int = 256) -> None:
        self._ring: "deque" = deque(maxlen=max(int(ring), 8))
        self._last: Dict[str, str] = {}
        self._lock = threading.Lock()

    def note(self, replica: str, state: str,
             t: Optional[float] = None) -> bool:
        """Record a state observation; returns True on a TRANSITION."""
        with self._lock:
            if self._last.get(replica) == state:
                return False
            self._last[replica] = state
            self._ring.append({"t": round(t if t is not None
                                          else time.time(), 3),
                               "replica": replica, "state": state})
            return True

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"current": dict(self._last),
                    "transitions": list(self._ring)}


class SignalPlane:
    """Fold successive fleet snapshots into the signal set. One instance
    per control point (typically the router process); ``update`` is called
    by the fleet scraper per scrape, ``snapshot`` by the autonomics loop
    (and the frontend's ``signals`` verb)."""

    def __init__(self, alpha: float = 0.3, good_ratio: float = 0.9,
                 health_ring: int = 256, recorder=None) -> None:
        self.knee = KneeEstimator(alpha=alpha, good_ratio=good_ratio)
        self.health = HealthTimeline(ring=health_ring)
        self._recorder = recorder
        self._lock = threading.Lock()
        self._prev: Optional[Dict] = None
        self._latest: Optional[Dict] = None
        self._shadow: Optional[Dict] = None
        self.ticks = 0

    def note_shadow(self, shadow: Optional[Dict]) -> None:
        """The promotion controller's shadow-delta window joins the signal
        stream: subsequent ticks carry it as the OPTIONAL ``shadow`` block
        (absent unless a shadow is armed — the schema stays backward-
        compatible). Pass None to clear it."""
        with self._lock:
            self._shadow = dict(shadow) if shadow is not None else None

    # -- folding ---------------------------------------------------------
    @staticmethod
    def _offered_count(merged: Dict) -> int:
        # offered = everything that knocked: served + shed + rejected
        return (merged.get("requests", 0) + merged.get("timeouts", 0)
                + merged.get("rejected", 0))

    def update(self, fleet_snap: Dict) -> Dict:
        """One scrape tick -> the current signals dict (also cached for
        :meth:`snapshot` and recorded as a ``signals`` event)."""
        merged = fleet_snap.get("merged") or {}
        now = fleet_snap.get("time_unix") or time.time()
        with self._lock:
            prev = self._prev
            self._prev = {"t": now,
                          "offered": self._offered_count(merged),
                          "bad": (merged.get("timeouts", 0)
                                  + merged.get("rejected", 0)
                                  + merged.get("errors", 0)),
                          "evictions": merged.get("evictions", 0),
                          "readmissions": merged.get("readmissions", 0)}
        interval: Dict[str, float] = {"dt_s": 0.0, "offered_rps": 0.0,
                                      "good_fraction": 1.0}
        if prev is not None and now > prev["t"]:
            dt = now - prev["t"]
            d_off = max(self._prev["offered"] - prev["offered"], 0)
            d_bad = max(self._prev["bad"] - prev["bad"], 0)
            interval["dt_s"] = round(dt, 3)
            interval["offered_rps"] = round(d_off / dt, 3)
            interval["good_fraction"] = round(
                1.0 - d_bad / d_off, 6) if d_off else 1.0
            self.knee.observe(interval["offered_rps"],
                              interval["good_fraction"])
        residency = self._residency(merged, prev)
        for name, state in (fleet_snap.get("router", {})
                            .get("replicas") or {}).items():
            if isinstance(state, dict):
                self.health.note(name, state.get("health", "unknown"), now)
        signals = {
            "type": "signals",
            "signals_version": SIGNALS_VERSION,
            "time_unix": now,
            "interval": interval,
            "goodput": self.knee.snapshot(),
            "residency": residency,
            "health": self.health.snapshot(),
        }
        with self._lock:
            if self._shadow is not None:
                signals["shadow"] = self._shadow
            self._latest = signals
            self.ticks += 1
        if self._recorder is not None:
            # the signal tick rides the flight-recorder ring (bounded), so
            # a postmortem sees the signals the autonomics were acting on
            self._recorder.event("signals_tick",
                                 goodput=signals["goodput"],
                                 interval=interval)
        return signals

    def _residency(self, merged: Dict, prev: Optional[Dict]
                   ) -> Dict[str, Any]:
        registry = merged.get("registry") or {}
        models = registry.get("models") or {}
        dt = ((self._prev["t"] - prev["t"])
              if prev is not None and self._prev["t"] > prev["t"] else 0.0)
        evict_rate = ((self._prev["evictions"] - prev["evictions"]) / dt
                      if prev is not None and dt > 0 else 0.0)
        readmit_rate = ((self._prev["readmissions"]
                         - prev["readmissions"]) / dt
                        if prev is not None and dt > 0 else 0.0)
        resident = registry.get("resident_models", 0)
        readmit_cost_ms = 0.0
        if self._recorder is not None:
            agg = self._recorder.aggregates().get("registry_readmit")
            if agg and agg.get("count"):
                readmit_cost_ms = round(agg["p50"] * 1e3, 3)
        return {
            "registered_models": registry.get("registered_models", 0),
            "resident_models": resident,
            "hbm_bytes_resident": registry.get("hbm_bytes_resident", 0),
            "hbm_budget_bytes": registry.get("hbm_budget_bytes", 0),
            "eviction_rate_per_s": round(max(evict_rate, 0.0), 4),
            "readmission_rate_per_s": round(max(readmit_rate, 0.0), 4),
            "eviction_pressure": round(max(evict_rate, 0.0)
                                       / max(resident, 1), 6),
            "readmit_cost_ms": readmit_cost_ms,
            "per_model": {
                name: {
                    "resident_replicas": m.get("resident_replicas",
                                               1 if m.get("resident")
                                               else 0),
                    "replicas": m.get("replicas", 1),
                    "builds": m.get("builds", 0),
                    "hbm_bytes": m.get("hbm_bytes", 0),
                } for name, m in sorted(models.items())
            },
        }

    def snapshot(self) -> Dict:
        """The latest signals tick (empty-but-valid before the first)."""
        with self._lock:
            if self._latest is not None:
                return self._latest
        return {
            "type": "signals", "signals_version": SIGNALS_VERSION,
            "time_unix": time.time(),
            "interval": {"dt_s": 0.0, "offered_rps": 0.0,
                         "good_fraction": 1.0},
            "goodput": self.knee.snapshot(),
            "residency": {"registered_models": 0, "resident_models": 0,
                          "hbm_bytes_resident": 0, "hbm_budget_bytes": 0,
                          "eviction_rate_per_s": 0.0,
                          "readmission_rate_per_s": 0.0,
                          "eviction_pressure": 0.0,
                          "readmit_cost_ms": 0.0, "per_model": {}},
            "health": self.health.snapshot(),
        }


def validate_signals(obj: Any) -> List[str]:
    """Schema check for one signals tick (docs/observability.md table);
    empty list = valid. This is the contract the autonomics loop codes
    against, so it is enforced by tests, not prose."""
    errs: List[str] = []
    if not isinstance(obj, dict):
        return [f"signals is {type(obj).__name__}, not an object"]
    if obj.get("type") != "signals":
        errs.append(f"type {obj.get('type')!r} != 'signals'")
    if obj.get("signals_version") != SIGNALS_VERSION:
        errs.append(f"signals_version {obj.get('signals_version')!r} "
                    f"!= {SIGNALS_VERSION}")
    if not isinstance(obj.get("time_unix"), (int, float)):
        errs.append("missing time_unix")
    good = obj.get("goodput")
    if not isinstance(good, dict):
        errs.append("missing goodput block")
    else:
        for key in ("offered_rps", "good_fraction", "knee_rps",
                    "knee_margin"):
            if not isinstance(good.get(key), (int, float)):
                errs.append(f"goodput.{key} missing or non-numeric")
        if isinstance(good.get("knee_margin"), (int, float)) \
                and good["knee_margin"] > 1.0 + 1e-9:
            errs.append(f"goodput.knee_margin {good['knee_margin']} > 1")
    res = obj.get("residency")
    if not isinstance(res, dict):
        errs.append("missing residency block")
    else:
        for key in ("resident_models", "eviction_pressure",
                    "readmit_cost_ms", "per_model"):
            if key not in res:
                errs.append(f"residency.{key} missing")
    health = obj.get("health")
    if not isinstance(health, dict):
        errs.append("missing health block")
    elif not isinstance(health.get("transitions"), list) \
            or not isinstance(health.get("current"), dict):
        errs.append("health block needs 'current' map + 'transitions' "
                    "list")
    shadow = obj.get("shadow")
    if shadow is not None:                # OPTIONAL: only while armed
        if not isinstance(shadow, dict):
            errs.append("shadow block must be an object")
        else:
            for key in ("sample", "dead", "mirrored", "compared", "shed"):
                if key not in shadow:
                    errs.append(f"shadow.{key} missing")
    return errs
