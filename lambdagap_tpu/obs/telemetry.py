"""TrainTelemetry: per-iteration phase spans for the boosting loop.

The training analog of serve's ``ServeStats`` and the replacement for the
coarse ``utils.timer`` scopes: every boosting iteration produces one record
with named phase spans (gradients, sampling, tree, histogram, split,
partition, score_update, eval, device_wait), kept in a bounded ring buffer
and aggregated into totals + an iteration-wall reservoir. The GPU GBDT
literature (arXiv:1806.11248, arXiv:2005.09148) attributes its wins with
exactly this phase-level breakdown; here it is a first-class subsystem so
every perf PR ships its own evidence.

Timing discipline (the part that keeps this graftlint-R1 clean):

- Phase spans are host wall-clock between dispatches — they never force
  the device. Under async dispatch a span measures the time to *issue* its
  work plus any sync its phase already contains.
- Device-complete time is taken ONCE per iteration, at the boundary: a
  single ``jax.block_until_ready`` on the score state inside
  :meth:`end_iteration`, recorded as the ``device_wait`` phase. Phases +
  device_wait therefore tile the iteration wall (tests assert ±10%).
- Spans NEST with exclusive accounting: a learner-internal ``histogram``
  span carves its time out of the enclosing ``tree`` span, so the per-phase
  map sums to the wall without double counting.

The iteration record is emitted (ring + JSONL) when the NEXT iteration
begins or at :meth:`close`, which lets late phases (the engine's ``eval``)
attach to the iteration that produced them.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

from ..utils import log
from ..utils import timer as _timer
from .events import RunLog
from .profile import ProfileWindow
from .reservoir import Reservoir
from .xla_watch import XlaWatchdog

# canonical phase names (docs/observability.md); "tree" holds whatever the
# learner does not attribute to a finer phase (the fused learner's whole
# on-device program lands here — its internal structure shows up in
# profiler windows via jax.named_scope, not host spans). "layout_apply"
# is the tree_layout=sorted reorder pre-pass (the per-tree leaf-ordered
# rebuild of the packed row matrix); the in-program per-split
# permutation-apply rides the tree span like the rest of the fused program
# "h2d_prefetch" / "chunk_wait" are the data_residency=stream ring phases
# (data/stream.py ShardRing): prefetch is the host-side window fetch +
# async device_put issue, chunk_wait is the ring-slot completion block —
# together they tile the streaming overhead into the iteration wall, so
# overlap efficiency (chunk_wait ~ 0) is a measured number. "d2h_scores"
# is the predict_stream score-ring counterpart (infer/stream.py
# ScoreRing): the async copy_to_host_async issue plus the residual block
# when the result is consumed — the D2H half of the batch-scoring
# overlap story, measured the same way
PHASES = ("gradients", "sampling", "layout_apply", "histogram", "split",
          "partition", "tree", "score_update", "eval", "device_wait",
          "h2d_prefetch", "chunk_wait", "d2h_scores")

# phase -> the utils.timer scope name it replaces (the deprecation shim:
# the legacy global_timer report keeps its historical row names)
_LEGACY = {
    "gradients": "boosting: gradients",
    "sampling": "boosting: sampling",
    "score_update": "score: update",
}


class _NullSpan:
    """Reusable no-op context manager for the disabled path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live phase span; see TrainTelemetry.phase."""
    __slots__ = ("tel", "name", "legacy")

    def __init__(self, tel: "TrainTelemetry", name: str,
                 legacy: Optional[str]) -> None:
        self.tel = tel
        self.name = name
        self.legacy = legacy

    def __enter__(self):
        # stack frame: [name, t_enter, child_inclusive_acc]
        self.tel._stack.append([self.name, time.perf_counter(), 0.0])
        return self

    def __exit__(self, *exc):
        tel = self.tel
        name, t0, child = tel._stack.pop()
        dt = time.perf_counter() - t0
        tel._add_phase(name, dt - child, dt, self.legacy)
        if tel._stack:
            tel._stack[-1][2] += dt
        return False


class TrainTelemetry:
    """Per-iteration training telemetry (phase spans, ring buffer, JSONL,
    recompile watchdog, profiler windows).

    Created by ``GBDT._setup_training`` via :meth:`from_config`; reachable
    as ``booster._booster.telemetry`` and on ``CallbackEnv.telemetry``.
    All methods are no-ops when ``enabled`` is False — the off path holds
    no buffers, writes no files and registers no ``jax.monitoring`` hooks.
    """

    def __init__(self, enabled: bool = False, out: str = "",
                 ring: int = 256, warmup: int = 2,
                 profile: Optional[ProfileWindow] = None,
                 params: Optional[Dict[str, Any]] = None) -> None:
        self.enabled = bool(enabled)
        self.records: "deque[Dict]" = deque(maxlen=max(int(ring), 1))
        self.iterations = 0
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._stack: List[list] = []
        self._cur: Optional[Dict] = None
        self._t0 = 0.0
        self._train_done = False
        self._closed = False
        self.run_log: Optional[RunLog] = None
        self.watchdog: Optional[XlaWatchdog] = None
        self.profile = profile
        if not self.enabled:
            return
        self.wall_res = Reservoir(cap=4096, seed=5)
        if out:
            self.run_log = RunLog(out, params=params)
        self.watchdog = XlaWatchdog(
            warmup=warmup, phase_getter=self.current_phase,
            on_steady_compile=self._on_steady_compile)
        self.watchdog.install()
        self._watch_base = self.watchdog.totals()

    # ------------------------------------------------------------------
    @classmethod
    def from_config(cls, config, params: Optional[Dict[str, Any]] = None
                    ) -> "TrainTelemetry":
        """Build from the ``telemetry*`` / ``profile_*`` config knobs.
        ``telemetry_out``, a configured profiler window, or the legacy
        ``LAMBDAGAP_TIMETAG`` env (evaluated NOW, not at import) each imply
        ``telemetry=true``."""
        out = getattr(config, "telemetry_out", "") or ""
        profile = ProfileWindow(
            start_iter=getattr(config, "profile_start_iter", -1),
            n_iters=getattr(config, "profile_n_iters", 1),
            out_dir=getattr(config, "profile_dir", "") or "")
        enabled = (bool(getattr(config, "telemetry", False)) or bool(out)
                   or profile.enabled or _timer.timer_enabled())
        return cls(enabled=enabled, out=out,
                   ring=getattr(config, "telemetry_ring", 256),
                   warmup=getattr(config, "telemetry_warmup", 2),
                   profile=profile if profile.enabled else None,
                   params=params if params is not None
                   else getattr(config, "to_dict", dict)())

    # -- span / iteration API -------------------------------------------
    def current_phase(self) -> Optional[str]:
        return self._stack[-1][0] if self._stack else None

    def phase(self, name: str, legacy: Optional[str] = None):
        """Context manager timing one named phase (nested spans use
        exclusive accounting). Cheap no-op when disabled or when no
        iteration record is open."""
        if not self.enabled or self._cur is None:
            return _NULL_SPAN
        return _Span(self, name, legacy)

    def begin_iteration(self, iteration: int) -> None:
        """Open the record for ``iteration`` (finalizing the previous
        one). Called at the top of ``GBDT.train_one_iter``."""
        if not self.enabled or self._closed:
            return
        self._finalize()
        if self.profile is not None:
            toggled = self.profile.on_iteration_start(iteration)
            if toggled and self.run_log is not None:
                self.run_log.event(f"profile_{toggled}",
                                   iter=int(iteration),
                                   dir=self.profile.out_dir)
        self.watchdog.set_iteration(iteration)
        self._watch_base = self.watchdog.totals()
        self._cur = {"type": "iteration", "iter": int(iteration),
                     "phases": {}}
        self._train_done = False
        self._t0 = time.perf_counter()

    def end_iteration(self, sync: Any = None) -> None:
        """Close the iteration's device-complete train window: ONE
        ``block_until_ready`` on ``sync`` (the score state), recorded as
        the ``device_wait`` phase; stamps ``wall_s`` and the iteration's
        compile/transfer deltas. The record stays open for late phases
        (eval) until the next :meth:`begin_iteration`."""
        if not self.enabled or self._cur is None or self._train_done:
            return
        t = time.perf_counter()
        if sync is not None:
            try:
                import jax
                jax.block_until_ready(sync)
            except Exception:  # pragma: no cover - deleted buffers etc.
                pass
        now = time.perf_counter()
        self._add_phase("device_wait", now - t, now - t, None)
        self._cur["wall_s"] = now - self._t0
        self._stamp_watch()
        self._train_done = True
        self.watchdog.set_iteration(None)

    def close(self) -> None:
        """Finalize the pending record, stop any open profiler window,
        unregister the monitoring hooks and close the JSONL log.
        Idempotent; further spans become no-ops."""
        if not self.enabled or self._closed:
            return
        self._finalize()
        if self.profile is not None:
            self.profile.close(self.iterations)
        self._join_cost_plane()
        self.watchdog.uninstall()
        if self.run_log is not None:
            self.run_log.close()
        self._closed = True

    def _join_cost_plane(self) -> None:
        """Push the run's measured phase walls into the cost plane (the
        wall side of its roofline join), append the ledger to the run log
        as a ``cost_plane`` event, and persist COSTS.json when
        ``cost_plane_out`` asked for it. No-op when the plane is off."""
        from .costplane import PLANE
        if not PLANE.enabled:
            return
        for name, secs in self.totals.items():
            PLANE.note_wall(name, secs, calls=self.counts.get(name, 1))
        if self.run_log is not None:
            try:
                attr = PLANE.attribution()
                self.run_log.event("cost_plane",
                                   entries=len(PLANE.entries),
                                   phases=attr["phases"],
                                   peaks=attr["peaks"])
            except Exception as e:  # pragma: no cover
                log.debug("cost plane run-log export failed: %s", e)
        try:
            PLANE.write()
        except Exception as e:  # pragma: no cover - unwritable path
            log.warning("cost plane: COSTS.json write failed: %s", e)

    # -- internals ------------------------------------------------------
    def _add_phase(self, name: str, exclusive: float, inclusive: float,
                   legacy: Optional[str]) -> None:
        if self._cur is not None:
            ph = self._cur["phases"]
            ph[name] = ph.get(name, 0.0) + exclusive
        self.totals[name] = self.totals.get(name, 0.0) + exclusive
        self.counts[name] = self.counts.get(name, 0) + 1
        if _timer.timer_enabled():
            # deprecation shim: the legacy global_timer table is now a view
            # over telemetry spans, under its historical scope names
            scope = legacy or _LEGACY.get(name, name)
            _timer.global_timer.totals[scope] += inclusive
            _timer.global_timer.counts[scope] += 1

    def _stamp_watch(self) -> None:
        tot = self.watchdog.totals()
        base = self._watch_base
        by_phase = {k: v - base["compiles_by_phase"].get(k, 0)
                    for k, v in tot["compiles_by_phase"].items()
                    if v - base["compiles_by_phase"].get(k, 0)}
        self._cur["compiles"] = {
            "total": tot["compiles"] - base["compiles"],
            "steady": tot["steady_compiles"] - base["steady_compiles"],
            "secs": round(tot["compile_secs"] - base["compile_secs"], 6),
            "by_phase": by_phase,
        }
        self._cur["transfers"] = {
            "total": tot["transfers"] - base["transfers"],
        }

    def _on_steady_compile(self, **fields) -> None:
        if self.run_log is not None:
            self.run_log.event("steady_compile", **fields)

    def _finalize(self) -> None:
        if self._cur is None:
            return
        rec = self._cur
        if "wall_s" not in rec:         # end_iteration never ran
            rec["wall_s"] = time.perf_counter() - self._t0
            self._stamp_watch()
        # round phase seconds for a compact JSONL (µs resolution)
        rec["phases"] = {k: round(v, 6) for k, v in rec["phases"].items()}
        rec["wall_s"] = round(rec["wall_s"], 6)
        self._cur = None
        self.records.append(rec)
        self.iterations += 1
        self.wall_res.add(rec["wall_s"])
        if self.run_log is not None:
            self.run_log.write(rec)

    # -- reporting ------------------------------------------------------
    def summary(self) -> Dict:
        """Aggregate view (the BENCH JSON ``telemetry`` section)."""
        if not self.enabled:
            return {"enabled": False}
        n = max(self.iterations, 1)
        out: Dict[str, Any] = {
            "enabled": True,
            "iterations": self.iterations,
            "phase_seconds_total": {k: round(v, 6)
                                    for k, v in sorted(self.totals.items())},
            "phase_seconds_per_iter": {k: round(v / n, 6)
                                       for k, v in sorted(self.totals.items())},
            "iter_wall_s": self.wall_res.percentiles(),
        }
        out.update({k: v for k, v in self.watchdog.totals().items()
                    if k in ("compiles", "steady_compiles", "transfers",
                             "compile_secs")})
        from .costplane import PLANE
        if PLANE.enabled and PLANE.entries:
            out["cost_plane"] = PLANE.attribution()
        return out

    def report(self) -> str:
        """Human-readable phase table (the global_timer.report analog)."""
        if not self.enabled:
            return "telemetry disabled"
        lines = [f"TrainTelemetry ({self.iterations} iterations):"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(f"  {name}: {self.totals[name]:.4f}s "
                         f"x{self.counts[name]}")
        w = self.watchdog.totals()
        lines.append(f"  compiles: {w['compiles']} "
                     f"({w['steady_compiles']} steady-state), "
                     f"transfers: {w['transfers']}")
        return "\n".join(lines)


#: shared inert instance — the default for anything that may run without a
#: booster-owned telemetry (e.g. a bare SerialTreeLearner in tests)
NULL_TELEMETRY = TrainTelemetry(enabled=False)
