"""Distributed request tracing + the serve flight recorder (graftscope v2).

PR 4 gave ONE process phase-accurate telemetry; a fleet request crosses
frontend -> router -> replica -> batcher -> registry -> device and until
this module left no connected record. A **trace** is the connected record:
a ``trace_id`` minted where the request enters the system (the TCP
frontend client, the router, or ``ForestServer.submit`` itself), carried
in the newline-JSON wire frames and the in-process
:class:`~lambdagap_tpu.serve.batcher.Request`, with one **span** recorded
at every hop:

========================  ====================================================
span name                 hop
========================  ====================================================
``client_request``        root: submit -> future resolution, client process
``route``                 router pick + failover window (attrs: replica,
                          failovers)
``frontend``              server-side frame decode -> reply written
``encode``                response serialization + socket write
``serve_request``         ``ForestServer.submit`` -> future resolution
``queue_wait``            batcher FairQueue wait (submit -> dispatch start)
``registry_get``          registry resolve; ``readmitted=True`` + the
                          compile seconds when the 174x readmission cliff
                          was paid BY THIS REQUEST
``dispatch``              padded device dispatch (attrs: rows, batch_rows)
========================  ====================================================

Spans are wall-aligned across processes: ``t0`` is ``time.time()`` (same
host => same epoch), durations are ``perf_counter`` deltas. A parent-linked
span tree therefore TILES the client-observed latency — the PR 4
span-sum≈wall discipline applied across processes — and
:func:`validate_tree` checks exactly that (containment + coverage within a
tolerance).

Records are the versioned JSONL schema of :mod:`lambdagap_tpu.obs.events`
(record type ``span``), so ``events.validate_file`` covers trace logs, and
the recorder keeps a bounded ring of recent spans/events per process — the
**flight recorder** — dumped atomically (guard's pid-tmp+fsync+rename
discipline) on uncaught exception / SIGTERM / a bounded interval, so even
a SIGKILLed replica leaves a valid recent-history file for
``tools/postmortem.py``.

Hot-path discipline (graftlint R1 guards this file): span enter/exit is
pure host bookkeeping — no jax import, no device sync, ever. Disabled
tracing (``serve_trace_sample=0`` and no explicit context) records
NOTHING: the request path pays one ``is None`` test per hop.
"""
from __future__ import annotations

import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import log
from .events import run_header
from .reservoir import Reservoir


def new_id(rng: Optional[random.Random] = None) -> str:
    """16-hex span/trace id; ``os.urandom`` so forked replicas never
    collide (a seeded rng is for tests only)."""
    if rng is not None:
        return f"{rng.getrandbits(64):016x}"
    return os.urandom(8).hex()


class TraceContext:
    """One node of a trace: the ids a child span needs. ``span_id`` is the
    id the NEXT hop should use as its parent. Immutable and tiny — it
    rides ``Request`` slots and wire frames (``{"id": trace_id,
    "parent": span_id}``)."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 sampled: bool = True) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)

    def child(self) -> "TraceContext":
        """A fresh context for a child span (new span id, same trace)."""
        return TraceContext(self.trace_id, new_id(), self.sampled)

    def to_wire(self) -> Dict[str, str]:
        return {"id": self.trace_id, "parent": self.span_id}

    @classmethod
    def from_wire(cls, obj: Any) -> Optional["TraceContext"]:
        """Parse the optional ``trace`` field of a wire frame; hostile or
        malformed values yield None (an untraced request, never an
        error — tracing must not take down serving)."""
        if not isinstance(obj, dict):
            return None
        tid, parent = obj.get("id"), obj.get("parent")
        if not (isinstance(tid, str) and isinstance(parent, str)
                and tid and parent):
            return None
        return cls(tid, parent, sampled=True)


class SpanRecorder:
    """Per-process span/event sink: a bounded ring (the flight-recorder
    buffer), optional JSONL output with bounded-interval flushing, and
    per-name duration reservoirs (the aggregate the signal plane and
    ``bench_serve trace_breakdown`` read). Thread-safe; records are plain
    dicts in the :mod:`.events` schema."""

    def __init__(self, ring: int = 4096, out: str = "",
                 proc: str = "", flush_every: int = 1,
                 flush_interval_s: float = 0.25) -> None:
        self._lock = threading.Lock()
        self.ring: "deque[Dict]" = deque(maxlen=max(int(ring), 16))
        self.proc = proc or f"pid:{os.getpid()}"
        self.sample = 0.0
        self._rng = random.Random(os.getpid() ^ int(time.time() * 1e3))
        self.n_spans = 0
        self.n_events = 0
        self._agg: Dict[str, Reservoir] = {}
        self._f = None
        self._out_path = ""
        self._flush_every = max(int(flush_every), 1)
        self._flush_interval = float(flush_interval_s)
        self._unflushed = 0
        self._last_flush = time.perf_counter()
        if out:
            self.open_out(out)

    # -- configuration --------------------------------------------------
    def configure(self, sample: Optional[float] = None,
                  out: Optional[str] = None, ring: Optional[int] = None,
                  proc: Optional[str] = None) -> "SpanRecorder":
        with self._lock:
            if sample is not None:
                self.sample = min(max(float(sample), 0.0), 1.0)
            if proc:
                self.proc = proc
            if ring is not None and ring != self.ring.maxlen:
                self.ring = deque(self.ring, maxlen=max(int(ring), 16))
        if out is not None and out != self._out_path:
            self.open_out(out)
        return self

    def open_out(self, path: str) -> None:
        """Attach a JSONL sink; leads with a run_header so
        ``events.validate_file`` accepts the file as-is."""
        with self._lock:
            if self._f is not None:
                self._f.close()
            self._f = open(path, "w", encoding="utf-8") if path else None
            self._out_path = path
            if self._f is not None:
                hdr = run_header({"proc": self.proc, "kind": "trace"})
                self._f.write(json.dumps(hdr, separators=(",", ":"),
                                         default=str) + "\n")
                self._f.flush()

    def maybe_trace(self) -> Optional[TraceContext]:
        """Mint a new sampled root context, or None (the common case):
        one random draw against ``serve_trace_sample``."""
        if self.sample <= 0.0:
            return None
        with self._lock:
            if self.sample < 1.0 and self._rng.random() >= self.sample:
                return None
            rid = f"{self._rng.getrandbits(64):016x}"
            sid = f"{self._rng.getrandbits(64):016x}"
        return TraceContext(rid, sid, sampled=True)

    # -- recording ------------------------------------------------------
    def record(self, name: str, ctx: Optional[TraceContext],
               t0: float, dur_s: float,
               span_id: Optional[str] = None,
               parent: Optional[str] = None,
               **attrs: Any) -> Optional[str]:
        """One finished span. ``ctx`` carries trace id + default parent;
        None is a no-op (the untraced fast path). ``t0`` is epoch seconds
        (``time.time()``), ``dur_s`` a perf_counter delta. Returns the
        span id (for callers that parented children before the parent
        closed)."""
        if ctx is None or not ctx.sampled:
            return None
        sid = span_id or new_id()
        rec: Dict[str, Any] = {
            "type": "span", "trace": ctx.trace_id, "span": sid,
            "parent": ctx.span_id if parent is None else (parent or None),
            "name": name, "proc": self.proc,
            "t0": round(float(t0), 6), "dur": round(max(float(dur_s), 0.0), 9),
        }
        if attrs:
            rec["attrs"] = attrs
        self._append(rec, is_span=True, name=name, dur=rec["dur"])
        return sid

    def span(self, name: str, ctx: Optional[TraceContext],
             **attrs: Any) -> "_LiveSpan":
        """Context manager recording ``name`` around a code block; yields
        a child :class:`TraceContext` (``.ctx``) for nested hops. No-op
        when ``ctx`` is None."""
        return _LiveSpan(self, name, ctx, attrs)

    def event(self, event: str, **fields: Any) -> None:
        """A punctual event into the flight-recorder ring (and the JSONL
        sink when attached): faults, health flips, scrape errors."""
        rec = {"type": "event", "event": event, "proc": self.proc,
               "time_unix": time.time(), **fields}
        self._append(rec, is_span=False)

    def _append(self, rec: Dict, is_span: bool, name: str = "",
                dur: float = 0.0) -> None:
        line = None
        with self._lock:
            self.ring.append(rec)
            if is_span:
                self.n_spans += 1
                agg = self._agg.get(name)
                if agg is None:
                    agg = self._agg[name] = Reservoir(cap=4096,
                                                      seed=len(self._agg))
                agg.add(dur)
            else:
                self.n_events += 1
            if self._f is not None:
                line = json.dumps(rec, separators=(",", ":"), default=str)
                self._f.write(line + "\n")
                self._unflushed += 1
                now = time.perf_counter()
                if (self._unflushed >= self._flush_every
                        or now - self._last_flush >= self._flush_interval):
                    # bounded-interval durability: a SIGKILLed process
                    # loses at most flush_every records / flush_interval
                    # seconds (events.validate_file tolerates the torn
                    # final line)
                    self._f.flush()
                    self._unflushed = 0
                    self._last_flush = now

    # -- reading --------------------------------------------------------
    def tail(self, n: int = 0) -> List[Dict]:
        with self._lock:
            recs = list(self.ring)
        return recs[-n:] if n else recs

    def spans(self, trace_id: Optional[str] = None) -> List[Dict]:
        return [r for r in self.tail() if r.get("type") == "span"
                and (trace_id is None or r.get("trace") == trace_id)]

    def aggregates(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name duration percentiles (seconds) + counts — the
        signal plane's readmission-cost input and the bench's breakdown."""
        with self._lock:
            names = list(self._agg.items())
        out = {}
        for name, res in names:
            p = res.percentiles()
            p["count"] = res.seen
            out[name] = p
        return out

    def reset(self) -> None:
        with self._lock:
            self.ring.clear()
            self._agg.clear()
            self.n_spans = 0
            self.n_events = 0

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
                self._out_path = ""


class _LiveSpan:
    """One open span; ``.ctx`` is the child context nested hops parent
    to. Reused as the no-op for untraced requests (ctx None)."""

    __slots__ = ("_rec", "_name", "_parent", "_attrs", "ctx", "_t0", "_tp")

    def __init__(self, rec: SpanRecorder, name: str,
                 parent: Optional[TraceContext], attrs: Dict) -> None:
        self._rec = rec
        self._name = name
        self._parent = parent
        self._attrs = attrs
        self.ctx = parent.child() if parent is not None else None

    def __enter__(self) -> "_LiveSpan":
        self._t0 = time.time()
        self._tp = time.perf_counter()
        return self

    def __exit__(self, etype, evalue, tb) -> bool:
        if self._parent is not None:
            if etype is not None:
                self._attrs = dict(self._attrs, error=etype.__name__)
            self._rec.record(self._name, self._parent, self._t0,
                             time.perf_counter() - self._tp,
                             span_id=self.ctx.span_id, **self._attrs)
        return False


#: the process-wide recorder every serve component records into; tests and
#: benches may swap in their own via the ``recorder=`` hooks, but one
#: process = one flight-recorder ring is the designed shape
RECORDER = SpanRecorder()


def configure(sample: Optional[float] = None, out: Optional[str] = None,
              ring: Optional[int] = None, proc: Optional[str] = None
              ) -> SpanRecorder:
    """Configure the process recorder from the ``serve_trace_*`` knobs."""
    return RECORDER.configure(sample=sample, out=out, ring=ring, proc=proc)


def start_trace() -> TraceContext:
    """An explicitly sampled root context (gates/tests/benches; the knob
    path goes through :meth:`SpanRecorder.maybe_trace`)."""
    return TraceContext(new_id(), new_id(), sampled=True)


# ---------------------------------------------------------------------------
# span-tree assembly + the cross-process tiling check
# ---------------------------------------------------------------------------
def build_tree(records: List[Dict], trace_id: Optional[str] = None
               ) -> Tuple[List[Dict], Dict[str, Dict]]:
    """(roots, by_span_id) from span records (one trace or all). Children
    are attached under ``"children"``, sorted by t0."""
    spans = [dict(r) for r in records if r.get("type") == "span"
             and (trace_id is None or r.get("trace") == trace_id)]
    by_id = {s["span"]: s for s in spans}
    roots = []
    for s in spans:
        s.setdefault("children", [])
    for s in spans:
        parent = by_id.get(s.get("parent") or "")
        if parent is None:
            roots.append(s)
        else:
            parent["children"].append(s)
    for s in spans:
        s["children"].sort(key=lambda c: c["t0"])
    roots.sort(key=lambda s: s["t0"])
    return roots, by_id


def _union_seconds(intervals: List[Tuple[float, float]]) -> float:
    total = 0.0
    last_end = None
    for lo, hi in sorted(intervals):
        if last_end is None or lo > last_end:
            total += hi - lo
            last_end = hi
        elif hi > last_end:
            total += hi - last_end
            last_end = hi
    return total


def validate_tree(records: List[Dict], trace_id: str,
                  tolerance: float = 0.25,
                  min_cover: float = 0.5) -> List[str]:
    """The cross-process tiling discipline, checked. Errors (empty list =
    valid):

    - exactly one root; every other span's parent EXISTS in the set
      (parent-linked, no orphans);
    - every span's interval is contained in its parent's, with slack
      ``tolerance * root_dur`` (cross-process clocks share an epoch but
      not a quartz crystal);
    - the union of the root's descendants covers >= ``min_cover`` of the
      root duration, and no level's child-sum exceeds ``(1 + tolerance)``
      x the parent — spans must TILE the client-observed wall, not
      overlap-double-count it.
    """
    roots, by_id = build_tree(records, trace_id)
    errs: List[str] = []
    if not by_id:
        return [f"trace {trace_id}: no spans recorded"]
    if len(roots) != 1:
        names = [r["name"] for r in roots]
        return [f"trace {trace_id}: expected exactly one root span, got "
                f"{len(roots)} ({names}) — a span references a parent "
                "that was never recorded"]
    root = roots[0]
    slack = max(tolerance * root["dur"], 2e-3)
    for s in by_id.values():
        parent = by_id.get(s.get("parent") or "")
        if parent is None:
            continue
        if s["t0"] < parent["t0"] - slack \
                or s["t0"] + s["dur"] > parent["t0"] + parent["dur"] + slack:
            errs.append(
                f"span {s['name']} [{s['t0']:.6f}+{s['dur']:.6f}s] escapes "
                f"parent {parent['name']} "
                f"[{parent['t0']:.6f}+{parent['dur']:.6f}s] beyond "
                f"{slack * 1e3:.1f}ms slack")
    for s in by_id.values():
        kids = s.get("children") or []
        if not kids:
            continue
        child_sum = sum(c["dur"] for c in kids)
        if child_sum > s["dur"] * (1.0 + tolerance) + slack:
            errs.append(
                f"children of {s['name']} sum to {child_sum * 1e3:.2f}ms > "
                f"parent {s['dur'] * 1e3:.2f}ms + tolerance — spans "
                "double-count instead of tiling")
    def _descend(s):
        for c in s.get("children") or []:
            yield (c["t0"], c["t0"] + c["dur"])
            yield from _descend(c)
    covered = _union_seconds(
        [(max(lo, root["t0"]), min(hi, root["t0"] + root["dur"]))
         for lo, hi in _descend(root)
         if min(hi, root["t0"] + root["dur"]) > max(lo, root["t0"])])
    if root["dur"] > 0 and covered < min_cover * root["dur"]:
        errs.append(
            f"descendants cover {covered * 1e3:.2f}ms of the "
            f"{root['dur'] * 1e3:.2f}ms root ({covered / root['dur']:.0%}) "
            f"< {min_cover:.0%} — the trace does not tile the "
            "client-observed latency")
    return errs


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
class FlightRecorder:
    """Bounded recent-history dump for serve processes.

    Holds no data of its own — it snapshots :class:`SpanRecorder`'s ring
    (spans AND events) and writes a self-contained JSONL file (run_header
    first, guard's pid-tmp+fsync+rename atomic write) so the file on disk
    is ALWAYS a complete, schema-valid dump:

    - on uncaught exception (``sys.excepthook`` chained, never replaced),
    - on SIGTERM (chained; best-effort — only installable from the main
      thread),
    - every ``interval_s`` seconds from a daemon thread — the SIGKILL
      story: a hard-killed replica leaves its last periodic dump intact
      (atomic replace means a kill mid-dump preserves the previous one).
    """

    def __init__(self, path: str, recorder: Optional[SpanRecorder] = None,
                 interval_s: float = 0.0,
                 params: Optional[Dict[str, Any]] = None) -> None:
        self.path = path
        self.recorder = recorder if recorder is not None else RECORDER
        self.interval_s = max(float(interval_s), 0.0)
        self.params = dict(params or {})
        self.dumps = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_excepthook: Optional[Callable] = None
        self._prev_sigterm = None
        self._installed = False

    def dump(self, reason: str = "manual") -> str:
        """Write the ring to ``self.path`` atomically; returns the path."""
        from ..guard.snapshot import atomic_write_text
        hdr = run_header({**self.params, "proc": self.recorder.proc,
                          "kind": "flight", "reason": reason})
        recs = self.recorder.tail()
        lines = [json.dumps(hdr, separators=(",", ":"), default=str)]
        lines += [json.dumps(r, separators=(",", ":"), default=str)
                  for r in recs]
        cost = self._cost_plane_record()
        if cost is not None:
            lines.append(json.dumps(cost, separators=(",", ":"),
                                    default=str))
        atomic_write_text(self.path, "\n".join(lines) + "\n")
        self.dumps += 1
        return self.path

    def _cost_plane_record(self) -> Optional[Dict[str, Any]]:
        """One ``cost_plane`` event record appended to each dump when the
        analytic ledger is armed: the postmortem of a killed replica then
        carries the per-executable traffic facts next to its last spans."""
        try:
            from .costplane import PLANE
            if not PLANE.enabled or not PLANE.entries:
                return None
            attr = PLANE.attribution()
            return {"type": "event", "event": "cost_plane",
                    "proc": self.recorder.proc, "time_unix": time.time(),
                    "entries": len(PLANE.entries),
                    "phases": attr["phases"], "peaks": attr["peaks"]}
        except Exception:  # pragma: no cover - the dump must never fail
            return None

    # -- hooks ----------------------------------------------------------
    def install(self) -> "FlightRecorder":
        import sys as _sys
        if self._installed:
            return self
        self._installed = True
        self._prev_excepthook = _sys.excepthook

        def _hook(etype, evalue, tb):
            try:
                self.recorder.event("uncaught_exception",
                                    exc=f"{etype.__name__}: {evalue}")
                self.dump(reason="uncaught_exception")
            except Exception:            # the dump must never mask the crash
                log.warning("flight recorder: dump on crash failed")
            self._prev_excepthook(etype, evalue, tb)

        _sys.excepthook = _hook
        try:
            import signal as _signal
            self._prev_sigterm = _signal.getsignal(_signal.SIGTERM)

            def _on_term(signum, frame):
                try:
                    self.recorder.event("sigterm")
                    self.dump(reason="sigterm")
                except Exception:
                    log.warning("flight recorder: dump on SIGTERM failed")
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)

            _signal.signal(_signal.SIGTERM, _on_term)
        except (ValueError, OSError):    # not the main thread
            log.debug("flight recorder: SIGTERM hook unavailable off the "
                      "main thread; periodic + excepthook dumps only")
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="lambdagap-flight-recorder")
            self._thread.start()
        log.info("flight recorder armed: ring=%d -> %s (interval %.1fs)",
                 self.recorder.ring.maxlen, self.path, self.interval_s)
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.dump(reason="interval")
            except Exception as e:       # pragma: no cover - disk full etc.
                log.warning("flight recorder: periodic dump failed: %s", e)

    def close(self, final_dump: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
        if final_dump:
            try:
                self.dump(reason="close")
            except Exception as e:       # pragma: no cover
                log.warning("flight recorder: final dump failed: %s", e)
