"""Recompile & transfer watchdog over ``jax.monitoring`` events.

XLA recompiles and host<->device transfers are the two silent performance
cliffs of this codebase (graftlint R1/R2 catch them statically; this module
catches them at runtime). jax reports both through ``jax.monitoring``:
``/jax/core/compile/backend_compile_duration`` fires once per backend
compile, and transfer-instrumented builds emit ``*transfer*`` events. The
watchdog registers listeners, attributes each event to the telemetry's
current (iteration, phase) context, and — the R2 hazard class — WARNS when
a steady-state iteration (``iter >= warmup``) triggers a fresh compile:
after warmup every shape should be compiled, so a steady-state compile
means a shape-unstable program (e.g. a non-power-of-2 pad, a closed-over
mutable attribute) silently recompiling every iteration.

Nothing registers unless :meth:`install` is called (the telemetry-off path
must add zero ``jax.monitoring`` hooks), and :meth:`uninstall` removes the
listeners again.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..utils import log

# steady-state warnings are load-bearing but a recompile-per-iteration bug
# would otherwise spam one warning per iteration for 500 iterations
_MAX_WARNINGS = 5


def _is_compile_event(event: str) -> bool:
    # "/jax/core/compile/backend_compile_duration" (the actual backend
    # compile); trace/lowering events also live under /compile/ but only
    # backend_compile implies a fresh executable
    return "backend_compile" in event


def _is_transfer_event(event: str) -> bool:
    return "transfer" in event


# jax.monitoring kwargs keys that identify WHICH executable a compile
# event belongs to, in preference order. Current jax versions fire
# backend_compile with empty kwargs (every compile is then an anonymous
# per-phase count, as before), but fingerprint/module kwargs exist in the
# instrumented builds and newer versions — when present, the watchdog
# attributes the compile to them so `totals()["compiles_by_module"]`
# names the recompiling program instead of just its phase.
_MODULE_KWARGS = ("fingerprint", "module_name", "fun_name", "module",
                  "name")


def _module_of(kwargs: Dict) -> Optional[str]:
    for key in _MODULE_KWARGS:
        val = kwargs.get(key)
        if val:
            return str(val)
    return None


class XlaWatchdog:
    """Counts compiles/transfers per phase; warns on steady-state compiles.

    Counters are cumulative; :class:`~.telemetry.TrainTelemetry` snapshots
    them at iteration boundaries and diffs. ``phase_getter`` supplies the
    innermost active phase name (or None) for attribution; ``iteration``
    is maintained by the telemetry via :meth:`set_iteration`.
    """

    def __init__(self, warmup: int = 2,
                 phase_getter: Optional[Callable[[], Optional[str]]] = None,
                 on_steady_compile: Optional[Callable] = None) -> None:
        self.warmup = int(warmup)
        self._phase_getter = phase_getter or (lambda: None)
        self._on_steady_compile = on_steady_compile
        self._lock = threading.Lock()
        self.installed = False
        self.iteration: Optional[int] = None   # None = outside training
        self.compiles = 0
        self.steady_compiles = 0
        self.transfers = 0
        self.compiles_by_phase: Dict[str, int] = {}
        self.compiles_by_module: Dict[str, int] = {}
        self.transfers_by_phase: Dict[str, int] = {}
        self.compile_secs = 0.0
        self._warnings = 0

    # -- lifecycle ------------------------------------------------------
    def install(self) -> None:
        if self.installed:
            return
        import jax.monitoring
        jax.monitoring.register_event_listener(self._on_event)
        jax.monitoring.register_event_duration_secs_listener(
            self._on_duration)
        self.installed = True

    def uninstall(self) -> None:
        if not self.installed:
            return
        if not self._uninstall_public():
            try:
                from jax._src import monitoring as _m
                _m._unregister_event_listener_by_callback(self._on_event)
                _m._unregister_event_duration_listener_by_callback(
                    self._on_duration)
            except Exception:  # pragma: no cover - jax internals moved
                log.warning("could not unregister jax.monitoring "
                            "listeners; the watchdog callbacks stay "
                            "registered (harmless but counted across runs)")
        self.installed = False

    def _uninstall_public(self) -> bool:
        """Prefer a public unregister API when the jax version grows one
        (the `_src` fallback below is version-coupled); returns True when
        both listeners were removed publicly."""
        import jax.monitoring
        unreg_ev = getattr(jax.monitoring,
                           "unregister_event_listener_by_callback", None)
        unreg_dur = getattr(
            jax.monitoring,
            "unregister_event_duration_listener_by_callback", None)
        if unreg_ev is None or unreg_dur is None:
            return False
        try:
            unreg_ev(self._on_event)
            unreg_dur(self._on_duration)
            return True
        except Exception:  # pragma: no cover - listener already gone
            return False

    def set_iteration(self, iteration: Optional[int]) -> None:
        self.iteration = iteration

    # -- listeners ------------------------------------------------------
    def _on_event(self, event: str, **kwargs) -> None:
        if _is_compile_event(event):
            self._record_compile(event, 0.0, kwargs)
        elif _is_transfer_event(event):
            with self._lock:
                self.transfers += 1
                phase = self._phase_getter() or "outside"
                self.transfers_by_phase[phase] = \
                    self.transfers_by_phase.get(phase, 0) + 1

    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if _is_compile_event(event):
            self._record_compile(event, float(duration), kwargs)
        elif _is_transfer_event(event):
            self._on_event(event)

    def _record_compile(self, event: str, duration: float,
                        kwargs: Optional[Dict] = None) -> None:
        module = _module_of(kwargs) if kwargs else None
        with self._lock:
            self.compiles += 1
            self.compile_secs += duration
            phase = self._phase_getter() or "outside"
            self.compiles_by_phase[phase] = \
                self.compiles_by_phase.get(phase, 0) + 1
            if module is not None:
                self.compiles_by_module[module] = \
                    self.compiles_by_module.get(module, 0) + 1
            it = self.iteration
            steady = it is not None and it >= self.warmup
            if steady:
                self.steady_compiles += 1
                warn = self._warnings < _MAX_WARNINGS
                self._warnings += 1
        if steady:
            if warn:
                log.warning(
                    "steady-state recompile at iteration %d (phase %s, "
                    "%.3fs): a fresh compile after %d warmup iterations "
                    "is either a shape-unstable program recompiling per "
                    "iteration (graftlint R2 hazard class) or a late "
                    "first-use shape (e.g. a new padding bucket); if it "
                    "repeats every iteration, it is the former",
                    it, phase, duration, self.warmup)
            if self._on_steady_compile is not None:
                self._on_steady_compile(monitor_event=event, iteration=it,
                                        phase=phase, duration=duration)

    # -- reporting ------------------------------------------------------
    def totals(self) -> Dict:
        with self._lock:
            return {
                "compiles": self.compiles,
                "steady_compiles": self.steady_compiles,
                "compile_secs": self.compile_secs,
                "transfers": self.transfers,
                "compiles_by_phase": dict(self.compiles_by_phase),
                "compiles_by_module": dict(self.compiles_by_module),
                "transfers_by_phase": dict(self.transfers_by_phase),
            }
