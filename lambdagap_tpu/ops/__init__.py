from .histogram import (full_histogram, leaf_histogram, histogram_from_rows,
                        subtract_histogram)
from .partition import split_partition, decision_go_left
from .predict import (TreeArrays, forest_to_arrays, predict_forest,
                      predict_forest_leaf, predict_tree_raw,
                      predict_tree_binned, predict_leaf_index_binned,
                      tree_to_arrays)
from .predict_tensor import (build_tree_tiles, predict_forest_tensor,
                             predict_forest_leaf_tensor)
from .split import SplitParams, SplitResult, find_best_split

__all__ = [
    "full_histogram", "leaf_histogram", "histogram_from_rows",
    "subtract_histogram", "split_partition", "decision_go_left",
    "TreeArrays", "forest_to_arrays", "predict_forest",
    "predict_forest_leaf", "predict_tree_raw", "predict_tree_binned",
    "predict_leaf_index_binned", "tree_to_arrays",
    "build_tree_tiles", "predict_forest_tensor",
    "predict_forest_leaf_tensor",
    "SplitParams", "SplitResult", "find_best_split",
]
