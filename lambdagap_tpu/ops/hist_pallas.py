"""Pallas TPU histogram kernel (the default on TPU since tpu_hist_impl=auto
graduated it from prototype; docs/performance.md).

The performance-critical replacement for the XLA one-hot histogram
(see :mod:`lambdagap_tpu.ops.histogram`): the CUDA analog builds per-block
shared-memory histograms with atomics
(reference: src/treelearner/cuda/cuda_histogram_constructor.cu:20-130).
TPUs have no atomics; the idiomatic equivalent is a one-hot contraction on
the MXU — but done *inside* a kernel so the one-hot operand lives only in
VMEM, block by block, instead of being materialized to HBM by XLA (round
1's main bandwidth sink: at HIGGS shape the XLA intermediate is ~28x the
size of the uint8 rows it encodes).

Grid layout: ``(feature_blocks, row_blocks)`` with the row dimension inner.
Each ``[row_tile, feature_tile]`` grid cell accumulates into an explicit
f32/int32 VMEM scratch block (``acc_ref``); the HBM output block is written
ONCE, when the last row block of a feature block retires — the canonical
Pallas accumulate-then-flush pattern. Each feature contributes one
``[BLK, B]`` one-hot built in registers and contracted against the per-row
channel matrix; channels are the split-precision pair
(g_hi, g_lo, h_hi, h_lo, count, pad...) so a single bf16 matmul chain
yields ~f32-accurate sums (same trick as ops.histogram.gh_contract
'split'). The channel dim (8) rides the f32 sublane tile exactly.

Ragged leaf slices: the kernel masks rows past the dynamic ``count``
IN-KERNEL (a per-block row iota against the live count), so the tail of
the final row block may carry arbitrary junk — under ``tree_layout=sorted``
a leaf's window routinely runs into the next leaf's rows, which are NOT
zero-channel. Callers still zero the channels of rows excluded by a
bagging mask (that information is per-row, not a prefix).

Off TPU the kernel runs in Pallas interpret mode (pure XLA semantics, slow
but exact), which keeps the tier-1 CPU parity tests honest about the code
path the TPU default actually takes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

HIST_C = 3

# int8 gradient levels fit signed int8: the hard cap on num_grad_quant_bins
# (config validation names the knob; see exact_accum_limit)
MAX_QUANT_BINS = 127

try:  # pallas is TPU-only at runtime; import-guarded for CPU-only setups
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False


def exact_accum_limit(hist_impl: str) -> int:
    """Largest integer the quantized-histogram level accumulator holds
    exactly under ``hist_impl`` — the ONE source of the row-limit guard
    queried by both the fused learner and config validation (it used to be
    two diverging literals at models/fused_learner.py and here):

    * ``pallas`` — raw int8 levels accumulate in int32 inside the kernel:
      int32 max.
    * anything else — levels accumulate as integer-valued float32 in the
      one-hot contraction: 2**24, the last exactly-representable contiguous
      integer.
    """
    return 2**31 - 1 if hist_impl == "pallas" else 2**24


def _interpret() -> bool:
    """Mosaic compiles only for TPU; everywhere else the kernel runs in
    interpret mode (slow, exact — the CPU tier-1 parity path)."""
    return jax.default_backend() != "tpu"


def _hist_kernel(count_ref, bins_ref, gh_ref, out_ref, acc_ref, *,
                 num_bins: int, fblk: int, blk: int, nrb: int):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # compute is gated on the dynamic row count: a call padded to a large
    # static row budget only pays DMA for the dead blocks (the analog of the
    # CUDA kernel's early-exit on out-of-range rows). Rows past count in
    # the live boundary block are masked in-kernel — their bins/channels
    # may be junk (a sorted-layout window running into the next leaf).
    @pl.when(r * blk < count_ref[0])
    def _():
        bins = bins_ref[:].astype(jnp.int32)                # [BLK, FBLK]
        live = count_ref[0] - r * blk
        rmask = lax.broadcasted_iota(jnp.int32, (blk, 1), 0) < live
        gh = jnp.where(rmask, gh_ref[:], 0)                 # [BLK, 8] bf16
        iota_b = lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)
        B = num_bins
        for f in range(fblk):
            onehot = (bins[:, f:f + 1] == iota_b).astype(jnp.bfloat16)
            acc_ref[:, f * B:(f + 1) * B] += lax.dot_general(
                gh, onehot,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)         # [8, B]

    # one HBM flush per [row_tile, feature_tile] grid column
    @pl.when(r == nrb - 1)
    def _():
        out_ref[:] = acc_ref[:]


def _pick_blocks(F: int, B: int, P: int):
    """Row block 1024 (2048 for small feature counts); feature block sized
    so the VMEM accumulator block [8, FBLK*B] f32 stays ~2 MB."""
    blk = 2048 if F * B <= 8192 else 1024
    blk = min(blk, max(256, P))
    fblk = max(1, min(F, (2 * 1024 * 1024 // 4) // (8 * B)))
    return blk, fblk


def _grid_spec(P: int, Fp: int, B: int, blk: int, fblk: int, acc_dtype):
    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Fp // fblk, P // blk),
        in_specs=[
            pl.BlockSpec((blk, fblk), lambda f, r, c: (r, f),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((blk, 8), lambda f, r, c: (r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((8, fblk * B), lambda f, r, c: (0, f),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((8, fblk * B), acc_dtype)],
    )


@functools.partial(jax.jit, static_argnames=("num_bins",))
def hist_pallas(bins: jax.Array, gh8: jax.Array, num_bins: int,
                count=None) -> jax.Array:
    """Histogram of a row block via the Pallas kernel.

    bins : uint8/uint16 [P, F] binned rows — either a gathered block or a
           contiguous sorted-layout leaf slice; rows past ``count`` may
           hold anything (masked in-kernel)
    gh8  : bf16 [P, 8] — (g_hi, g_lo, h_hi, h_lo, count, 0, 0, 0),
           see :func:`pack_gh8`; bagging-masked rows must carry zero
           channels (the count mask only covers the ragged tail)
    count: optional dynamic number of live rows (<= P); blocks past it skip
           compute, so heavily padded calls cost ~DMA only
    Returns f32 [F, B, 3] (sum_grad, sum_hess, count).
    """
    P, F = bins.shape
    B = num_bins
    blk, fblk = _pick_blocks(F, B, P)
    if P % blk != 0:
        pad = blk - P % blk
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh8 = jnp.pad(gh8, ((0, pad), (0, 0)))
        P += pad
    Fp = ((F + fblk - 1) // fblk) * fblk
    if Fp != F:
        # padded feature columns produce junk histograms, sliced off below
        bins = jnp.pad(bins, ((0, 0), (0, Fp - F)))
    count = jnp.asarray([P if count is None else count], jnp.int32)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_bins=B, fblk=fblk, blk=blk,
                          nrb=P // blk),
        out_shape=jax.ShapeDtypeStruct((8, Fp * B), jnp.float32),
        grid_spec=_grid_spec(P, Fp, B, blk, fblk, jnp.float32),
        interpret=_interpret(),
    )(count, bins, gh8)

    out = out.reshape(8, Fp, B)[:, :F]                      # [8, F, B]
    sg = out[0] + out[1]
    sh = out[2] + out[3]
    cnt = out[4]
    return jnp.stack([sg, sh, cnt], axis=-1)                # [F, B, 3]


def pack_gh8(grad: jax.Array, hess: jax.Array, valid: jax.Array) -> jax.Array:
    """Split-precision channel packing for :func:`hist_pallas`."""
    g = jnp.where(valid, grad, 0.0)
    h = jnp.where(valid, hess, 0.0)
    g_hi = g.astype(jnp.bfloat16)
    g_lo = (g - g_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    h_hi = h.astype(jnp.bfloat16)
    h_lo = (h - h_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    cnt = valid.astype(jnp.bfloat16)
    zero = jnp.zeros_like(cnt)
    return jnp.stack([g_hi, g_lo, h_hi, h_lo, cnt, zero, zero, zero], axis=1)


# ---------------------------------------------------------------------------
# quantized-gradient path: int8 one-hot matmul with exact int32 accumulation
# (reference: src/treelearner/gradient_discretizer.hpp + the 16/32-bit
# integer histogram variants of feature_histogram.hpp)
#
# Measured (round 2, 500k rows x 255 leaves, one throttled chip): AUC parity
# with fp32 at qb=64, per-iter 233ms vs 216ms fp32 — the discretize pass
# costs more than the int8 matmul saves while per-split fixed costs
# dominate. Expected to win once histogram FLOPs are the bottleneck
# (larger N/F or full-speed MXU).
# ---------------------------------------------------------------------------

def _hist_kernel_q(count_ref, bins_ref, gh_ref, out_ref, acc_ref, *,
                   num_bins: int, fblk: int, blk: int, nrb: int):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(r * blk < count_ref[0])
    def _():
        bins = bins_ref[:].astype(jnp.int32)                # [BLK, FBLK]
        live = count_ref[0] - r * blk
        rmask = lax.broadcasted_iota(jnp.int32, (blk, 1), 0) < live
        gh = jnp.where(rmask, gh_ref[:], 0)                 # [BLK, 8] int8
        iota_b = lax.broadcasted_iota(jnp.int32, (1, num_bins), 1)
        B = num_bins
        for f in range(fblk):
            onehot = (bins[:, f:f + 1] == iota_b).astype(jnp.int8)
            acc_ref[:, f * B:(f + 1) * B] += lax.dot_general(
                gh, onehot,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)           # [8, B] i32

    @pl.when(r == nrb - 1)
    def _():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("num_bins",))
def hist_pallas_q(bins: jax.Array, ghq8: jax.Array, num_bins: int,
                  count=None) -> jax.Array:
    """Quantized histogram: int8 channels, exact int32 accumulation.

    ghq8: int8 [P, 8] — (g_q, h_q, in_bag, 0...), see :func:`pack_ghq8`.
    Rows past ``count`` are masked in-kernel (sorted-layout windows may
    carry the next leaf's rows there). Returns int32 [F, B, 3]
    (sum_gq, sum_hq, count).
    """
    P, F = bins.shape
    B = num_bins
    blk, fblk = _pick_blocks(F, B, P)
    if P % blk != 0:
        pad = blk - P % blk
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghq8 = jnp.pad(ghq8, ((0, pad), (0, 0)))
        P += pad
    Fp = ((F + fblk - 1) // fblk) * fblk
    if Fp != F:
        bins = jnp.pad(bins, ((0, 0), (0, Fp - F)))
    count = jnp.asarray([P if count is None else count], jnp.int32)

    out = pl.pallas_call(
        functools.partial(_hist_kernel_q, num_bins=B, fblk=fblk, blk=blk,
                          nrb=P // blk),
        out_shape=jax.ShapeDtypeStruct((8, Fp * B), jnp.int32),
        grid_spec=_grid_spec(P, Fp, B, blk, fblk, jnp.int32),
        interpret=_interpret(),
    )(count, bins, ghq8)
    out = out.reshape(8, Fp, B)[:, :F]
    return jnp.stack([out[0], out[1], out[2]], axis=-1)     # [F, B, 3] i32


def pack_ghq8(gq: jax.Array, hq: jax.Array, valid: jax.Array) -> jax.Array:
    """Channel packing for :func:`hist_pallas_q` (int8 quantized grads)."""
    v8 = valid.astype(jnp.int8)
    g = gq.astype(jnp.int8) * v8
    h = hq.astype(jnp.int8) * v8
    zero = jnp.zeros_like(v8)
    return jnp.stack([g, h, v8, zero, zero, zero, zero, zero], axis=1)


def quantize_gradients(grad: jax.Array, hess: jax.Array, key,
                       num_bins: int, stochastic: bool = True,
                       gmax=None, hmax=None):
    """Discretize grad/hess to signed int8 levels with stochastic rounding
    (reference: GradientDiscretizer::DiscretizeGradients,
    src/treelearner/gradient_discretizer.cpp). Returns
    (g_q i8, h_q i8, g_scale, h_scale).

    ``gmax``/``hmax`` override the locally-measured extrema — the
    pre-partitioned multi-process path passes GLOBAL maxima so every rank
    derives identical scales (the distributed analog of the reference
    syncing gradient scales before histogram reduction)."""
    qb = max(2, min(num_bins, MAX_QUANT_BINS))
    half = max(qb // 2, 1)
    if gmax is None:
        gmax = jnp.maximum(jnp.max(jnp.abs(grad)), 1e-12)
    if hmax is None:
        hmax = jnp.maximum(jnp.max(hess), 1e-12)
    gs = gmax / half
    hs = hmax / qb
    g = grad / gs
    h = hess / hs
    if stochastic:
        import jax.random as jrandom
        k1, k2 = jrandom.split(key)
        g = jnp.floor(g + jrandom.uniform(k1, g.shape))
        h = jnp.floor(h + jrandom.uniform(k2, h.shape))
    else:
        g = jnp.round(g)
        h = jnp.round(h)
    gq = jnp.clip(g, -MAX_QUANT_BINS, MAX_QUANT_BINS).astype(jnp.int8)
    hq = jnp.clip(h, 0, MAX_QUANT_BINS).astype(jnp.int8)
    return gq, hq, gs, hs
