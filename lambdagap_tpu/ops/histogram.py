"""Histogram construction over the binned matrix.

The TPU replacement for the reference's histogram kernels
(reference: src/io/dense_bin.hpp:99-141 ConstructHistogramInner on CPU;
src/treelearner/cuda/cuda_histogram_constructor.cu:20-130 on CUDA).

TPUs have no fast scatter-add, so instead of atomics the default strategy is a
one-hot expansion contracted on the MXU: for a block of rows, build
``onehot[r, f*B + b] = (bin[r, f] == b)`` and contract with the per-row
``(grad, hess, 1)`` channels — a ``[C, R] @ [R, F*B]`` matmul whose N dimension
(total bins) is large, keeping the systolic array busy. Blocks are accumulated
with ``lax.scan`` so the one-hot tensor never materializes in HBM.

Histograms are ``float32 [F, B, 3]`` with channels (sum_grad, sum_hess, count).
The reference approximates per-bin counts by ``RoundInt(hess * cnt_factor)``
(src/treelearner/feature_histogram.hpp:843); we track exact counts in a third
channel — the MXU pads the channel dim anyway, so it is free.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

HIST_CHANNELS = 3  # (sum_grad, sum_hess, count)


def gh_contract(gh: jax.Array, onehot2d: jax.Array,
                precision: str) -> jax.Array:
    """Contract per-row (grad, hess, count) channels with a one-hot matrix on
    the MXU: ``[C, R] @ [R, FB] -> [C, FB]`` float32.

    precision (config ``tpu_hist_precision``):
      * ``split`` — two-term bf16 decomposition ``g = hi + lo`` with
        ``hi = bf16(g)``, ``lo = bf16(g - hi)``; both halves ride one fused
        matmul (channel dim 2C) and are summed after, recovering ~f32
        accuracy at bf16 MXU throughput. The reference accumulates f32/double
        histograms (src/io/bin.h HistogramSumReducer), so this is the parity
        default.
      * ``bf16`` — raw bf16 cast of the operands (fastest, ~2^-9 relative
        error per gradient).
      * ``f32`` — full float32 matmul.
    """
    if precision not in ("split", "bf16", "f32"):
        raise ValueError(f"tpu_hist_precision must be split/bf16/f32, "
                         f"got {precision!r}")
    C = gh.shape[1]
    if precision == "f32":
        return lax.dot_general(
            gh.T, onehot2d.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    if precision == "bf16":
        return lax.dot_general(
            gh.astype(jnp.bfloat16).T, onehot2d,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    hi = gh.astype(jnp.bfloat16)
    lo = (gh - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    ghs = jnp.concatenate([hi, lo], axis=1)          # [R, 2C]
    part = lax.dot_general(
        ghs.T, onehot2d,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return part[:C] + part[C:]


def gather_leaf_rows(perm: jax.Array, begin: jax.Array, count: jax.Array,
                     padded_size: int):
    """Row indices of one leaf from the partition permutation array.

    Analog of reading ``indices_[leaf_begin_ .. leaf_begin_+leaf_count_]``
    (reference: src/treelearner/data_partition.hpp:21-63), padded to a static
    size so downstream shapes are jit-stable. Out-of-range lanes are clamped
    (callers mask them with ``valid``).
    """
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = jnp.clip(begin + lane, 0, perm.shape[0] - 1)
    rows = perm[idx]
    valid = lane < count
    return rows, valid


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block",
                                             "precision"))
def histogram_from_rows(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                        valid: jax.Array, num_bins: int,
                        rows_per_block: int = 4096,
                        precision: str = "split") -> jax.Array:
    """Histogram of a padded row block.

    Parameters
    ----------
    bins : uint8/uint16 [P, F] — gathered binned rows
    grad, hess : float32 [P]
    valid : bool [P] — padding mask
    num_bins : static B (uniform per-feature bin budget, e.g. 256)

    Returns float32 [F, B, 3].
    """
    P, F = bins.shape
    B = num_bins
    gh = jnp.stack([grad * valid, hess * valid,
                    valid.astype(jnp.float32)], axis=1)  # [P, 3]

    block = min(rows_per_block, P)
    if P % block != 0:
        # pad rows to a block multiple; masked lanes contribute zeros
        pad = block - P % block
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
        P += pad
    nblocks = P // block

    bins_blocks = bins.reshape(nblocks, block, F)
    gh_blocks = gh.reshape(nblocks, block, HIST_CHANNELS)
    bin_iota = jnp.arange(B, dtype=bins.dtype)

    def body(acc, xs):
        b_blk, gh_blk = xs
        # [R, F, B] one-hot, built in registers/VMEM and fed straight to the MXU
        onehot = (b_blk[:, :, None] == bin_iota).astype(jnp.bfloat16)
        onehot2d = onehot.reshape(block, F * B)
        # [C, R] @ [R, F*B] -> [C, F*B]: N dim is big -> good MXU tiling
        part = gh_contract(gh_blk, onehot2d, precision)
        return acc + part, None

    # zeros-of-inputs trick keeps the carry's device-varying annotation
    # consistent when this runs inside shard_map (per-shard partial hists)
    init = (jnp.zeros((HIST_CHANNELS, F * B), dtype=jnp.float32)
            + gh[0, 0] * 0 + bins[0, 0].astype(jnp.float32) * 0)
    acc, _ = lax.scan(body, init, (bins_blocks, gh_blocks))
    return acc.reshape(HIST_CHANNELS, F, B).transpose(1, 2, 0)


@functools.partial(jax.jit,
                   static_argnames=("padded_size", "num_bins",
                                    "rows_per_block", "precision"))
def leaf_histogram(x_binned: jax.Array, perm: jax.Array, grad: jax.Array,
                   hess: jax.Array, begin: jax.Array, count: jax.Array,
                   padded_size: int, num_bins: int,
                   rows_per_block: int = 4096,
                   row_mask: Optional[jax.Array] = None,
                   precision: str = "split") -> jax.Array:
    """Histogram for one leaf's rows: gather + block-accumulate.

    Analog of ``SerialTreeLearner::ConstructHistograms`` for the smaller leaf
    (reference: src/treelearner/serial_tree_learner.cpp:408-476); the larger
    sibling is obtained by subtraction (:func:`subtract_histogram`).

    ``row_mask`` (bool [N]) marks in-bag rows when bagging/GOSS is active so
    the count channel only counts sampled rows (out-of-bag rows still live in
    the partition; their grad/hess are pre-zeroed by the sample strategy).
    """
    rows, valid = gather_leaf_rows(perm, begin, count, padded_size)
    if row_mask is not None:
        valid = valid & row_mask[rows]
    bins = x_binned[rows]
    g = grad[rows]
    h = hess[rows]
    return histogram_from_rows(bins, g, h, valid, num_bins, rows_per_block,
                               precision)


@functools.partial(jax.jit, static_argnames=("padded_size", "num_bins",
                                             "rows_per_block", "precision"))
def leaf_histogram_sorted(x_sorted: jax.Array, gh_sorted: jax.Array,
                          begin: jax.Array, count: jax.Array,
                          padded_size: int, num_bins: int,
                          rows_per_block: int = 4096,
                          precision: str = "split") -> jax.Array:
    """Histogram for one leaf under ``tree_layout=sorted``: the leaf's rows
    occupy a contiguous position slice of the physically reordered matrix
    (maintained by :func:`..ops.partition.split_partition_sorted`), so the
    read is a consecutive-index window — no row gather through the
    permutation (docs/performance.md).

    gh_sorted: f32 [N, 2 or 3] — (grad, hess[, in-bag]) permuted alongside
    the rows; the optional third channel carries the bagging mask so the
    count channel matches the gather path's ``row_mask`` semantics.
    """
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = jnp.clip(begin + lane, 0, x_sorted.shape[0] - 1)
    valid = lane < count
    bins = x_sorted[idx]
    gh = gh_sorted[idx]
    if gh_sorted.shape[1] > 2:
        valid = valid & (gh[:, 2] > 0)
    return histogram_from_rows(bins, gh[:, 0], gh[:, 1], valid, num_bins,
                               rows_per_block, precision)


def unbundle_hist(hist_b: jax.Array, src: jax.Array, kind: jax.Array,
                  parent_g, parent_h, parent_c) -> jax.Array:
    """Expand a bundled-column histogram back to per-feature space.

    hist_b: f32 [C, Bb, 3] histogram over EFB-bundled columns.
    src/kind: the precomputed gather map (data.bundling.unbundle_map) —
    COPY bins gather from the flattened bundle histogram; a bundled
    feature's default bin is the leaf residual ``total - sum(COPY bins)``
    (the analog of FixHistogram's sum patching, reference:
    src/treelearner/feature_histogram.hpp GatherInfoForThreshold).
    Returns f32 [F, B, 3].
    """
    flat = hist_b.reshape(-1, HIST_CHANNELS)
    out = flat[src]                                     # [F, B, 3]
    copy = (kind == 1)[..., None]
    out = jnp.where(copy, out, 0.0)
    nzsum = jnp.sum(out, axis=1)                        # [F, 3]
    totals = jnp.stack([parent_g, parent_h, parent_c])  # [3]
    resid = totals[None, :] - nzsum                     # [F, 3]
    return jnp.where((kind == 2)[..., None], resid[:, None, :], out)


def subtract_histogram(parent_hist: jax.Array, child_hist: jax.Array) -> jax.Array:
    """The histogram-subtraction trick
    (reference: src/treelearner/feature_histogram.hpp ``Subtract``)."""
    return parent_hist - child_hist


# ---------------------------------------------------------------------------
# data_residency=stream kernels (docs/performance.md "Out-of-core"): the
# binned matrix lives in host shards; windows arrive as UPLOADED buffers
# while grad/hess/mask stay device-resident. Accumulation replicates the
# resident kernels' order window-for-window (same gh_contract shapes, same
# sequential f32 adds), so streamed histograms are bit-identical.
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_bins", "precision"))
def histogram_block_acc(acc: jax.Array, bins_blk: jax.Array,
                        grad: jax.Array, hess: jax.Array,
                        row_mask: Optional[jax.Array], start: jax.Array,
                        num_bins: int, precision: str = "split") -> jax.Array:
    """One streamed block of the root histogram: ``acc + contract(block)``.

    ``bins_blk`` is the uploaded rows ``[start, start+block)`` in dataset
    order (host zero-pads the ragged tail, matching the resident
    ``histogram_from_rows`` tail padding); grad/hess/mask index on device.
    Carrying ``acc`` across dispatches reproduces the resident scan's
    sequential block adds exactly.
    """
    block, F = bins_blk.shape
    B = num_bins
    N = grad.shape[0]
    lane = jnp.arange(block, dtype=jnp.int32)
    idxg = start + lane
    in_range = idxg < N
    idx = jnp.clip(idxg, 0, N - 1)
    valid = in_range if row_mask is None else in_range & row_mask[idx]
    vf = valid.astype(jnp.float32)
    # same construction as the resident gh matrix (grad * valid), with the
    # tail rows forced to exact 0.0 like jnp.pad's zeros
    g = jnp.where(in_range, grad[idx] * vf, 0.0)
    h = jnp.where(in_range, hess[idx] * vf, 0.0)
    gh_blk = jnp.stack([g, h, vf], axis=1)
    bin_iota = jnp.arange(B, dtype=bins_blk.dtype)
    onehot = (bins_blk[:, :, None] == bin_iota).astype(jnp.bfloat16)
    part = gh_contract(gh_blk, onehot.reshape(block, F * B), precision)
    return acc + part


def finish_histogram_acc(acc: jax.Array, num_features: int,
                         num_bins: int) -> jax.Array:
    """[3, F*B] streamed accumulator -> the [F, B, 3] histogram layout."""
    return acc.reshape(HIST_CHANNELS, num_features,
                       num_bins).transpose(1, 2, 0)


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block",
                                             "precision"))
def leaf_histogram_streamed(bins: jax.Array, rows: jax.Array,
                            grad: jax.Array, hess: jax.Array,
                            count: jax.Array, num_bins: int,
                            rows_per_block: int = 4096,
                            row_mask: Optional[jax.Array] = None,
                            precision: str = "split") -> jax.Array:
    """:func:`leaf_histogram` with the row gather done on the HOST: the
    leaf's binned rows arrive uploaded (``bins``, padded like
    ``gather_leaf_rows`` pads) together with their dataset row indices
    (``rows``) so grad/hess/mask still index device-resident arrays.
    Identical values into the same :func:`histogram_from_rows` → identical
    histogram."""
    P = bins.shape[0]
    lane = jnp.arange(P, dtype=jnp.int32)
    valid = lane < count
    if row_mask is not None:
        valid = valid & row_mask[rows]
    return histogram_from_rows(bins, grad[rows], hess[rows], valid,
                               num_bins, rows_per_block, precision)


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block",
                                             "precision"))
def leaf_histogram_sorted_streamed(bins: jax.Array, gh_sorted: jax.Array,
                                   begin: jax.Array, count: jax.Array,
                                   num_bins: int,
                                   rows_per_block: int = 4096,
                                   precision: str = "split") -> jax.Array:
    """:func:`leaf_histogram_sorted` with the contiguous window read done
    on the HOST (the sorted payload lives in host shards under stream
    residency); the gradient channels stay device-resident and slice at
    the same clamped positions as the resident kernel."""
    P = bins.shape[0]
    lane = jnp.arange(P, dtype=jnp.int32)
    idx = jnp.clip(begin + lane, 0, gh_sorted.shape[0] - 1)
    valid = lane < count
    gh = gh_sorted[idx]
    if gh_sorted.shape[1] > 2:
        valid = valid & (gh[:, 2] > 0)
    return histogram_from_rows(bins, gh[:, 0], gh[:, 1], valid, num_bins,
                               rows_per_block, precision)


@functools.partial(jax.jit, static_argnames=("num_bins", "rows_per_block",
                                             "precision"))
def full_histogram(x_binned: jax.Array, grad: jax.Array, hess: jax.Array,
                   sample_mask: Optional[jax.Array], num_bins: int,
                   rows_per_block: int = 4096,
                   precision: str = "split") -> jax.Array:
    """Histogram over the whole dataset (root node), optionally bagging-masked."""
    N = x_binned.shape[0]
    valid = (jnp.ones(N, dtype=bool) if sample_mask is None
             else sample_mask.astype(bool))
    return histogram_from_rows(x_binned, grad, hess, valid, num_bins,
                               rows_per_block, precision)


# graftir IR contracts (`python -m lambdagap_tpu.analysis --ir`)
from ..analysis.ir.contracts import register_program

register_program(
    "histogram.full_histogram", collective_free=True,
    notes="root histogram over the full training slab; fixed shape")
register_program(
    "histogram.leaf_histogram", collective_free=True, max_traces=5,
    notes="host-serial per-leaf slices retrace per pow2 row bucket by "
          "design (the fused paths are where one-trace is contractual); "
          "the 1603-row scenario exercises 3 buckets")
