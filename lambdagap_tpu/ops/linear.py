"""Batched linear-leaf ops: MXU moment accumulation + one solve per tree.

Piece-wise linear regression trees ("Gradient Boosting With Piece-Wise
Linear Regression Trees", arXiv:1802.05640) fit a ridge-regularized linear
model in every leaf over the numeric features used on the leaf's path. The
reference implementation (src/treelearner/linear_tree_learner.cpp
CalculateLinear) loops leaves on the host, gathering each leaf's raw rows
and running one small normal-equations solve per leaf — exactly the shape
a TPU is worst at (many tiny host-driven solves) and the MXU is best at
when batched.

This module is the TPU formulation, and the SINGLE implementation both the
serial and the fused learners call — fused==serial bit-identity for linear
trees is by construction, not by parallel maintenance of two codepaths:

* :func:`accumulate_leaf_moments` — ONE jitted pass over the raw matrix in
  dataset-row order (chunked; each chunk contracts a one-hot leaf-membership
  matrix against the per-row design vectors on the MXU) producing
  ``X^T H X`` ``[L+1, P, P]``, ``X^T g`` ``[L+1, P]`` and valid-row counts
  per leaf, where ``P = FL + 1`` (padded feature slots + intercept). Row
  order is canonical (dataset order), so the accumulation is independent
  of which learner produced the row->leaf map.
* :func:`solve_linear_leaves` — ONE batched float64 solve over the
  ``[L, P, P]`` stack (``linear_lambda`` on the feature diagonal, identity
  rows on padding slots), with the reference's fallbacks: a singular or
  non-finite system, too few non-NaN rows, or an empty feature set leaves
  the constant leaf in place.
* :func:`linear_leaf_values` — the device-side per-row leaf evaluation
  (``const + coeff . x`` with the NaN fallback to the constant leaf value)
  shared verbatim by BOTH predict engines (ops/predict.py scan oracle and
  ops/predict_tensor.py), so tensor==scan ``array_equal`` holds for linear
  forests the same way it does for constant ones.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def leaf_feature_width(num_numeric: int, num_leaves: int) -> int:
    """The padded per-leaf feature-slot count FL, FIXED per config.

    A leaf's path can reference at most ``min(num_numeric, num_leaves-1)``
    distinct numeric features; padding to that bound (rounded to a
    multiple of 8, floor 8) keeps the jitted accumulation at ONE compiled
    shape for the whole run — per-tree widths would retrace the program
    every time a deeper path appeared (the steady-state recompile class
    the telemetry gate forbids)."""
    need = max(1, min(int(num_numeric), max(int(num_leaves) - 1, 1)))
    return max(8, ((need + 7) // 8) * 8)


def moment_chunk_rows(num_leaves: int, width: int) -> int:
    """Rows per accumulation chunk: the [W, (L+1)*P] one-hot design
    operand is the peak intermediate; bound it near 64 MB so HIGGS- and
    MSLR-shaped configs both fit comfortably beside the training state."""
    P = width + 1
    budget = (64 << 20) // max((num_leaves + 1) * P * 4, 1)
    return max(256, min(4096, budget))


@functools.partial(jax.jit, static_argnames=("num_leaves", "chunk"))
def accumulate_leaf_moments(X: jax.Array, leaf_idx: jax.Array,
                            grad: jax.Array, hess: jax.Array,
                            feat_tbl: jax.Array, *, num_leaves: int,
                            chunk: int
                            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-leaf normal-equation moments in ONE device pass.

    X: [N, D] raw float32 features (the linear_tree-retained matrix).
    leaf_idx: [N] int32 row->leaf map (searchsorted order from either
        learner; values in [0, L)).
    grad/hess: [N] float32 sampled gradients.
    feat_tbl: [L+1, FL] int32 per-leaf sorted numeric path features,
        ``-1`` on padding slots (row L is the dump row — all padding).

    Returns (XtHX [L+1, P, P] f32, Xtg [L+1, P] f32, cnt [L+1] f32) with
    P = FL + 1; slot P-1 is the intercept. Rows with NaN in any of their
    leaf's REAL feature slots contribute nothing (the reference's NaN
    fallback); their count is excluded so the eligibility check matches
    the per-leaf loop it replaces. Chunks accumulate in dataset-row order
    with a fixed trip count, so the result is independent of the learner
    that produced ``leaf_idx`` — the fused==serial bit-identity anchor.
    """
    N, D = X.shape
    Lp1, FL = feat_tbl.shape
    assert Lp1 == num_leaves + 1
    P = FL + 1
    nch = (N + chunk - 1) // chunk
    pad = nch * chunk - N
    Xp = jnp.concatenate([X, jnp.zeros((pad, D), X.dtype)]) if pad else X
    lp = jnp.concatenate(
        [leaf_idx.astype(jnp.int32),
         jnp.full(pad, num_leaves, jnp.int32)]) if pad else leaf_idx
    gp = jnp.concatenate([grad, jnp.zeros(pad, grad.dtype)]) if pad else grad
    hp = jnp.concatenate([hess, jnp.zeros(pad, hess.dtype)]) if pad else hess

    def body(carry, c):
        XtHX, Xtg, cnt = carry
        sl = lambda a: lax.dynamic_slice_in_dim(a, c * chunk, chunk)
        xw = sl(Xp)                            # [W, D]
        lw = jnp.clip(sl(lp), 0, num_leaves)   # [W]
        gw, hw = sl(gp), sl(hp)
        feats = feat_tbl[lw]                   # [W, FL]
        slot = feats >= 0
        vals = jnp.take_along_axis(xw, jnp.clip(feats, 0, D - 1), axis=1)
        nan_row = jnp.any(slot & jnp.isnan(vals), axis=1)
        ok = ~nan_row & (lw < num_leaves)
        v = jnp.where(slot & ~jnp.isnan(vals), vals, 0.0)
        v = jnp.concatenate([v, jnp.ones((chunk, 1), v.dtype)], axis=1)
        g = jnp.where(ok, gw, 0.0)
        h = jnp.where(ok, hw, 0.0)
        onehot = (lw[:, None] == jnp.arange(Lp1, dtype=jnp.int32)[None, :]
                  ) & ok[:, None]              # [W, L+1]
        oh = onehot.astype(jnp.float32)
        # the MXU contraction: per-leaf sum of h-weighted outer products
        # — one [ (L+1)*P x W ] @ [ W x P ] matmul per chunk
        vh = v * h[:, None]                    # [W, P]
        XtHX = XtHX + jnp.einsum("wl,wp,wq->lpq", oh, vh, v)
        Xtg = Xtg + jnp.einsum("wl,wp->lp", oh, v * g[:, None])
        cnt = cnt + jnp.sum(oh, axis=0)
        return (XtHX, Xtg, cnt), None

    init = (jnp.zeros((Lp1, P, P), jnp.float32),
            jnp.zeros((Lp1, P), jnp.float32),
            jnp.zeros(Lp1, jnp.float32))
    (XtHX, Xtg, cnt), _ = lax.scan(body, init,
                                   jnp.arange(nch, dtype=jnp.int32))
    return XtHX, Xtg, cnt


def solve_linear_leaves(XtHX: np.ndarray, Xtg: np.ndarray, cnt: np.ndarray,
                        nfeat: np.ndarray, linear_lambda: float
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """ONE batched regularized solve over the [L, P, P] moment stack.

    Host float64 (the coefficients are serialized into model text and
    replayed exactly — float64 solve output is the payload contract).
    ``linear_lambda`` rides the FEATURE diagonal only (the intercept is
    unregularized, matching the reference); padding slots get identity
    rows so the batch stays non-singular regardless of ragged per-leaf
    widths. Returns (sol [L, P] f64, ok [L] bool) where ``ok`` is the
    reference's eligibility: >= 1 path feature, more valid rows than
    unknowns, finite solution, non-singular system.
    """
    L, P = Xtg.shape
    FL = P - 1
    M = XtHX.astype(np.float64).copy()
    b = -Xtg.astype(np.float64)
    slots = np.arange(FL)[None, :] < nfeat[:, None]          # [L, FL]
    fd = np.arange(FL)
    M[:, fd, fd] += np.where(slots, float(linear_lambda), 0.0)
    # padding slots (and the intercept row of feature-less leaves) would be
    # all-zero rows; identity them so ONE batched solve covers the ragged
    # stack, then mask ineligible leaves after
    dead = np.concatenate([~slots, np.zeros((L, 1), bool)], axis=1)
    for j in range(P):
        rows = dead[:, j]
        if rows.any():
            M[rows, j, :] = 0.0
            M[rows, :, j] = 0.0
            M[rows, j, j] = 1.0
            b[rows, j] = 0.0
    try:
        sol = np.linalg.solve(M, b[..., None])[..., 0]
        solved = np.ones(L, bool)
    except np.linalg.LinAlgError:
        # rare (linear_lambda=0 + degenerate leaf): retry leaf-by-leaf so
        # one singular system only constant-falls ITS leaf
        sol = np.zeros((L, P), np.float64)
        solved = np.zeros(L, bool)
        for leaf in range(L):
            try:
                sol[leaf] = np.linalg.solve(M[leaf], b[leaf])
                solved[leaf] = True
            # graftlint: disable=R8 — a singular leaf system IS the signal:
            # solved[leaf] stays False and the caller keeps the constant
            # leaf (the reference's CalculateLinear fallback); there is
            # nothing to log per leaf
            except np.linalg.LinAlgError:
                pass
    ok = (solved & (nfeat >= 1) & (cnt >= nfeat + 1)
          & np.isfinite(sol).all(axis=1))
    return sol, ok


# ---------------------------------------------------------------------------
# device-side linear leaf evaluation (shared by BOTH predict engines)
# ---------------------------------------------------------------------------

def linear_leaf_values(x: jax.Array, leaf_flat: jax.Array,
                       leaf_value_flat: jax.Array,
                       leaf_const_flat: jax.Array,
                       leaf_feat_flat: jax.Array,
                       leaf_coeff_flat: jax.Array) -> jax.Array:
    """Per-row linear leaf outputs on device, f32.

    x: [R, D] raw float rows; leaf_flat: [R, K] flat leaf indices into the
    (tree-major) flattened leaf tables (K = trees evaluated per row: 1 for
    the scan engine's per-tree call, Tt for a tensor tile).
    leaf_*_flat: [T*L(, FL)] flattened per-leaf tables; feature ``-1``
    marks a padding slot.

    Semantics replicate ``models.tree.linear_leaf_outputs`` decision for
    decision: a row with NaN in any REAL slot of its leaf falls back to the
    constant ``leaf_value``; otherwise ``leaf_const + sum_j coeff_j * x_j``
    accumulated in fixed slot order (a fori_loop, so the f32 addition
    order — and therefore the bits — are identical wherever this runs:
    scan engine, tensor engine, any tile shape)."""
    R, K = leaf_flat.shape
    FL = leaf_feat_flat.shape[-1]
    D = x.shape[1]
    feats = leaf_feat_flat[leaf_flat]                  # [R, K, FL]
    slot = feats >= 0
    safe = jnp.clip(feats, 0, D - 1)
    vals = jnp.take_along_axis(x, safe.reshape(R, K * FL),
                               axis=1).reshape(R, K, FL)
    nan_row = jnp.any(slot & jnp.isnan(vals), axis=-1)           # [R, K]
    v = jnp.where(slot & ~jnp.isnan(vals), vals, jnp.float32(0.0))
    coeff = leaf_coeff_flat[leaf_flat]                 # [R, K, FL]

    def body(j, acc):
        return acc + coeff[..., j] * v[..., j]

    lin = lax.fori_loop(0, FL, body, leaf_const_flat[leaf_flat])
    return jnp.where(nan_row, leaf_value_flat[leaf_flat], lin)


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "linear.accumulate_leaf_moments", collective_free=True,
    notes="linear-leaf Gram/moment accumulation stays on device")
