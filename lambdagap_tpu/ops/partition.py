"""Leaf data partition.

TPU analog of ``DataPartition`` (reference:
src/treelearner/data_partition.hpp:21-123): a permutation array of row indices
grouped by leaf plus per-leaf (begin, count). Splitting a leaf stably
partitions its index slice. The reference CPU uses a parallel two-way stable
partition; the CUDA learner uses bit-vector + prefix sums
(reference: src/treelearner/cuda/cuda_data_partition.hpp:106-139). Here the
stable partition is a key sort over the padded slice (O(P log P) but fully
vectorized on the VPU), followed by an in-range scatter back into the
permutation array.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .split import MT_NAN, MT_ZERO


def decision_go_left(bin_vals: jax.Array, threshold: jax.Array,
                     default_left: jax.Array, default_bin: jax.Array,
                     missing_type: jax.Array, num_bin: jax.Array,
                     is_categorical: jax.Array, cat_bitset: jax.Array) -> jax.Array:
    """Routing decision for a batch of bin values of one feature.

    Mirrors the train-time split semantics of the reference's Bin::Split
    (reference: src/io/dense_bin.hpp Split / tree.h Decision): numerical goes
    left iff ``bin <= threshold``; rows in the missing bin follow
    ``default_left``; categorical goes left iff its bin is in the bitset.
    """
    b = bin_vals.astype(jnp.int32)
    is_missing = jnp.where(
        missing_type == MT_ZERO, b == default_bin,
        jnp.where(missing_type == MT_NAN, b == num_bin - 1, False))
    num_left = jnp.where(is_missing, default_left, b <= threshold)
    word = jnp.clip(b // 32, 0, cat_bitset.shape[0] - 1)
    bit = jnp.right_shift(cat_bitset[word], (b % 32).astype(jnp.uint32)) & 1
    cat_left = bit == 1
    return jnp.where(is_categorical, cat_left, num_left)


@functools.partial(jax.jit, static_argnames=("padded_size",))
def split_partition(x_binned: jax.Array, perm: jax.Array,
                    begin: jax.Array, count: jax.Array,
                    feature: jax.Array, threshold: jax.Array,
                    default_left: jax.Array, default_bin: jax.Array,
                    missing_type: jax.Array, num_bin: jax.Array,
                    is_categorical: jax.Array, cat_bitset: jax.Array,
                    padded_size: int):
    """Stably partition one leaf's slice of the permutation array.

    Returns ``(new_perm, left_count)``. Rows with ``go_left`` keep their
    relative order at the front of the slice, the rest follow — matching the
    reference's stable two-way partition (data_partition.hpp:100-123) so that
    ordered-gradient gathers stay deterministic.
    """
    N = perm.shape[0]
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = begin + lane
    safe_idx = jnp.clip(idx, 0, N - 1)
    rows = perm[safe_idx]
    valid = lane < count

    bin_vals = x_binned[rows, feature]
    go_left = decision_go_left(bin_vals, threshold, default_left, default_bin,
                               missing_type, num_bin, is_categorical, cat_bitset)
    go_left = go_left & valid

    # stable 3-way key: valid&left -> 0, valid&right -> 1, padding -> 2;
    # combined with the lane index so one int32 sort is stable
    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key * padded_size + lane)
    new_slice = rows[order]

    left_count = jnp.sum(go_left, dtype=jnp.int32)
    # scatter back; out-of-range lanes dropped, padding lanes rewrite their
    # original values (they sort after all valid lanes, preserving order)
    new_perm = perm.at[idx].set(new_slice, mode="drop")
    return new_perm, left_count


@functools.partial(jax.jit, static_argnames=("padded_size",))
def split_partition_sorted(x_sorted: jax.Array, gh_sorted: jax.Array,
                           perm: jax.Array, begin: jax.Array,
                           count: jax.Array, feature: jax.Array,
                           threshold: jax.Array, default_left: jax.Array,
                           default_bin: jax.Array, missing_type: jax.Array,
                           num_bin: jax.Array, is_categorical: jax.Array,
                           cat_bitset: jax.Array, padded_size: int):
    """:func:`split_partition` under ``tree_layout=sorted``: the stable
    partition of one leaf's slice is applied PHYSICALLY — the binned row
    payload (``x_sorted``, position-ordered [N, F]) and the gradient
    channels (``gh_sorted``, [N, 2 or 3] f32 grad/hess[/in-bag]) are
    permuted alongside the permutation array, so the next histogram pass
    reads the leaf as a contiguous stream (docs/performance.md).

    The split feature's bin values come straight out of the sorted window
    (a consecutive-index read) instead of a row gather through ``perm``.
    Functional updates (no donation): this is the host-orchestrated oracle
    path; the zero-copy production variant lives inside the fused program.

    Returns ``(new_perm, new_x_sorted, new_gh_sorted, left_count)``.
    """
    N = perm.shape[0]
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = begin + lane
    safe_idx = jnp.clip(idx, 0, N - 1)
    rows = perm[safe_idx]
    valid = lane < count

    bin_vals = x_sorted[safe_idx, feature]
    go_left = decision_go_left(bin_vals, threshold, default_left, default_bin,
                               missing_type, num_bin, is_categorical,
                               cat_bitset)
    go_left = go_left & valid

    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key * padded_size + lane)
    left_count = jnp.sum(go_left, dtype=jnp.int32)

    # the same scatter-back contract as split_partition: padding lanes sort
    # after all valid lanes in their original order, so they rewrite their
    # own values; out-of-range lanes drop
    new_perm = perm.at[idx].set(rows[order], mode="drop")
    new_x = x_sorted.at[idx].set(x_sorted[safe_idx][order], mode="drop")
    new_gh = gh_sorted.at[idx].set(gh_sorted[safe_idx][order], mode="drop")
    return new_perm, new_x, new_gh, left_count


# ---------------------------------------------------------------------------
# data_residency=stream variants (docs/performance.md "Out-of-core"):
# the split feature's bin values arrive as an UPLOADED buffer (the host
# gathered them from its shards — 1-2 bytes per row over the link instead
# of holding the whole matrix in HBM). Decision + permutation math is
# bit-identical to the resident kernels above; the host mirrors the
# resulting order from the returned go_left flags (stable: lefts then
# rights, each in slice order).
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("padded_size",))
def split_partition_vals(bin_vals: jax.Array, perm: jax.Array,
                         begin: jax.Array, count: jax.Array,
                         threshold: jax.Array, default_left: jax.Array,
                         default_bin: jax.Array, missing_type: jax.Array,
                         num_bin: jax.Array, is_categorical: jax.Array,
                         cat_bitset: jax.Array, padded_size: int):
    """:func:`split_partition` with host-supplied bin values.

    ``bin_vals[i]`` is the split feature's bin for the row at slice lane
    ``i`` (padding lanes arbitrary — they sort last and never count).
    Returns ``(new_perm, left_count, go_left)``; ``go_left`` lets the host
    update its permutation mirror without a second transfer of the slice.
    """
    N = perm.shape[0]
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = begin + lane
    safe_idx = jnp.clip(idx, 0, N - 1)
    rows = perm[safe_idx]
    valid = lane < count

    go_left = decision_go_left(bin_vals.astype(jnp.int32), threshold,
                               default_left, default_bin, missing_type,
                               num_bin, is_categorical, cat_bitset)
    go_left = go_left & valid

    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key * padded_size + lane)
    left_count = jnp.sum(go_left, dtype=jnp.int32)
    new_perm = perm.at[idx].set(rows[order], mode="drop")
    return new_perm, left_count, go_left


@functools.partial(jax.jit, static_argnames=("padded_size",))
def split_partition_sorted_vals(bin_vals: jax.Array, gh_sorted: jax.Array,
                                perm: jax.Array, begin: jax.Array,
                                count: jax.Array, threshold: jax.Array,
                                default_left: jax.Array,
                                default_bin: jax.Array,
                                missing_type: jax.Array, num_bin: jax.Array,
                                is_categorical: jax.Array,
                                cat_bitset: jax.Array, padded_size: int):
    """:func:`split_partition_sorted` with host-supplied bin values: the
    binned payload lives in HOST shards under stream residency, so only
    ``perm`` and the device-resident gradient channels are permuted here;
    the host applies the same stable order to its payload slice from the
    returned ``go_left`` flags. Returns
    ``(new_perm, new_gh_sorted, left_count, go_left)``."""
    N = perm.shape[0]
    lane = jnp.arange(padded_size, dtype=jnp.int32)
    idx = begin + lane
    safe_idx = jnp.clip(idx, 0, N - 1)
    rows = perm[safe_idx]
    valid = lane < count

    go_left = decision_go_left(bin_vals.astype(jnp.int32), threshold,
                               default_left, default_bin, missing_type,
                               num_bin, is_categorical, cat_bitset)
    go_left = go_left & valid

    key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
    order = jnp.argsort(key * padded_size + lane)
    left_count = jnp.sum(go_left, dtype=jnp.int32)
    new_perm = perm.at[idx].set(rows[order], mode="drop")
    new_gh = gh_sorted.at[idx].set(gh_sorted[safe_idx][order], mode="drop")
    return new_perm, new_gh, left_count, go_left


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "partition.split_partition", collective_free=True, max_traces=6,
    notes="host-serial permutation update retraces per pow2 leaf bucket "
          "by design; the 1603-row scenario exercises 4 buckets")
