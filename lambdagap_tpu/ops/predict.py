"""Batched tree traversal on device.

TPU analog of the reference's prediction paths: per-row inline traversal
(reference: include/LightGBM/tree.h:130-141 Predict/NumericalDecision) and the
binned-data traversal used for validation-score updates
(reference: tree.h AddPredictionToScore over the train/valid Dataset).

Trees are stacked into padded arrays and traversed with a bounded
``fori_loop`` (leaf-wise trees record their true max depth at build time);
rows are vectorized with ``vmap`` so the whole batch advances one level per
iteration — the same shape as the CUDA tree-predict kernel
(reference: src/io/cuda/cuda_tree.cu).
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K_ZERO_THRESHOLD = 1e-35
MT_NONE, MT_ZERO, MT_NAN = 0, 1, 2


class TreeArrays(NamedTuple):
    """One tree in device-friendly form. M = padded internal-node count."""
    split_feature: jax.Array   # i32 [M] — feature index (original or inner)
    threshold: jax.Array       # f32 [M] raw threshold (numerical)
    threshold_bin: jax.Array   # i32 [M] bin threshold (numerical, binned data)
    default_left: jax.Array    # bool [M]
    missing_type: jax.Array    # i32 [M]
    default_bin: jax.Array     # i32 [M] (binned decisions, Zero-missing)
    num_bin: jax.Array         # i32 [M] (binned decisions, NaN-missing)
    left_child: jax.Array      # i32 [M]
    right_child: jax.Array     # i32 [M]
    is_categorical: jax.Array  # bool [M]
    cat_bitset: jax.Array      # u32 [M, 8] bin-space bitset
    cat_bitset_real: jax.Array  # u32 [M, 8] raw-category bitset
    leaf_value: jax.Array      # f32 [L]


def tree_to_arrays(tree, feature_meta=None, use_inner_feature: bool = False,
                   pad_nodes: int = 0) -> TreeArrays:
    """Stack a host Tree into TreeArrays.

    feature_meta: dict from BinnedDataset.feature_arrays() — required for
    binned traversal (default_bin / num_bin per node's feature).
    """
    n = max(tree.num_internal, 1)
    M = max(n, pad_nodes)

    def pad_i(vals, fill=0, dtype=np.int32):
        a = np.full(M, fill, dtype=dtype)
        a[:len(vals)] = vals
        return jnp.asarray(a)

    def pad_f(vals, fill=0.0):
        a = np.full(M, fill, dtype=np.float32)
        a[:len(vals)] = vals
        return jnp.asarray(a)

    feats = tree.split_feature_inner if use_inner_feature else tree.split_feature
    if tree.num_internal == 0:
        # degenerate single-leaf tree: both children point at leaf 0
        left = [~0]
        right = [~0]
        feats = [0]
    else:
        left = tree.left_child
        right = tree.right_child

    default_bin = np.zeros(M, dtype=np.int32)
    num_bin = np.zeros(M, dtype=np.int32)
    if feature_meta is not None:
        fi = np.asarray(tree.split_feature_inner[:tree.num_internal], dtype=np.int64)
        if len(fi):
            default_bin[:len(fi)] = feature_meta["default_bins"][fi]
            num_bin[:len(fi)] = feature_meta["num_bins"][fi]

    bits = np.zeros((M, 8), dtype=np.uint32)
    bits_real = np.zeros((M, 8), dtype=np.uint32)
    for i in range(tree.num_internal):
        bits[i] = tree.cat_bitset[i]
        bits_real[i] = tree.cat_bitset_real[i][:8] if len(tree.cat_bitset_real[i]) >= 8 \
            else np.pad(tree.cat_bitset_real[i], (0, 8 - len(tree.cat_bitset_real[i])))

    L = max(tree.num_leaves, 1)
    return TreeArrays(
        split_feature=pad_i(feats[:max(tree.num_internal, 1)]),
        threshold=pad_f(tree.threshold_real),
        threshold_bin=pad_i(tree.threshold_bin),
        default_left=pad_i(tree.default_left, dtype=bool),
        missing_type=pad_i(tree.missing_type),
        default_bin=jnp.asarray(default_bin),
        num_bin=jnp.asarray(num_bin),
        left_child=pad_i(left, fill=~0),
        right_child=pad_i(right, fill=~0),
        is_categorical=pad_i(tree.is_categorical, dtype=bool),
        cat_bitset=jnp.asarray(bits),
        cat_bitset_real=jnp.asarray(bits_real),
        leaf_value=jnp.asarray(tree.leaf_value[:L], dtype=jnp.float32),
    )


def _cat_go_left(cat: jax.Array, bitset_row: jax.Array) -> jax.Array:
    inb = (cat >= 0) & (cat < bitset_row.shape[-1] * 32)
    safe = jnp.clip(cat, 0, bitset_row.shape[-1] * 32 - 1)
    word = safe // 32
    bit = (bitset_row[word] >> (safe % 32).astype(jnp.uint32)) & jnp.uint32(1)
    return inb & (bit == jnp.uint32(1))


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree_raw(x: jax.Array, t: TreeArrays, max_depth: int) -> jax.Array:
    """Predict one tree on raw float features [N, D] -> [N] leaf values."""

    def traverse(row):
        def body(_, node):
            def step(n):
                f = t.split_feature[n]
                v = row[f]
                nan = jnp.isnan(v)
                mt = t.missing_type[n]
                # NaN converted to 0 unless NaN-missing
                # (reference: tree.h NumericalDecision)
                v0 = jnp.where(nan & (mt != MT_NAN), 0.0, v)
                missing = ((mt == MT_NAN) & nan) | \
                          ((mt == MT_ZERO) & (jnp.abs(v0) <= K_ZERO_THRESHOLD))
                go_num = jnp.where(missing, t.default_left[n], v0 <= t.threshold[n])
                cat = jnp.where(nan, -1, v).astype(jnp.int32)
                go_cat = _cat_go_left(cat, t.cat_bitset_real[n])
                go = jnp.where(t.is_categorical[n], go_cat, go_num)
                return jnp.where(go, t.left_child[n], t.right_child[n])
            return jnp.where(node < 0, node, step(jnp.maximum(node, 0)))

        node = lax.fori_loop(0, max_depth, body, jnp.int32(0))
        return t.leaf_value[~node]

    return jax.vmap(traverse)(x)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree_binned(x_binned: jax.Array, t: TreeArrays,
                        max_depth: int) -> jax.Array:
    """Predict one tree on the binned matrix [N, F] (train/valid data).
    Exactly mirrors train-time routing (ops.partition.decision_go_left)."""

    def traverse(row):
        def body(_, node):
            def step(n):
                f = t.split_feature[n]
                b = row[f].astype(jnp.int32)
                mt = t.missing_type[n]
                missing = ((mt == MT_ZERO) & (b == t.default_bin[n])) | \
                          ((mt == MT_NAN) & (b == t.num_bin[n] - 1))
                go_num = jnp.where(missing, t.default_left[n],
                                   b <= t.threshold_bin[n])
                go_cat = _cat_go_left(b, t.cat_bitset[n])
                go = jnp.where(t.is_categorical[n], go_cat, go_num)
                return jnp.where(go, t.left_child[n], t.right_child[n])
            return jnp.where(node < 0, node, step(jnp.maximum(node, 0)))

        node = lax.fori_loop(0, max_depth, body, jnp.int32(0))
        return t.leaf_value[~node]

    return jax.vmap(traverse)(x_binned)


@functools.partial(jax.jit, static_argnames=("max_depth", "output_leaf"))
def predict_leaf_index_binned(x_binned: jax.Array, t: TreeArrays,
                              max_depth: int, output_leaf: bool = True) -> jax.Array:
    """Leaf index per row (for refit / predict_leaf_index)."""

    def traverse(row):
        def body(_, node):
            def step(n):
                f = t.split_feature[n]
                b = row[f].astype(jnp.int32)
                mt = t.missing_type[n]
                missing = ((mt == MT_ZERO) & (b == t.default_bin[n])) | \
                          ((mt == MT_NAN) & (b == t.num_bin[n] - 1))
                go_num = jnp.where(missing, t.default_left[n],
                                   b <= t.threshold_bin[n])
                go_cat = _cat_go_left(b, t.cat_bitset[n])
                go = jnp.where(t.is_categorical[n], go_cat, go_num)
                return jnp.where(go, t.left_child[n], t.right_child[n])
            return jnp.where(node < 0, node, step(jnp.maximum(node, 0)))

        return ~lax.fori_loop(0, max_depth, body, jnp.int32(0))

    return jax.vmap(traverse)(x_binned)
