"""Batched tree traversal on device.

TPU analog of the reference's prediction paths: per-row inline traversal
(reference: include/LightGBM/tree.h:130-141 Predict/NumericalDecision) and the
binned-data traversal used for validation-score updates
(reference: tree.h AddPredictionToScore over the train/valid Dataset).

Trees are stacked into padded arrays and traversed with a bounded
``fori_loop`` (leaf-wise trees record their true max depth at build time);
rows are vectorized with ``vmap`` so the whole batch advances one level per
iteration — the same shape as the CUDA tree-predict kernel
(reference: src/io/cuda/cuda_tree.cu).

Whole forests are traversed in ONE jitted dispatch: trees are stacked along a
leading ``T`` axis and a ``lax.scan`` accumulates per-class scores without
materializing the ``[T, N]`` intermediate (the analog of ``GBDT::Predict``
iterating inlined trees, reference: src/boosting/gbdt_prediction.cpp).
"""
from __future__ import annotations

import functools
import os
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K_ZERO_THRESHOLD = 1e-35
MT_NONE, MT_ZERO, MT_NAN = 0, 1, 2


class TreeArrays(NamedTuple):
    """One tree in device-friendly form. M = padded internal-node count.
    When stacked into a forest, every field gains a leading T axis."""
    split_feature: jax.Array   # i32 [M] — feature index (original or inner)
    threshold: jax.Array       # f32 [M] raw threshold (numerical)
    threshold_bin: jax.Array   # i32 [M] bin threshold (numerical, binned data)
    default_left: jax.Array    # bool [M]
    missing_type: jax.Array    # i32 [M]
    default_bin: jax.Array     # i32 [M] (binned decisions, Zero-missing)
    num_bin: jax.Array         # i32 [M] (binned decisions, NaN-missing)
    left_child: jax.Array      # i32 [M]
    right_child: jax.Array     # i32 [M]
    is_categorical: jax.Array  # bool [M]
    cat_bitset: jax.Array      # u32 [M, 8] bin-space bitset
    cat_bitset_real: jax.Array  # u32 [M, W] raw-category bitset (W >= 8,
    #                              sized to the largest category; reference
    #                              sizes these dynamically via
    #                              Common::ConstructBitset, src/io/tree.cpp)
    leaf_value: jax.Array      # f32 [L]
    # piece-wise linear leaf payload (docs/linear-trees.md): constant term,
    # padded per-leaf feature ids (-1 = empty slot) and coefficients. For
    # constant trees leaf_const == leaf_value and every slot is empty, so
    # the linear traversal carry degenerates to the constant gather —
    # engines only read these under has_linear=True (raw rows only).
    leaf_const: jax.Array      # f32 [L]
    leaf_feat: jax.Array       # i32 [L, FL]
    leaf_coeff: jax.Array      # f32 [L, FL]


def tree_to_arrays(tree, feature_meta=None, use_inner_feature: bool = False,
                   pad_nodes: int = 0, pad_leaves: int = 0,
                   pad_cat_words: int = 0, pad_leaf_feats: int = 0) -> TreeArrays:
    """Stack a host Tree into TreeArrays.

    feature_meta: dict from BinnedDataset.feature_arrays() — required for
    binned traversal (default_bin / num_bin per node's feature).
    pad_nodes / pad_leaves / pad_cat_words / pad_leaf_feats: minimum padded
    sizes, used to align trees before stacking them into a forest.
    """
    n = max(tree.num_internal, 1)
    M = max(n, pad_nodes)

    def pad_i(vals, fill=0, dtype=np.int32):
        a = np.full(M, fill, dtype=dtype)
        a[:len(vals)] = vals
        return jnp.asarray(a)

    def pad_f(vals, fill=0.0):
        a = np.full(M, fill, dtype=np.float32)
        a[:len(vals)] = vals
        return jnp.asarray(a)

    feats = tree.split_feature_inner if use_inner_feature else tree.split_feature
    if tree.num_internal == 0:
        # degenerate single-leaf tree: both children point at leaf 0
        left = [~0]
        right = [~0]
        feats = [0]
    else:
        left = tree.left_child
        right = tree.right_child

    default_bin = np.zeros(M, dtype=np.int32)
    num_bin = np.zeros(M, dtype=np.int32)
    if feature_meta is not None:
        fi = np.asarray(tree.split_feature_inner[:tree.num_internal], dtype=np.int64)
        if len(fi):
            default_bin[:len(fi)] = feature_meta["default_bins"][fi]
            num_bin[:len(fi)] = feature_meta["num_bins"][fi]

    W = max(8, pad_cat_words,
            max((len(tree.cat_bitset_real[i]) for i in range(tree.num_internal)),
                default=0))
    bits = np.zeros((M, 8), dtype=np.uint32)
    bits_real = np.zeros((M, W), dtype=np.uint32)
    for i in range(tree.num_internal):
        bb = np.asarray(tree.cat_bitset[i], dtype=np.uint32)[:8]
        bits[i, :len(bb)] = bb
        br = np.asarray(tree.cat_bitset_real[i], dtype=np.uint32)
        bits_real[i, :len(br)] = br

    L = max(tree.num_leaves, 1, pad_leaves)
    leaf_value = np.zeros(L, dtype=np.float32)
    leaf_value[:max(tree.num_leaves, 1)] = \
        tree.leaf_value[:max(tree.num_leaves, 1)]
    # linear payload: constant trees carry leaf_const == leaf_value with
    # every slot empty, so a mixed (linear + constant) forest evaluates
    # uniformly under has_linear=True
    FL = max(1, pad_leaf_feats,
             max((len(tree.leaf_features[i]) for i in range(tree.num_leaves)),
                 default=0) if getattr(tree, "is_linear", False) else 0)
    leaf_const = leaf_value.copy()
    leaf_feat = np.full((L, FL), -1, dtype=np.int32)
    leaf_coeff = np.zeros((L, FL), dtype=np.float32)
    if getattr(tree, "is_linear", False):
        nl = tree.num_leaves
        leaf_const[:nl] = np.asarray(tree.leaf_const[:nl], np.float32)
        for i in range(nl):
            lfeats = tree.leaf_features[i]
            if lfeats:
                leaf_feat[i, :len(lfeats)] = lfeats
                leaf_coeff[i, :len(lfeats)] = np.asarray(tree.leaf_coeff[i],
                                                         np.float32)
    return TreeArrays(
        split_feature=pad_i(feats[:max(tree.num_internal, 1)]),
        threshold=pad_f(tree.threshold_real),
        threshold_bin=pad_i(tree.threshold_bin),
        default_left=pad_i(tree.default_left, dtype=bool),
        missing_type=pad_i(tree.missing_type),
        default_bin=jnp.asarray(default_bin),
        num_bin=jnp.asarray(num_bin),
        left_child=pad_i(left, fill=~0),
        right_child=pad_i(right, fill=~0),
        is_categorical=pad_i(tree.is_categorical, dtype=bool),
        cat_bitset=jnp.asarray(bits),
        cat_bitset_real=jnp.asarray(bits_real),
        leaf_value=jnp.asarray(leaf_value),
        leaf_const=jnp.asarray(leaf_const),
        leaf_feat=jnp.asarray(leaf_feat),
        leaf_coeff=jnp.asarray(leaf_coeff),
    )


def forest_to_arrays(trees, feature_meta=None,
                     use_inner_feature: bool = False
                     ) -> Tuple[TreeArrays, int]:
    """Stack host Trees into one TreeArrays with a leading T axis, padded to
    common node/leaf/bitset-width sizes (rounded up to bound jit retraces).
    Returns (stacked arrays, padded max_depth)."""
    assert trees, "forest_to_arrays needs at least one tree"

    def _round32(v: int) -> int:
        return max(32, ((v + 31) // 32) * 32)

    M = _round32(max(max(t.num_internal, 1) for t in trees))
    L = _round32(max(max(t.num_leaves, 1) for t in trees))
    W = max([8] + [len(t.cat_bitset_real[i]) for t in trees
                   for i in range(t.num_internal)])
    # linear leaf slots, rounded up so appended trees rarely change FL
    # (a new width re-stacks the forest, it never recompiles silently)
    FLr = max([0] + [len(t.leaf_features[i]) for t in trees
                     if getattr(t, "is_linear", False)
                     for i in range(t.num_leaves)])
    FL = max(1, ((FLr + 3) // 4) * 4) if FLr else 1
    depth = _round_depth(max(t.max_depth for t in trees) + 1)
    per_tree = [tree_to_arrays(t, feature_meta, use_inner_feature,
                               pad_nodes=M, pad_leaves=L, pad_cat_words=W,
                               pad_leaf_feats=FL)
                for t in trees]
    stacked = TreeArrays(*(jnp.stack(cols) for cols in zip(*per_tree)))
    return stacked, depth


def _round_depth(d: int) -> int:
    """Pad traversal depth to a multiple of 8 to bound jit specializations."""
    return max(8, ((d + 7) // 8) * 8)


def _cat_go_left(cat: jax.Array, bitset_row: jax.Array) -> jax.Array:
    inb = (cat >= 0) & (cat < bitset_row.shape[-1] * 32)
    safe = jnp.clip(cat, 0, bitset_row.shape[-1] * 32 - 1)
    word = safe // 32
    bit = (bitset_row[word] >> (safe % 32).astype(jnp.uint32)) & jnp.uint32(1)
    return inb & (bit == jnp.uint32(1))


def _traverse_leaf_id(x: jax.Array, t: TreeArrays, max_depth: int,
                      binned: bool) -> jax.Array:
    """Vectorized traversal of one tree over all rows -> leaf index [N].

    binned=True routes exactly like train-time partitioning
    (ops.partition.decision_go_left); binned=False uses raw thresholds with
    the reference's NaN/zero missing semantics (tree.h NumericalDecision).
    """

    def traverse(row):
        def body(_, node):
            def step(n):
                f = t.split_feature[n]
                if binned:
                    b = row[f].astype(jnp.int32)
                    mt = t.missing_type[n]
                    missing = ((mt == MT_ZERO) & (b == t.default_bin[n])) | \
                              ((mt == MT_NAN) & (b == t.num_bin[n] - 1))
                    go_num = jnp.where(missing, t.default_left[n],
                                       b <= t.threshold_bin[n])
                    go_cat = _cat_go_left(b, t.cat_bitset[n])
                else:
                    v = row[f]
                    nan = jnp.isnan(v)
                    mt = t.missing_type[n]
                    # NaN converted to 0 unless NaN-missing
                    # (reference: tree.h NumericalDecision)
                    v0 = jnp.where(nan & (mt != MT_NAN), 0.0, v)
                    missing = ((mt == MT_NAN) & nan) | \
                              ((mt == MT_ZERO) & (jnp.abs(v0) <= K_ZERO_THRESHOLD))
                    go_num = jnp.where(missing, t.default_left[n],
                                       v0 <= t.threshold[n])
                    cat = jnp.where(nan, -1, v).astype(jnp.int32)
                    go_cat = _cat_go_left(cat, t.cat_bitset_real[n])
                go = jnp.where(t.is_categorical[n], go_cat, go_num)
                return jnp.where(go, t.left_child[n], t.right_child[n])
            return jnp.where(node < 0, node, step(jnp.maximum(node, 0)))

        return ~lax.fori_loop(0, max_depth, body, jnp.int32(0))

    return jax.vmap(traverse)(x)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree_raw(x: jax.Array, t: TreeArrays, max_depth: int) -> jax.Array:
    """Predict one tree on raw float features [N, D] -> [N] leaf values."""
    return t.leaf_value[_traverse_leaf_id(x, t, max_depth, binned=False)]


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree_binned(x_binned: jax.Array, t: TreeArrays,
                        max_depth: int) -> jax.Array:
    """Predict one tree on the binned matrix [N, F] (train/valid data)."""
    return t.leaf_value[_traverse_leaf_id(x_binned, t, max_depth, binned=True)]


@functools.partial(jax.jit, static_argnames=("max_depth", "output_leaf"))
def predict_leaf_index_binned(x_binned: jax.Array, t: TreeArrays,
                              max_depth: int, output_leaf: bool = True) -> jax.Array:
    """Leaf index per row (for refit / predict_leaf_index)."""
    del output_leaf
    return _traverse_leaf_id(x_binned, t, max_depth, binned=True)


def _tree_leaf_vals(x: jax.Array, t: TreeArrays, max_depth: int,
                    binned: bool, has_linear: bool) -> jax.Array:
    """One tree's per-row output [N]: the constant leaf gather, or — for
    linear forests on raw rows — the shared per-leaf dot-product
    evaluation (ops/linear.py), identical op-for-op to the tensor
    engine's so both engines stay ``array_equal``."""
    leaf = _traverse_leaf_id(x, t, max_depth, binned)
    if not has_linear:
        return t.leaf_value[leaf]
    from .linear import linear_leaf_values
    return linear_leaf_values(x, leaf[:, None], t.leaf_value, t.leaf_const,
                              t.leaf_feat, t.leaf_coeff)[:, 0]


@functools.partial(jax.jit,
                   static_argnames=("num_class", "max_depth", "binned",
                                    "early_stop_freq", "has_linear"))
def _predict_forest_block(x: jax.Array, forest: TreeArrays,
                          tree_class: jax.Array, carry,
                          num_class: int, max_depth: int, binned: bool,
                          early_stop_freq: int = 0,
                          early_stop_margin: float = 0.0,
                          has_linear: bool = False):
    """One bounded block of trees, threading the (out, stopped, i) carry."""
    if early_stop_freq <= 0:
        out, stopped, i = carry

        def step(o, tk):
            t, k = tk
            vals = _tree_leaf_vals(x, t, max_depth, binned, has_linear)
            return o.at[k].add(vals), None

        out, _ = lax.scan(step, out, (forest, tree_class))
        return out, stopped, i

    def margin_of(out):
        if num_class == 1:
            # reference binary margin is 2*|raw score|
            # (src/boosting/prediction_early_stop.cpp)
            return 2.0 * jnp.abs(out[0])
        top2 = lax.top_k(out.T, 2)[0]          # [N, 2]
        return top2[:, 0] - top2[:, 1]

    def step(c, tk):
        out, stopped, i = c
        t, k = tk
        vals = _tree_leaf_vals(x, t, max_depth, binned, has_linear)
        out = out.at[k].add(jnp.where(stopped, 0.0, vals))
        i = i + 1
        check = (i % early_stop_freq) == 0
        stopped = jnp.where(check, stopped | (margin_of(out)
                                              > early_stop_margin), stopped)
        return (out, stopped, i), None

    (out, stopped, i), _ = lax.scan(step, carry, (forest, tree_class))
    return out, stopped, i


def build_forest_blocks(forest: TreeArrays, tree_class: jax.Array,
                        tree_block: Optional[int] = None):
    """Pre-slice a stacked forest into bounded, padded tree blocks ONCE.

    The blocked predict paths used to re-slice and zero-pad-concatenate the
    stacked forest per block on EVERY call, adding device copies of the
    whole forest each invocation (ADVICE round 5, predict.py:313). The
    forest is immutable between calls, so callers (the booster's predict
    cache, serve's CompiledForestCache) build the blocks once and pass them
    to :func:`predict_forest` / :func:`predict_forest_leaf`.

    Returns a tuple of ``(block TreeArrays, block tree_class, n_real)``
    entries, or ``None`` when the forest fits a single dispatch (callers
    pass the unsliced forest through unchanged in that case)."""
    T = int(tree_class.shape[0])
    if tree_block is None:
        tree_block = int(os.environ.get("LAMBDAGAP_PREDICT_TREE_BLOCK", 64))
    if tree_block <= 0 or T <= tree_block:
        return None
    out = []
    for b in range(0, T, tree_block):
        blk, tc = _forest_block(forest, tree_class, b, tree_block, T)
        out.append((blk, tc, min(b + tree_block, T) - b))
    return tuple(out)


def predict_forest(x: jax.Array, forest: TreeArrays, tree_class: jax.Array,
                   num_class: int, max_depth: int, binned: bool,
                   early_stop_freq: int = 0,
                   early_stop_margin: float = 0.0,
                   tree_block: Optional[int] = None,
                   blocks=None, has_linear: bool = False) -> jax.Array:
    """Sum a whole forest's leaf values into per-class scores.

    x: [N, D] raw floats (binned=False) or [N, F] binned (binned=True).
    forest: TreeArrays stacked along a leading T axis (forest_to_arrays).
    tree_class: i32 [T] — class index of each tree (iter-major, class-minor).
    early_stop_freq/margin: margin-based prediction early stopping — every
    ``freq`` trees, rows whose decision margin exceeds ``margin`` stop
    accumulating further trees (reference:
    src/boosting/prediction_early_stop.cpp; binary margin = |score|,
    multiclass = top1 - top2).
    Returns [num_class, N] float32.

    A ``lax.scan`` over trees keeps peak memory at O(N) instead of the
    O(T·N) a tree-vmapped traversal would materialize — the device analog
    of GBDT::Predict accumulating over inlined trees
    (reference: src/boosting/gbdt_prediction.cpp, cuda_tree.cu:459).

    The scan is dispatched in bounded blocks of ``tree_block`` trees
    (default ``LAMBDAGAP_PREDICT_TREE_BLOCK`` or 64) with the accumulator
    carried between dispatches: no single kernel grows with the forest, so
    a 500+ tree forest never exceeds what the device (or a tunneled
    worker) tolerates, at the cost of T/block dispatches. Forests at most
    one block long compile to the identical single kernel as before.

    ``blocks``: pre-sliced device blocks from :func:`build_forest_blocks`;
    passing them skips the per-call forest re-slice entirely.

    ``has_linear``: evaluate the per-leaf linear payload (raw rows only —
    linear leaves read raw feature values, which binned matrices no longer
    carry; callers replay binned linear forests host-side)."""
    assert not (binned and has_linear), \
        "linear forests traverse raw rows; binned linear replay is host-side"
    N = x.shape[0]
    T = tree_class.shape[0]
    if tree_block is None:
        tree_block = int(os.environ.get("LAMBDAGAP_PREDICT_TREE_BLOCK", 64))
    init = (jnp.zeros((num_class, N), jnp.float32),
            jnp.zeros(N, dtype=bool), jnp.int32(0))
    from ..obs import costplane
    if blocks is None:
        if tree_block <= 0 or T <= tree_block:
            out, _, _ = costplane.observed_call(
                "predict.scan", _predict_forest_block,
                (x, forest, tree_class, init, num_class, max_depth,
                 binned, early_stop_freq, early_stop_margin, has_linear),
                bucket=N, phase="predict")
            return out
        blocks = build_forest_blocks(forest, tree_class, tree_block)
    carry = init
    for blk, tc, _ in blocks:
        carry = costplane.observed_call(
            "predict.scan", _predict_forest_block,
            (x, blk, tc, carry, num_class, max_depth, binned,
             early_stop_freq, early_stop_margin, has_linear),
            bucket=N, phase="predict")
    return carry[0]


def _forest_block(forest: TreeArrays, tree_class: jax.Array, b: int,
                  tree_block: int, T: int):
    """Trees [b, b+tree_block) of the stacked forest; only the TAIL block
    pads, with no-op trees (all-zero arrays: the bounded traversal lands on
    ``leaf_value[-1] == 0``, adding nothing — and pads sit strictly after
    every real tree, so early-stop margins are unaffected)."""
    hi = min(b + tree_block, T)
    pad = tree_block - (hi - b)

    def cut(a):
        blk = lax.slice_in_dim(a, b, hi)
        if pad:
            blk = jnp.concatenate(
                [blk, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
        return blk

    return (jax.tree_util.tree_map(cut, forest),
            cut(tree_class))


@functools.partial(jax.jit, static_argnames=("max_depth", "binned"))
def _predict_forest_leaf_block(x: jax.Array, forest: TreeArrays,
                               max_depth: int, binned: bool) -> jax.Array:
    def step(_, t):
        return None, _traverse_leaf_id(x, t, max_depth, binned)

    _, ys = lax.scan(step, None, forest)
    return ys


def predict_forest_leaf(x: jax.Array, forest: TreeArrays,
                        max_depth: int, binned: bool,
                        tree_block: Optional[int] = None,
                        blocks=None) -> jax.Array:
    """Leaf index per (tree, row) for a whole forest: [T, N] int32.

    Dispatched in the same bounded tree blocks as :func:`predict_forest`
    (refit / linear-tree replay / pred_leaf hit this path with full-size
    forests, where a single T-long scan kernel can fault a tunneled
    worker just like the score scan). ``blocks`` from
    :func:`build_forest_blocks` skips the per-call forest re-slice."""
    T = forest.leaf_value.shape[0]
    if tree_block is None:
        tree_block = int(os.environ.get("LAMBDAGAP_PREDICT_TREE_BLOCK", 64))
    if blocks is None:
        if tree_block <= 0 or T <= tree_block:
            return _predict_forest_leaf_block(x, forest, max_depth, binned)
        blocks = build_forest_blocks(
            forest, jnp.zeros(T, jnp.int32), tree_block)
    outs = []
    for blk, _, n_real in blocks:
        ys = _predict_forest_leaf_block(x, blk, max_depth, binned)
        outs.append(ys[:n_real])
    return jnp.concatenate(outs, axis=0)


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "predict._predict_forest_block", collective_free=True,
    notes="scan-engine block kernel; steady-state predict replays the "
          "one trace")
