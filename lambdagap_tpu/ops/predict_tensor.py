"""Tensorized forest traversal: all rows x all trees per depth step.

The sequential engine (:mod:`lambdagap_tpu.ops.predict`) scans trees one at
a time, each tree a per-row ``fori_loop`` of scalar node gathers — the
500-tree dimension is serialized instead of exploited as data parallelism,
which is exactly the anti-pattern the GPU GBDT literature fixes with
batched node-table traversal (GPU-acceleration for Large-scale Tree
Boosting, arXiv:1706.08359; XGBoost: Scalable GPU Accelerated Learning,
arXiv:1806.11248).

This engine traverses a ``[R, Tt]`` node-index carry — R rows x a tile of
Tt trees — with ONE depth-major ``fori_loop`` whose body does batched 2-D
gathers on the stacked SoA node tables (``TreeArrays`` with the leading T
axis flattened to ``T*M``), plus one ``take_along_axis`` per level for the
feature values. Tiles are bounded by the ``predict_tree_tile`` knob so the
working set never grows with the forest; the accumulator carries across
tiles exactly like the sequential engine's tree blocks.

Bit-exactness contract: after the (parallel) traversal computes every
tree's leaf value, the per-class accumulation runs as a ``lax.scan`` over
trees IN FOREST ORDER — the identical f32 addition order as the sequential
engine — so both engines return bit-identical scores (the parity suite in
``tests/test_predict_tensor.py`` asserts equality, not closeness). The
early-stop margin check replays the sequential semantics tree by tree on
the accumulation scan; the traversal itself still computes stopped rows
(a latency trade the parallel engine accepts for exactness).

Semantics (NaN/default-left routing, categorical bitsets, binned bin
compares, zero-missing) replicate ``ops.predict._traverse_leaf_id``
decision for decision; that per-tree path stays behind
``predict_engine=scan`` as the reference oracle.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .predict import (K_ZERO_THRESHOLD, MT_NAN, MT_ZERO, TreeArrays,
                      build_forest_blocks)


def default_tree_tile() -> int:
    """predict_tree_tile default (env override for benchmarking)."""
    return int(os.environ.get("LAMBDAGAP_PREDICT_TREE_TILE", 64))


def _traverse_tile(x: jax.Array, t: TreeArrays, max_depth: int,
                   binned: bool) -> jax.Array:
    """All rows through all trees of one tile -> final node carry [R, Tt]
    (negative entries are ``~leaf``; non-negative means the tree never
    reached a leaf — only the zero-padded no-op trees do that)."""
    R = x.shape[0]
    Tt, M = t.split_feature.shape
    W = t.cat_bitset_real.shape[-1]
    # flatten the stacked node tables once; every per-level gather is then
    # one flat [R*Tt] gather at index tree*M + node
    feat = t.split_feature.reshape(-1)
    left = t.left_child.reshape(-1)
    right = t.right_child.reshape(-1)
    missing_type = t.missing_type.reshape(-1)
    default_left = t.default_left.reshape(-1)
    is_cat = t.is_categorical.reshape(-1)
    if binned:
        thr_bin = t.threshold_bin.reshape(-1)
        default_bin = t.default_bin.reshape(-1)
        num_bin = t.num_bin.reshape(-1)
        cat_bits = t.cat_bitset.reshape(-1)
        cat_words = t.cat_bitset.shape[-1]
    else:
        thr = t.threshold.reshape(-1)
        cat_bits = t.cat_bitset_real.reshape(-1)
        cat_words = W
    base = (jnp.arange(Tt, dtype=jnp.int32) * M)[None, :]     # [1, Tt]

    def cat_go_left(cat, idx):
        """_cat_go_left over the [R, Tt] lattice (same clipping/bit math)."""
        nbits = cat_words * 32
        inb = (cat >= 0) & (cat < nbits)
        safe = jnp.clip(cat, 0, nbits - 1)
        word = idx * cat_words + safe // 32
        bit = (cat_bits[word] >> (safe % 32).astype(jnp.uint32)) \
            & jnp.uint32(1)
        return inb & (bit == jnp.uint32(1))

    def body(_, node):
        idx = base + jnp.maximum(node, 0)                     # [R, Tt]
        f = feat[idx]
        mt = missing_type[idx]
        if binned:
            b = jnp.take_along_axis(x, f, axis=1).astype(jnp.int32)
            missing = ((mt == MT_ZERO) & (b == default_bin[idx])) | \
                      ((mt == MT_NAN) & (b == num_bin[idx] - 1))
            go_num = jnp.where(missing, default_left[idx],
                               b <= thr_bin[idx])
            go_cat = cat_go_left(b, idx)
        else:
            v = jnp.take_along_axis(x, f, axis=1)
            nan = jnp.isnan(v)
            # NaN converted to 0 unless NaN-missing
            # (reference: tree.h NumericalDecision)
            v0 = jnp.where(nan & (mt != MT_NAN), 0.0, v)
            missing = ((mt == MT_NAN) & nan) | \
                      ((mt == MT_ZERO) & (jnp.abs(v0) <= K_ZERO_THRESHOLD))
            go_num = jnp.where(missing, default_left[idx], v0 <= thr[idx])
            cat = jnp.where(nan, -1, v).astype(jnp.int32)
            go_cat = cat_go_left(cat, idx)
        go = jnp.where(is_cat[idx], go_cat, go_num)
        nxt = jnp.where(go, left[idx], right[idx])
        return jnp.where(node < 0, node, nxt)

    return lax.fori_loop(0, max_depth, body,
                         jnp.zeros((R, Tt), jnp.int32))


def _tile_leaf_values(node: jax.Array, t: TreeArrays, x: jax.Array,
                      has_linear: bool) -> jax.Array:
    """Leaf-value gather for a traversed tile: [R, Tt] f32. No-op pad trees
    (node >= 0) contribute exactly 0.0, like the sequential engine's padded
    tail blocks. Under ``has_linear`` the gather becomes the shared
    per-leaf dot-product evaluation (ops/linear.py) over the flattened
    leaf tables — the same elementwise op sequence the scan engine runs,
    so the engines stay ``array_equal`` on linear forests."""
    Tt = t.split_feature.shape[0]
    L = t.leaf_value.shape[-1]
    done = node < 0
    leaf = jnp.where(done, ~node, 0)
    idx = (jnp.arange(Tt, dtype=jnp.int32) * L)[None, :] + leaf   # [R, Tt]
    if has_linear:
        from .linear import linear_leaf_values
        FL = t.leaf_feat.shape[-1]
        vals = linear_leaf_values(
            x, idx, t.leaf_value.reshape(-1), t.leaf_const.reshape(-1),
            t.leaf_feat.reshape(-1, FL), t.leaf_coeff.reshape(-1, FL))
    else:
        vals = t.leaf_value.reshape(-1)[idx]
    return jnp.where(done, vals, jnp.float32(0.0))


@functools.partial(jax.jit,
                   static_argnames=("num_class", "max_depth", "binned",
                                    "early_stop_freq", "has_linear"))
def _predict_tensor_tile(x: jax.Array, t: TreeArrays, tree_class: jax.Array,
                         carry, num_class: int, max_depth: int, binned: bool,
                         early_stop_freq: int = 0,
                         early_stop_margin: float = 0.0,
                         has_linear: bool = False):
    """One tile: parallel [R, Tt] traversal, then an in-forest-order
    accumulation scan threading the sequential engine's (out, stopped, i)
    carry — identical f32 addition order, identical early-stop points."""
    node = _traverse_tile(x, t, max_depth, binned)
    vals = _tile_leaf_values(node, t, x, has_linear)          # [R, Tt]
    if early_stop_freq <= 0:
        out, stopped, i = carry

        def step(o, vk):
            v, k = vk
            return o.at[k].add(v), None

        out, _ = lax.scan(step, out, (vals.T, tree_class))
        return out, stopped, i

    def margin_of(out):
        if num_class == 1:
            # reference binary margin is 2*|raw score|
            # (src/boosting/prediction_early_stop.cpp)
            return 2.0 * jnp.abs(out[0])
        top2 = lax.top_k(out.T, 2)[0]          # [N, 2]
        return top2[:, 0] - top2[:, 1]

    def step(c, vk):
        out, stopped, i = c
        v, k = vk
        out = out.at[k].add(jnp.where(stopped, 0.0, v))
        i = i + 1
        check = (i % early_stop_freq) == 0
        stopped = jnp.where(check, stopped | (margin_of(out)
                                              > early_stop_margin), stopped)
        return (out, stopped, i), None

    carry, _ = lax.scan(step, carry, (vals.T, tree_class))
    return carry


@functools.partial(jax.jit, static_argnames=("max_depth", "binned"))
def _leaf_tensor_tile(x: jax.Array, t: TreeArrays, max_depth: int,
                      binned: bool) -> jax.Array:
    """Leaf index per (tree, row) for one tile: [Tt, R] int32."""
    return (~_traverse_tile(x, t, max_depth, binned)).T


def build_tree_tiles(forest: TreeArrays, tree_class: jax.Array,
                     tree_tile: Optional[int] = None):
    """Pre-slice a stacked forest into ``predict_tree_tile``-sized tiles
    ONCE (same padded-tail layout as :func:`predict.build_forest_blocks`,
    so either engine can consume the result). Returns None when the forest
    fits one tile."""
    if tree_tile is None:
        tree_tile = default_tree_tile()
    return build_forest_blocks(forest, tree_class, tree_tile)


def predict_forest_tensor(x: jax.Array, forest: TreeArrays,
                          tree_class: jax.Array, num_class: int,
                          max_depth: int, binned: bool,
                          early_stop_freq: int = 0,
                          early_stop_margin: float = 0.0,
                          tree_tile: Optional[int] = None,
                          tiles=None, has_linear: bool = False) -> jax.Array:
    """Tensorized drop-in for :func:`ops.predict.predict_forest`.

    Same signature semantics: x is [N, D] raw floats (binned=False) or
    [N, F] binned; returns [num_class, N] float32, bit-identical to the
    sequential engine. ``tiles`` (from :func:`build_tree_tiles`) skips the
    per-call forest re-slice; ``tree_tile`` bounds the [R, Tt] working set
    per dispatch (default ``predict_tree_tile``). ``has_linear`` switches
    the leaf gather to the per-leaf dot-product payload (raw rows only)."""
    assert not (binned and has_linear), \
        "linear forests traverse raw rows; binned linear replay is host-side"
    N = x.shape[0]
    T = tree_class.shape[0]
    if tree_tile is None:
        tree_tile = default_tree_tile()
    init = (jnp.zeros((num_class, N), jnp.float32),
            jnp.zeros(N, dtype=bool), jnp.int32(0))
    from ..obs import costplane
    if tiles is None:
        if tree_tile <= 0 or T <= tree_tile:
            out, _, _ = costplane.observed_call(
                "predict.tensor", _predict_tensor_tile,
                (x, forest, tree_class, init, num_class, max_depth,
                 binned, early_stop_freq, early_stop_margin, has_linear),
                bucket=N, phase="predict")
            return out
        tiles = build_tree_tiles(forest, tree_class, tree_tile)
    carry = init
    for blk, tc, _ in tiles:
        carry = costplane.observed_call(
            "predict.tensor", _predict_tensor_tile,
            (x, blk, tc, carry, num_class, max_depth, binned,
             early_stop_freq, early_stop_margin, has_linear),
            bucket=N, phase="predict")
    return carry[0]


def predict_forest_leaf_tensor(x: jax.Array, forest: TreeArrays,
                               max_depth: int, binned: bool,
                               tree_tile: Optional[int] = None,
                               tiles=None) -> jax.Array:
    """Tensorized drop-in for :func:`ops.predict.predict_forest_leaf`:
    leaf index per (tree, row), [T, N] int32."""
    T = forest.leaf_value.shape[0]
    if tree_tile is None:
        tree_tile = default_tree_tile()
    if tiles is None:
        if tree_tile <= 0 or T <= tree_tile:
            return _leaf_tensor_tile(x, forest, max_depth, binned)
        tiles = build_tree_tiles(forest, jnp.zeros(T, jnp.int32), tree_tile)
    outs = []
    for blk, _, n_real in tiles:
        ys = _leaf_tensor_tile(x, blk, max_depth, binned)
        outs.append(ys[:n_real])
    return jnp.concatenate(outs, axis=0)


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "predict_tensor._predict_tensor_tile", collective_free=True,
    notes="tensorized predict tile; steady-state predict replays the "
          "one trace")
