"""Best-split search over histograms.

Vectorized TPU re-implementation of the reference's per-feature threshold scan
(reference: src/treelearner/feature_histogram.hpp:396-441 dispatch,
:828-1058 FindBestThresholdSequentially) and the split gain / leaf output math
(:711-830 ThresholdL1 / CalculateSplittedLeafOutput / GetLeafGain /
GetSplitGains). Instead of a sequential two-direction loop per feature, both
missing-direction scans are computed for every (feature, bin) at once with
cumulative sums, followed by one flat argmax — the same shape as the CUDA
best-split kernel (reference: src/treelearner/cuda/cuda_best_split_finder.cu:129)
but expressed as XLA ops.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

# missing-type codes (match data.dataset.feature_arrays)
MT_NONE, MT_ZERO, MT_NAN = 0, 1, 2


@dataclass(frozen=True)
class SplitParams:
    """Static hyperparameters entering gain math; hashable so jitted scans
    specialize on them (they are fixed for a whole training run)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    max_cat_threshold: int = 32
    max_cat_to_onehot: int = 4
    min_data_per_group: int = 100


class SplitResult(NamedTuple):
    """Device-resident best split for one leaf — the analog of ``SplitInfo``
    (reference: src/treelearner/split_info.hpp)."""
    gain: jax.Array            # f32, -inf when unsplittable
    feature: jax.Array         # i32 (index into used features)
    threshold: jax.Array       # i32 bin threshold (left: bin <= threshold)
    default_left: jax.Array    # bool
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    left_count: jax.Array      # f32 (exact, from count channel)
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    right_count: jax.Array
    left_output: jax.Array
    right_output: jax.Array
    is_categorical: jax.Array  # bool
    cat_bitset: jax.Array      # u32 [8] — bins going LEFT for categorical splits


def threshold_l1(s, l1):
    """(reference: feature_histogram.hpp:711 ThresholdL1)"""
    reg = jnp.maximum(0.0, jnp.abs(s) - l1)
    return jnp.sign(s) * reg


def calculate_leaf_output(sum_g, sum_h, p: SplitParams, num_data=None,
                          parent_output=0.0, l2_extra=0.0):
    """(reference: feature_histogram.hpp:716-737 CalculateSplittedLeafOutput)"""
    l2 = p.lambda_l2 + l2_extra
    if p.lambda_l1 > 0:
        ret = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + l2)
    else:
        ret = -sum_g / (sum_h + l2)
    if p.max_delta_step > 0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > K_EPSILON and num_data is not None:
        n_over_s = num_data / p.path_smooth
        ret = ret * n_over_s / (n_over_s + 1.0) + parent_output / (n_over_s + 1.0)
    return ret


def leaf_gain_given_output(sum_g, sum_h, output, p: SplitParams, l2_extra=0.0):
    """(reference: feature_histogram.hpp:818-830 GetLeafGainGivenOutput)"""
    l2 = p.lambda_l2 + l2_extra
    sg = threshold_l1(sum_g, p.lambda_l1) if p.lambda_l1 > 0 else sum_g
    return -(2.0 * sg * output + (sum_h + l2) * output * output)


def leaf_gain(sum_g, sum_h, p: SplitParams, num_data=None, parent_output=0.0,
              l2_extra=0.0):
    """(reference: feature_histogram.hpp:800-816 GetLeafGain)"""
    if p.max_delta_step <= 0 and p.path_smooth <= K_EPSILON and l2_extra == 0.0:
        sg = threshold_l1(sum_g, p.lambda_l1) if p.lambda_l1 > 0 else sum_g
        return (sg * sg) / (sum_h + p.lambda_l2)
    out = calculate_leaf_output(sum_g, sum_h, p, num_data, parent_output, l2_extra)
    return leaf_gain_given_output(sum_g, sum_h, out, p, l2_extra)


def split_gains(lg, lh, rg, rh, p: SplitParams, l_cnt=None, r_cnt=None,
                parent_output=0.0, l2_extra=0.0):
    """(reference: feature_histogram.hpp:759-797 GetSplitGains, no monotone)"""
    return (leaf_gain(lg, lh, p, l_cnt, parent_output, l2_extra)
            + leaf_gain(rg, rh, p, r_cnt, parent_output, l2_extra))


def _norm_constraints(constraints):
    """Normalize monotone constraints to
    ``(monotone[F], min_l, max_l, min_r, max_r)``.

    ``min_l``/``max_l`` bound the LEFT child at threshold t, ``min_r``/
    ``max_r`` the RIGHT child; each broadcasts against ``[F, B]`` — scalars
    for the basic/intermediate methods (one bound per leaf), dense
    per-threshold arrays for the advanced method (prefix/suffix cumulative
    extrema of the per-bin constraints — the vectorized form of the
    reference's CumulativeFeatureConstraint,
    src/treelearner/monotone_constraints.hpp:146-264)."""
    if len(constraints) == 3:
        monotone, min_c, max_c = constraints
        return monotone, min_c, max_c, min_c, max_c
    return constraints


# ---------------------------------------------------------------------------
# numerical scan
# ---------------------------------------------------------------------------

def _numerical_best(hist, parent_g, parent_h, parent_c, parent_output,
                    num_bins, default_bins, missing_types, feature_mask,
                    p: SplitParams, constraints=None, rand_thresholds=None):
    """Both-direction scan for all features at once.

    ``constraints``: optional (monotone[F] in {-1,0,+1}, min_c, max_c) for
    monotone-constrained leaves (None = unconstrained fast path).
    ``rand_thresholds``: optional [F] i32 — extra_trees mode, each feature
    considers ONLY its random threshold (reference:
    feature_histogram.hpp:192-205 USE_RAND / rand_threshold).
    Returns per-feature best: (gain[F], threshold[F], default_left[F],
    left_g[F], left_h[F], left_c[F]).
    """
    F, B, _ = hist.shape
    g = hist[:, :, 0].astype(jnp.float32)
    h = hist[:, :, 1].astype(jnp.float32)
    c = hist[:, :, 2].astype(jnp.float32)
    bin_idx = jnp.arange(B, dtype=jnp.int32)[None, :]          # [1, B]
    nb = num_bins[:, None]                                     # [F, 1]
    is_zero_missing = (missing_types == MT_ZERO)[:, None]
    is_nan_missing = (missing_types == MT_NAN)[:, None]
    is_default = bin_idx == default_bins[:, None]
    is_nan_bin = bin_idx == (nb - 1)

    # Forward scan: missing -> right (default_left=False). The missing bin's
    # content is excluded from the left accumulation so it lands on the right
    # via right = parent - left (reference: SKIP_DEFAULT_BIN / NA_AS_MISSING
    # template args of FindBestThresholdSequentially). The reverse scan
    # (missing -> left) uses the same exclusion, so all six prefix sums ride
    # ONE cumsum over a packed [2, F, B, 3] tensor (launch-count matters:
    # this runs per split step inside the fused tree program).
    excl_fwd = (is_zero_missing & is_default) | (is_nan_missing & is_nan_bin)
    ghc = jnp.stack([jnp.where(excl_fwd, 0.0, g),
                     jnp.where(excl_fwd, 0.0, h),
                     jnp.where(excl_fwd, 0.0, c)], axis=-1)    # [F, B, 3]
    both = jnp.stack([ghc, ghc[:, ::-1]], axis=0)              # [2, F, B, 3]
    cs = jnp.cumsum(both, axis=2)
    lg_f, lh_f, lc_f = cs[0, ..., 0], cs[0, ..., 1], cs[0, ..., 2]
    # right sums for threshold t = sum of bins > t
    rev = cs[1][:, ::-1]                                       # inclusive
    rg_r = rev[..., 0] - ghc[..., 0]
    rh_r = rev[..., 1] - ghc[..., 1]
    rc_r = rev[..., 2] - ghc[..., 2]

    def eval_dir(left_g, left_h, left_c):
        right_g = parent_g - left_g
        right_h = parent_h - left_h
        right_c = parent_c - left_c
        ok = ((left_c >= p.min_data_in_leaf) & (right_c >= p.min_data_in_leaf)
              & (left_h >= p.min_sum_hessian_in_leaf)
              & (right_h >= p.min_sum_hessian_in_leaf))
        if constraints is None:
            gain = split_gains(left_g, left_h, right_g, right_h, p,
                               left_c, right_c, parent_output)
            return jnp.where(ok, gain, K_MIN_SCORE)
        # monotone path: per-candidate child outputs clamped to the leaf's
        # bounds — scalar for basic/intermediate, per-threshold [F, B]
        # arrays for advanced — with a direction veto on the constrained
        # feature (reference:
        # src/treelearner/monotone_constraints.hpp:329 BasicLeafConstraints
        # + feature_histogram.hpp monotone-templated scan; per-threshold
        # bounds: CumulativeFeatureConstraint Get{Left,Right}{Min,Max})
        monotone, min_l, max_l, min_r, max_r = _norm_constraints(constraints)
        lout = jnp.clip(calculate_leaf_output(left_g, left_h, p, left_c,
                                              parent_output), min_l, max_l)
        rout = jnp.clip(calculate_leaf_output(right_g, right_h, p, right_c,
                                              parent_output), min_r, max_r)
        m = monotone[:, None]
        veto = ((m > 0) & (lout > rout)) | ((m < 0) & (lout < rout))
        gain = (leaf_gain_given_output(left_g, left_h, lout, p)
                + leaf_gain_given_output(right_g, right_h, rout, p))
        return jnp.where(ok & ~veto, gain, K_MIN_SCORE)

    gain_f = eval_dir(lg_f, lh_f, lc_f)
    lg_r = parent_g - rg_r
    lh_r = parent_h - rh_r
    lc_r = parent_c - rc_r
    gain_r = eval_dir(lg_r, lh_r, lc_r)

    # valid threshold candidates: t in [0, num_bin-2]; Zero-missing skips the
    # default bin as a candidate (it would make train/predict placement of
    # zeros inconsistent); the reverse scan with NaN-missing cannot place the
    # NaN bin alone on the right (it must stay left), so t = num_bin-2 is
    # excluded there (reference: reverse loop starts at num_bin-2-NA_AS_MISSING).
    cand = (bin_idx < nb - 1) & (feature_mask[:, None])
    if rand_thresholds is not None:
        cand = cand & (bin_idx == rand_thresholds[:, None])
    cand_f = cand & ~(is_zero_missing & is_default)
    cand_r = cand_f & ~(is_nan_missing & (bin_idx == nb - 2))
    gain_f = jnp.where(cand_f, gain_f, K_MIN_SCORE)
    gain_r = jnp.where(cand_r, gain_r, K_MIN_SCORE)

    # pick direction per (f, b): reverse wins ties (matches reference running
    # REVERSE first and requiring strict improvement)
    use_fwd = gain_f > gain_r
    gain = jnp.maximum(gain_f, gain_r)
    left_g = jnp.where(use_fwd, lg_f, lg_r)
    left_h = jnp.where(use_fwd, lh_f, lh_r)
    left_c = jnp.where(use_fwd, lc_f, lc_r)
    default_left = ~use_fwd

    best_t = jnp.argmax(gain, axis=1).astype(jnp.int32)        # [F]
    take = lambda a: jnp.take_along_axis(a, best_t[:, None], axis=1)[:, 0]
    return (take(gain), best_t, take(default_left),
            take(left_g), take(left_h), take(left_c))


# ---------------------------------------------------------------------------
# categorical scan (one-hot + sorted-subset)
# ---------------------------------------------------------------------------

def _categorical_best(hist, parent_g, parent_h, parent_c, parent_output,
                      num_bins, feature_mask, p: SplitParams,
                      constraints=None, rand_thresholds=None):
    """Categorical split search
    (reference: feature_histogram.hpp FindBestThresholdCategoricalInner):
    one-vs-rest for small cardinality, otherwise scan prefixes of bins sorted
    by grad/(hess+cat_smooth), both directions, capped at max_cat_threshold.

    Returns per-feature best plus a bitset of bins going left.
    """
    F, B, _ = hist.shape
    g = hist[:, :, 0].astype(jnp.float32)
    h = hist[:, :, 1].astype(jnp.float32)
    c = hist[:, :, 2].astype(jnp.float32)
    bin_idx = jnp.arange(B, dtype=jnp.int32)[None, :]
    nb = num_bins[:, None]
    valid_bin = (bin_idx < nb) & (c > 0)

    l2 = p.lambda_l2 + p.cat_l2

    def gains_for(left_g, left_h, left_c):
        right_g = parent_g - left_g
        right_h = parent_h - left_h
        right_c = parent_c - left_c
        ok = ((left_c >= p.min_data_in_leaf) & (right_c >= p.min_data_in_leaf)
              & (left_h >= p.min_sum_hessian_in_leaf)
              & (right_h >= p.min_sum_hessian_in_leaf))
        if constraints is None:
            gain = split_gains(left_g, left_h, right_g, right_h, p,
                               left_c, right_c, parent_output,
                               l2_extra=p.cat_l2)
            return jnp.where(ok, gain, K_MIN_SCORE)
        # no ordering veto for categorical splits, but child outputs still
        # clamp to the leaf's inherited monotone bounds; under the advanced
        # method a categorical split scatters bins to both sides, so the
        # FULL-range bound applies (last prefix-cumulated column)
        _, min_l, max_l, _, _ = _norm_constraints(constraints)
        min_c = min_l[:, -1:] if getattr(min_l, "ndim", 0) == 2 else min_l
        max_c = max_l[:, -1:] if getattr(max_l, "ndim", 0) == 2 else max_l
        lout = jnp.clip(calculate_leaf_output(
            left_g, left_h, p, left_c, parent_output, l2_extra=p.cat_l2),
            min_c, max_c)
        rout = jnp.clip(calculate_leaf_output(
            right_g, right_h, p, right_c, parent_output, l2_extra=p.cat_l2),
            min_c, max_c)
        gain = (leaf_gain_given_output(left_g, left_h, lout, p,
                                       l2_extra=p.cat_l2)
                + leaf_gain_given_output(right_g, right_h, rout, p,
                                         l2_extra=p.cat_l2))
        return jnp.where(ok, gain, K_MIN_SCORE)

    # extra_trees: one random candidate position per feature (reference:
    # the USE_RAND checks inside FindBestThresholdCategoricalInner,
    # feature_histogram.hpp:1152,1269); here the same random draw indexes
    # both the one-hot bin and the sorted-order position
    rand_pos = None
    if rand_thresholds is not None:
        rand_pos = (rand_thresholds[:, None] % jnp.maximum(nb - 1, 1))

    # --- one-vs-rest: category k alone goes left --------------------------
    onehot_cand = valid_bin & feature_mask[:, None]
    if rand_pos is not None:
        onehot_cand = onehot_cand & (bin_idx == rand_pos)
    onehot_gain = jnp.where(onehot_cand, gains_for(g, h, c), K_MIN_SCORE)

    # --- sorted-subset: order bins by g/(h + cat_smooth); scan BOTH
    # directions (prefixes and suffixes of the order), mirroring the
    # reference's dir = +1/-1 loop so subsets taken from the high end of
    # the order remain candidates under the max_cat_threshold cap
    # (reference: FindBestThresholdCategoricalInner) ----------------------
    score = g / (h + p.cat_smooth)
    score = jnp.where(valid_bin, score, jnp.inf)
    order = jnp.argsort(score, axis=1)                          # [F, B]
    g_s = jnp.take_along_axis(g, order, axis=1)
    h_s = jnp.take_along_axis(h, order, axis=1)
    c_s = jnp.take_along_axis(c, order, axis=1)
    v_s = jnp.take_along_axis(valid_bin, order, axis=1)
    g_s = jnp.where(v_s, g_s, 0.0)
    h_s = jnp.where(v_s, h_s, 0.0)
    c_s = jnp.where(v_s, c_s, 0.0)
    csum_g = jnp.cumsum(g_s, axis=1)
    csum_h = jnp.cumsum(h_s, axis=1)
    csum_c = jnp.cumsum(c_s, axis=1)
    prefix_len = jnp.cumsum(v_s.astype(jnp.int32), axis=1)
    cap_ok = prefix_len <= p.max_cat_threshold
    sorted_cand = cap_ok & v_s & feature_mask[:, None]
    if rand_pos is not None:
        sorted_cand = sorted_cand & (bin_idx == rand_pos)
    sorted_gain = jnp.where(sorted_cand,
                            gains_for(csum_g, csum_h, csum_c), K_MIN_SCORE)

    # suffix direction: left set = bins AFTER position t in the order
    # (computed from totals minus the inclusive prefix at t)
    tot_g = csum_g[:, -1:]
    tot_h = csum_h[:, -1:]
    tot_c = csum_c[:, -1:]
    sfx_g = tot_g - csum_g
    sfx_h = tot_h - csum_h
    sfx_c = tot_c - csum_c
    n_valid = prefix_len[:, -1:]
    sfx_len = n_valid - prefix_len
    sfx_cap = (sfx_len <= p.max_cat_threshold) & (sfx_len > 0)
    sfx_cand = sfx_cap & v_s & feature_mask[:, None]
    if rand_pos is not None:
        sfx_cand = sfx_cand & (bin_idx == rand_pos)
    suffix_gain = jnp.where(sfx_cand,
                            gains_for(sfx_g, sfx_h, sfx_c), K_MIN_SCORE)

    # choose between strategies per feature
    best_onehot = jnp.max(onehot_gain, axis=1)
    t_onehot = jnp.argmax(onehot_gain, axis=1).astype(jnp.int32)
    best_pref = jnp.max(sorted_gain, axis=1)
    t_pref = jnp.argmax(sorted_gain, axis=1).astype(jnp.int32)
    best_sfx = jnp.max(suffix_gain, axis=1)
    t_sfx = jnp.argmax(suffix_gain, axis=1).astype(jnp.int32)

    use_sfx = best_sfx > best_pref
    best_sorted = jnp.maximum(best_pref, best_sfx)
    t_sorted = jnp.where(use_sfx, t_sfx, t_pref)

    small = num_bins <= p.max_cat_to_onehot
    use_onehot = small | (best_onehot >= best_sorted)
    gain = jnp.where(use_onehot, best_onehot, best_sorted)

    # bitsets of bins going left (u32 words)
    words = jnp.arange(8, dtype=jnp.uint32)[None, :]
    def onehot_bits(t):
        w = (t // 32).astype(jnp.uint32)
        bit = jnp.left_shift(jnp.uint32(1), (t % 32).astype(jnp.uint32))
        return jnp.where(words == w[:, None], bit[:, None], jnp.uint32(0))
    pos = jnp.cumsum(jnp.ones_like(order), axis=1) - 1
    in_pref = pos <= t_sorted[:, None]
    in_sfx = pos > t_sorted[:, None]
    member = _scatter_rows(order,
                           jnp.where(use_sfx[:, None], in_sfx, in_pref) & v_s)
    sorted_bits = _bins_to_bitset(member)
    bits = jnp.where(use_onehot[:, None], onehot_bits(t_onehot), sorted_bits)

    take_at = lambda csA, t: jnp.take_along_axis(csA, t[:, None], axis=1)[:, 0]
    sort_g = jnp.where(use_sfx, take_at(sfx_g, t_sorted),
                       take_at(csum_g, t_sorted))
    sort_h = jnp.where(use_sfx, take_at(sfx_h, t_sorted),
                       take_at(csum_h, t_sorted))
    sort_c = jnp.where(use_sfx, take_at(sfx_c, t_sorted),
                       take_at(csum_c, t_sorted))
    left_g = jnp.where(use_onehot, take_at(g, t_onehot), sort_g)
    left_h = jnp.where(use_onehot, take_at(h, t_onehot), sort_h)
    left_c = jnp.where(use_onehot, take_at(c, t_onehot), sort_c)
    threshold = jnp.where(use_onehot, t_onehot, t_sorted)
    return gain, threshold, left_g, left_h, left_c, bits


def _scatter_rows(order, values):
    """out[f, order[f, b]] = values[f, b] via the inverse-permutation gather."""
    inv = jnp.argsort(order, axis=1)
    return jnp.take_along_axis(values, inv, axis=1)


def _bins_to_bitset(member: jax.Array) -> jax.Array:
    """bool [F, B] -> u32 [F, 8] bitset (B <= 256)."""
    F, B = member.shape
    pad = (-B) % 256
    m = jnp.pad(member, ((0, 0), (0, pad))).reshape(F, 8, 32)
    bits = jnp.left_shift(jnp.uint32(1), jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(jnp.where(m, bits, jnp.uint32(0)), axis=2, dtype=jnp.uint32)


# ---------------------------------------------------------------------------
# combined entry
# ---------------------------------------------------------------------------

def per_feature_best(hist: jax.Array, parent_g, parent_h, parent_c,
                     parent_output, num_bins, default_bins, missing_types,
                     is_categorical, feature_mask, params: SplitParams,
                     has_categorical: bool = False, constraints=None,
                     gain_penalty=None, rand_thresholds=None):
    """Per-feature best split candidates (the per-feature stage of
    ``FindBestSplitsFromHistograms``), used directly by the voting-parallel
    learner's local top-k vote (reference:
    src/treelearner/voting_parallel_tree_learner.cpp:151-175)."""
    p = params
    F, B, _ = hist.shape
    num_gain, num_t, num_dl, num_lg, num_lh, num_lc = _numerical_best(
        hist, parent_g, parent_h, parent_c, parent_output,
        num_bins, default_bins, missing_types,
        feature_mask & ~is_categorical, p, constraints, rand_thresholds)

    if has_categorical:
        cat_gain, cat_t, cat_lg, cat_lh, cat_lc, cat_bits = _categorical_best(
            hist, parent_g, parent_h, parent_c, parent_output,
            num_bins, feature_mask & is_categorical, p, constraints,
            rand_thresholds)
    else:
        cat_gain = jnp.full((F,), K_MIN_SCORE, jnp.float32)
        cat_t = jnp.zeros((F,), jnp.int32)
        cat_lg = cat_lh = cat_lc = jnp.zeros((F,), jnp.float32)
        cat_bits = jnp.zeros((F, 8), jnp.uint32)

    use_cat = is_categorical
    gain = jnp.where(use_cat, cat_gain, num_gain)
    if gain_penalty is not None:
        # CEGB: per-feature gain penalty (reference:
        # src/treelearner/cost_effective_gradient_boosting.hpp:23 DetlaGain)
        gain = jnp.where(jnp.isfinite(gain), gain - gain_penalty, gain)
    thr = jnp.where(use_cat, cat_t, num_t)
    dl = jnp.where(use_cat, False, num_dl)
    lg = jnp.where(use_cat, cat_lg, num_lg)
    lh = jnp.where(use_cat, cat_lh, num_lh)
    lc = jnp.where(use_cat, cat_lc, num_lc)
    return gain, thr, dl, lg, lh, lc, cat_bits


def monotone_split_penalty(depth, penalization: float):
    """Gain multiplier for splits on monotone-constrained features at a
    given leaf depth (reference: monotone_constraints.hpp:357
    ComputeMonotoneSplitGainPenalty): ~0 for the first
    floor(penalization) levels, then a decaying penalty."""
    d = jnp.asarray(depth, jnp.float32)
    p = float(penalization)
    pen = jnp.where(p <= 1.0,
                    1.0 - p / jnp.exp2(d) + K_EPSILON,
                    1.0 - jnp.exp2(p - 1.0 - d) + K_EPSILON)
    return jnp.where(p >= d + 1.0, K_EPSILON, pen)


def gather_threshold_split(hist_f, parent_g, parent_h, parent_c,
                           parent_output, feature, threshold, num_bin,
                           default_bin, missing_type, is_cat,
                           params: SplitParams, bounds=None) -> SplitResult:
    """Split info at a FIXED (feature, threshold) — the forced-splits path
    (reference: src/treelearner/feature_histogram.hpp:474-609
    GatherInfoForThresholdNumerical/Categorical).

    Numerical semantics match the reference gather: right = bins in
    (threshold, num_bin) excluding the missing bin's content, so missing
    values always ride LEFT and ``default_left`` is True. Categorical is the
    one-hot form: bin == threshold goes left, ``default_left`` False. The
    gain is shifted by the parent gain + min_gain_to_split and set to
    ``kMinScore`` when the forced split is worse than not splitting (the
    caller aborts forcing then, like ForceSplits'
    ``abort_last_forced_split``).
    """
    p = params
    g = hist_f[:, 0].astype(jnp.float32)
    h = hist_f[:, 1].astype(jnp.float32)
    c = hist_f[:, 2].astype(jnp.float32)
    B = hist_f.shape[0]
    bin_idx = jnp.arange(B, dtype=jnp.int32)
    in_range = bin_idx < num_bin
    excl = (((missing_type == MT_ZERO) & (bin_idx == default_bin))
            | ((missing_type == MT_NAN) & (bin_idx == num_bin - 1)))
    right_mask = (bin_idx > threshold) & in_range & ~excl
    rg = jnp.sum(jnp.where(right_mask, g, 0.0))
    rh = jnp.sum(jnp.where(right_mask, h, 0.0))
    rc = jnp.sum(jnp.where(right_mask, c, 0.0))
    lg_num, lh_num, lc_num = parent_g - rg, parent_h - rh, parent_c - rc
    sel = (bin_idx == threshold) & in_range
    lg_cat = jnp.sum(jnp.where(sel, g, 0.0))
    lh_cat = jnp.sum(jnp.where(sel, h, 0.0))
    lc_cat = jnp.sum(jnp.where(sel, c, 0.0))
    lg = jnp.where(is_cat, lg_cat, lg_num)
    lh = jnp.where(is_cat, lh_cat, lh_num)
    lc = jnp.where(is_cat, lc_cat, lc_num)
    rg2, rh2, rc2 = parent_g - lg, parent_h - lh, parent_c - lc

    gain_num = split_gains(lg, lh, rg2, rh2, p, lc, rc2, parent_output)
    gain_cat = split_gains(lg, lh, rg2, rh2, p, lc, rc2, parent_output,
                           l2_extra=p.cat_l2)
    gain_raw = jnp.where(is_cat, gain_cat, gain_num)
    shift = leaf_gain(parent_g, parent_h, p, parent_c, parent_output) \
        + p.min_gain_to_split
    # a split that leaves either side without hessian mass is degenerate
    usable = (lh > 0) & (rh2 > 0) & (lc > 0) & (rc2 > 0)
    splittable = usable & jnp.isfinite(gain_raw) & (gain_raw > shift)

    lout_n = calculate_leaf_output(lg, lh, p, lc, parent_output)
    rout_n = calculate_leaf_output(rg2, rh2, p, rc2, parent_output)
    lout_c = calculate_leaf_output(lg, lh, p, lc, parent_output,
                                   l2_extra=p.cat_l2)
    rout_c = calculate_leaf_output(rg2, rh2, p, rc2, parent_output,
                                   l2_extra=p.cat_l2)
    lout = jnp.where(is_cat, lout_c, lout_n)
    rout = jnp.where(is_cat, rout_c, rout_n)
    if bounds is not None:
        min_c, max_c = bounds
        lout = jnp.clip(lout, min_c, max_c)
        rout = jnp.clip(rout, min_c, max_c)

    thr32 = threshold.astype(jnp.uint32) if hasattr(threshold, "astype") \
        else jnp.uint32(threshold)
    words = jnp.arange(8, dtype=jnp.uint32)
    cat_bits = jnp.where(words == thr32 // 32,
                         jnp.left_shift(jnp.uint32(1), thr32 % 32),
                         jnp.uint32(0))
    return SplitResult(
        gain=jnp.where(splittable, gain_raw - shift, K_MIN_SCORE),
        feature=jnp.int32(feature),
        threshold=jnp.int32(threshold),
        default_left=~is_cat,
        left_sum_g=lg, left_sum_h=lh, left_count=lc,
        right_sum_g=rg2, right_sum_h=rh2, right_count=rc2,
        left_output=lout, right_output=rout,
        is_categorical=jnp.asarray(is_cat),
        cat_bitset=jnp.where(jnp.asarray(is_cat), cat_bits, jnp.uint32(0)),
    )


@functools.partial(jax.jit, static_argnames=("params", "has_categorical"))
def find_best_split(hist: jax.Array, parent_g: jax.Array, parent_h: jax.Array,
                    parent_c: jax.Array, parent_output: jax.Array,
                    num_bins: jax.Array, default_bins: jax.Array,
                    missing_types: jax.Array, is_categorical: jax.Array,
                    feature_mask: jax.Array, params: SplitParams,
                    has_categorical: bool = False,
                    constraints=None, gain_penalty=None,
                    rand_thresholds=None, gain_contri=None) -> SplitResult:
    """Best split for one leaf over all features.

    The analog of ``FindBestSplitsFromHistograms`` + per-leaf argmax
    (reference: src/treelearner/serial_tree_learner.cpp:477+, :225).

    ``gain_contri``: optional [F] multiplier on the post-shift gain
    (feature_contri — reference: feature_histogram.hpp:174 ``output->gain
    *= meta_->penalty``).
    """
    p = params
    use_cat = is_categorical
    gain, thr, dl, lg, lh, lc, cat_bits = per_feature_best(
        hist, parent_g, parent_h, parent_c, parent_output, num_bins,
        default_bins, missing_types, is_categorical, feature_mask, params,
        has_categorical, constraints, gain_penalty, rand_thresholds)

    # parent gain shift (reference: BeforeNumerical gain_shift + min_gain_to_split)
    parent_gain = leaf_gain(parent_g, parent_h, p, parent_c, parent_output)
    shift = parent_gain + p.min_gain_to_split

    if gain_contri is not None:
        gain = jnp.where(jnp.isfinite(gain),
                         (gain - shift) * gain_contri + shift, gain)
    best_f = jnp.argmax(gain, axis=0).astype(jnp.int32)
    best_gain_raw = gain[best_f]
    split_gain = best_gain_raw - shift

    left_g = lg[best_f]
    left_h = lh[best_f]
    left_c = lc[best_f]
    right_g = parent_g - left_g
    right_h = parent_h - left_h
    right_c = parent_c - left_c
    num_data = parent_c
    left_out = calculate_leaf_output(left_g, left_h, p, left_c, parent_output)
    right_out = calculate_leaf_output(right_g, right_h, p, right_c, parent_output)
    if constraints is not None:
        _, min_l, max_l, min_r, max_r = _norm_constraints(constraints)
        if getattr(min_l, "ndim", 0) == 2:
            # advanced: bound at the CHOSEN (feature, threshold); a
            # categorical winner uses the full-range bound (last prefix col)
            bt = thr[best_f]
            cat_w = use_cat[best_f]
            lmin = jnp.where(cat_w, min_l[best_f, -1], min_l[best_f, bt])
            lmax = jnp.where(cat_w, max_l[best_f, -1], max_l[best_f, bt])
            rmin = jnp.where(cat_w, min_l[best_f, -1], min_r[best_f, bt])
            rmax = jnp.where(cat_w, max_l[best_f, -1], max_r[best_f, bt])
            left_out = jnp.clip(left_out, lmin, lmax)
            right_out = jnp.clip(right_out, rmin, rmax)
        else:
            left_out = jnp.clip(left_out, min_l, max_l)
            right_out = jnp.clip(right_out, min_r, max_r)

    splittable = jnp.isfinite(best_gain_raw) & (split_gain > 0.0)
    return SplitResult(
        gain=jnp.where(splittable, split_gain, K_MIN_SCORE),
        feature=best_f,
        threshold=thr[best_f],
        default_left=dl[best_f],
        left_sum_g=left_g, left_sum_h=left_h, left_count=left_c,
        right_sum_g=right_g, right_sum_h=right_h, right_count=right_c,
        left_output=left_out, right_output=right_out,
        is_categorical=use_cat[best_f],
        cat_bitset=cat_bits[best_f],
    )


# graftir IR contract
from ..analysis.ir.contracts import register_program

register_program(
    "split.find_best_split", collective_free=True,
    notes="histogram shapes are (F, bins)-fixed, so exactly one trace")
