from .data_parallel import DataParallelTreeLearner
from .feature_parallel import FeatureParallelTreeLearner
from .mesh import DATA_AXIS, make_mesh
from .voting_parallel import VotingParallelTreeLearner

__all__ = ["DataParallelTreeLearner", "FeatureParallelTreeLearner",
           "VotingParallelTreeLearner", "make_mesh", "DATA_AXIS"]
