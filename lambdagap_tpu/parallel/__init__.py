from .data_parallel import DataParallelTreeLearner
from .feature_parallel import FeatureParallelTreeLearner
from .fused_parallel import FusedDataParallelTreeLearner
from .mesh import DATA_AXIS, make_mesh
from .voting_parallel import VotingParallelTreeLearner

__all__ = ["DataParallelTreeLearner", "FeatureParallelTreeLearner",
           "FusedDataParallelTreeLearner", "VotingParallelTreeLearner",
           "make_mesh", "DATA_AXIS"]
