from .data_parallel import DataParallelTreeLearner
from .feature_parallel import FeatureParallelTreeLearner
from .fused_parallel import (Fused2DTreeLearner,
                             FusedDataParallelTreeLearner)
from .mesh import make_mesh
from .sharding import DATA_AXIS, FEATURE_AXIS, MESH_AXES, RULES, spec, specs
from .voting_parallel import VotingParallelTreeLearner

__all__ = ["DataParallelTreeLearner", "FeatureParallelTreeLearner",
           "Fused2DTreeLearner",
           "FusedDataParallelTreeLearner", "VotingParallelTreeLearner",
           "make_mesh", "DATA_AXIS", "FEATURE_AXIS", "MESH_AXES", "RULES",
           "spec", "specs"]
