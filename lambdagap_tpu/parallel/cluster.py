"""Single-call cluster training — the Dask-module analog.

(reference: python-package/lightgbm/dask.py — ``_train`` :375-520 builds the
machine list, finds open ports, ships one data part to every worker and
drives per-worker distributed training automatically; the user just says
"here is a cluster, train on it".)

TPU shape: JAX multi-process is coordinator-based, so the launcher picks a
free coordinator port, row-partitions the input into per-worker files
(query-boundary-aligned when ``group`` is given), and spawns one process
per worker through the CLI's ``pre_partition=true`` flow — which joins the
distributed runtime BEFORE the package import touches the backend, loads
its own part, syncs bin mappers from allgathered samples, and trains over
the global device mesh with one histogram psum per split. Rank 0's model
(byte-identical to every other rank's) is returned as a Booster.

For multi-HOST clusters the same worker command runs on each host with
``machines=<coordinator_ip>:<port> num_machines=K machine_rank=r`` — this
launcher automates the single-host multi-process case and documents the
multi-host invocation it generates (``verbose_command``).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from ..utils import log


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _params_to_cli(params: Dict[str, Any]) -> List[str]:
    toks = []
    for k, v in params.items():
        if isinstance(v, (list, tuple)):
            v = ",".join(str(x) for x in v)
        elif isinstance(v, bool):
            v = "true" if v else "false"
        toks.append(f"{k}={v}")
    return toks


def _partition_bounds(n: int, k: int,
                      group: Optional[np.ndarray]) -> List[int]:
    """Row bounds of k contiguous parts; query-aligned when group sizes are
    given (a query must not straddle ranks — the reference's dask module
    likewise keeps each part's groups intact)."""
    if group is None:
        # floor-balanced: never an empty part for n >= k
        return [i * n // k for i in range(k + 1)]
    qb = np.concatenate([[0], np.cumsum(np.asarray(group, np.int64))])
    if qb[-1] != n:
        log.fatal("group sizes sum to %d but data has %d rows", qb[-1], n)
    targets = [round(i * n / k) for i in range(k + 1)]
    bounds = [0]
    for t in targets[1:-1]:
        j = int(np.searchsorted(qb, t, side="left"))
        bounds.append(int(qb[min(j, len(qb) - 1)]))
    bounds.append(n)
    return bounds


def train_cluster(params: Dict[str, Any], data, label=None, *,
                  num_workers: int = 2,
                  weight=None, group=None,
                  num_boost_round: Optional[int] = None,
                  workdir: Optional[str] = None,
                  timeout: float = 1800.0,
                  worker_env: Optional[Dict[str, str]] = None,
                  keep_files: bool = False):
    """Train one model across ``num_workers`` local processes with a single
    call (reference behavior: lightgbm.dask train()/DaskLGBM*.fit()).

    ``data`` is either a (rows, features) matrix — partitioned and written
    per-worker here — or a list of ``num_workers`` pre-partitioned file
    paths (the multi-host layout: every host already holds its own shard).
    Returns a :class:`lambdagap_tpu.Booster` built from rank 0's model
    (all ranks build byte-identical models).
    """
    from ..basic import Booster

    if num_workers < 2:
        log.fatal("train_cluster needs num_workers >= 2 (use lgb.train "
                  "for single-process training)")
    tmp = workdir or tempfile.mkdtemp(prefix="lambdagap_cluster_")
    os.makedirs(tmp, exist_ok=True)

    if isinstance(data, (list, tuple)) and data and isinstance(
            data[0], (str, os.PathLike)):
        if len(data) != num_workers:
            log.fatal("got %d part files for %d workers", len(data),
                      num_workers)
        if label is not None or weight is not None or group is not None:
            log.fatal("label/weight/group must live in the part files (or "
                      "their sidecars) when data is a list of paths")
        part_files = [str(p) for p in data]
    else:
        X = np.asarray(data, dtype=np.float64)
        if label is None:
            log.fatal("label is required when data is a matrix")
        y = np.asarray(label, dtype=np.float64).reshape(-1)
        bounds = _partition_bounds(len(X), num_workers, group)
        part_files = []
        for r in range(num_workers):
            lo, hi = bounds[r], bounds[r + 1]
            if lo >= hi:
                log.fatal("partitioning produced an empty part for worker "
                          "%d (%d rows over %d workers)", r, len(X),
                          num_workers)
            path = os.path.join(tmp, f"part{r}.tsv")
            np.savetxt(path, np.column_stack([y[lo:hi], X[lo:hi]]),
                       delimiter="\t", fmt="%.17g")
            if weight is not None:
                np.savetxt(path + ".weight",
                           np.asarray(weight, np.float64)[lo:hi],
                           fmt="%.17g")
            if group is not None:
                qb = np.concatenate([[0], np.cumsum(np.asarray(group,
                                                               np.int64))])
                sizes = np.diff(qb[(qb >= lo) & (qb <= hi)])
                np.savetxt(path + ".query", sizes, fmt="%d")
            part_files.append(path)

    port = _free_port()
    machines = f"127.0.0.1:{port}"
    run_params = dict(params)
    if num_boost_round is not None:
        run_params["num_iterations"] = num_boost_round
    run_params.pop("pre_partition", None)

    procs = []
    cmds = []
    log_paths = []
    env = dict(os.environ)
    env.update(worker_env or {})
    for r in range(num_workers):
        model_path = os.path.join(tmp, f"model{r}.txt")
        cmd = [sys.executable, "-m", "lambdagap_tpu", "task=train",
               f"data={part_files[r]}", "pre_partition=true",
               f"num_machines={num_workers}", f"machine_rank={r}",
               f"machines={machines}", f"output_model={model_path}",
               *_params_to_cli(run_params)]
        cmds.append(" ".join(cmd))
        # per-rank log FILES, not pipes: a verbose worker that fills a 64KB
        # pipe buffer blocks mid-collective and drags every rank to the
        # timeout kill; files never backpressure the workers
        lp = os.path.join(tmp, f"worker{r}.log")
        log_paths.append(lp)
        lf = open(lp, "w")
        try:
            procs.append(subprocess.Popen(cmd, stdout=lf,
                                          stderr=subprocess.STDOUT,
                                          cwd=os.getcwd(), env=env))
        finally:
            lf.close()          # the child holds its own descriptor
    def _tail(path, n=3000):
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(f.tell() - n, 0))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    import time
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 0.1))
        except subprocess.TimeoutExpired:
            # reap already-finished ranks first: kill() does not set
            # returncode, so without poll() every unwaited-but-exited
            # worker would be misreported as stalled
            stalled = [r for r, q in enumerate(procs) if q.poll() is None]
            for q in procs:
                q.kill()
            detail = "\n".join(
                f"--- worker {r} ({log_paths[r]}) ---\n{_tail(log_paths[r])}"
                for r in stalled)
            log.fatal("cluster training timed out after %.0fs "
                      "(stalled ranks: %s)\n%s", timeout, stalled, detail)
    for r, p in enumerate(procs):
        if p.returncode != 0:
            log.fatal("cluster worker %d failed (rc=%d):\n%s", r,
                      p.returncode, _tail(log_paths[r]))

    with open(os.path.join(tmp, "model0.txt")) as f:
        model_str = f.read()
    booster = Booster(model_str=model_str)
    booster.cluster_commands = cmds       # the multi-host recipe, verbatim
    if not keep_files and workdir is None:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return booster
