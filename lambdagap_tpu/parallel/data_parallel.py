"""Distributed tree learners over a device mesh.

TPU re-design of the reference's three distributed learners:

- **data-parallel** (reference: src/treelearner/data_parallel_tree_learner.cpp):
  rows sharded over the ``data`` mesh axis; per split every device builds the
  histogram of its local rows and a ``psum`` over ICI replaces the
  ReduceScatter+HistogramSumReducer machinery (:283-298) — the feature→rank
  ownership tables (PrepareBufferPos :71-121) disappear because XLA owns the
  reduction schedule. The best-split argmax runs replicated on every device
  (deterministic), which subsumes ``SyncUpGlobalBestSplit`` (:443).
- **feature-parallel** (reference: src/treelearner/feature_parallel_tree_learner.cpp):
  data replicated, each device builds histograms only for its feature block
  (:38-59 greedy assignment → here a static equal block), then an
  ``all_gather`` of per-block histograms replaces the SplitInfo Allgather.
- **voting-parallel** (reference: src/treelearner/voting_parallel_tree_learner.cpp):
  data-parallel with communication capped: each device proposes its top-k
  features by local gain (:151-175 GlobalVoting), histograms are summed only
  for the voted union (:184 CopyLocalHistogram).

All three keep the serial learner's host loop; only the device ops change.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.learner import SerialTreeLearner, _HostSplit, _next_pow2
from ..models.tree import Tree
from ..ops.histogram import histogram_from_rows
from ..ops.partition import decision_go_left
from ..ops.split import find_best_split
from ..utils import log
from .mesh import shard_rows
from .sharding import DATA_AXIS, make_mesh, shard_map, spec, specs


class DataParallelTreeLearner(SerialTreeLearner):
    """Rows sharded over the mesh; histograms psum-reduced over ICI."""

    # the host-loop distributed learners histogram through their own
    # sharded-matrix hooks; they opt out of the physically sorted layout
    # (the fused data-parallel learner supports it in-program)
    supports_sorted_layout = False
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__(dataset, config)
        if self.mono_on:
            log.warning("tree_learner=%s enforces monotone constraints only "
                        "per-split (direction veto); inherited leaf bounds "
                        "are not propagated — use the serial/fused learner "
                        "for strict monotonicity", config.tree_learner)
        if config.interaction_constraints:
            log.fatal("interaction_constraints are not supported with "
                      "tree_learner=%s; use the serial learner",
                      config.tree_learner)
        if self.cegb_on or config.feature_fraction_bynode < 1.0:
            log.warning("cegb/feature_fraction_bynode are not applied by "
                        "tree_learner=%s", config.tree_learner)
        self.mesh = mesh if mesh is not None else make_mesh(
            config.tpu_num_devices, mesh_shape=config.mesh_shape)
        if int(self.mesh.shape.get("feature", 1)) > 1:
            log.fatal("tree_learner=%s shards rows; mesh_shape=%s places "
                      "devices on the feature axis", config.tree_learner,
                      config.mesh_shape)
        self.n_dev = int(self.mesh.shape[DATA_AXIS])

        N = self.num_data
        pad = (-N) % self.n_dev
        self.n_pad = N + pad
        self.n_loc = self.n_pad // self.n_dev

        xb = np.asarray(dataset.binned)
        if pad:
            xb = np.pad(xb, ((0, pad), (0, 0)))
        self.x_sharded = jax.device_put(
            jnp.asarray(xb), NamedSharding(self.mesh, spec("x_rows")))
        # local permutation per shard (local indices)
        self.perm0_local = jax.device_put(
            jnp.tile(jnp.arange(self.n_loc, dtype=jnp.int32), self.n_dev),
            NamedSharding(self.mesh, spec("perm")))
        # padding-row mask (True = real row): the explicit mask channel of
        # shard_rows — the ONE place pad rows are decided (ISSUE-8
        # satellite; histogram/count kernels consume this mask, so pad
        # rows contribute exact zeros by construction)
        _, self.real_mask, _ = shard_rows(self.mesh,
                                          jnp.ones(N, dtype=bool))

        self._build_ops()

    # -- sharding helpers ----------------------------------------------
    def shard_grad(self, grad: jax.Array) -> jax.Array:
        return shard_rows(self.mesh, grad)[0]

    def combine_mask(self, row_mask: Optional[jax.Array]) -> jax.Array:
        if row_mask is None:
            return self.real_mask
        # in-bag mask and pad-row mask combine inside shard_rows
        return shard_rows(self.mesh, row_mask, mask=row_mask)[1]

    # -- shard_map ops --------------------------------------------------
    def _build_ops(self) -> None:
        mesh = self.mesh
        B = self.B
        rpb = self.rows_per_block
        prec = self.config.tpu_hist_precision

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=specs("x_rows", "grad", "hess", "row_mask"),
            out_specs=spec("hist"), check_vma=False)
        def root_hist(x_l, g_l, h_l, m_l):
            local = histogram_from_rows(x_l, g_l, h_l, m_l, B, rpb,
                                        precision=prec)
            return jax.lax.psum(local, DATA_AXIS)

        self._root_hist_op = jax.jit(root_hist)

        def leaf_hist(x_l, perm_l, g_l, h_l, m_l, begin_l, count_l, padded):
            lane = jnp.arange(padded, dtype=jnp.int32)
            idx = jnp.clip(begin_l[0] + lane, 0, perm_l.shape[0] - 1)
            rows = perm_l[idx]
            valid = (lane < count_l[0]) & m_l[rows]
            local = histogram_from_rows(x_l[rows], g_l[rows], h_l[rows],
                                        valid, B, rpb,
                                        precision=prec)
            return jax.lax.psum(local, DATA_AXIS)

        self._leaf_hist_ops: Dict[int, callable] = {}
        self._leaf_hist_fn = leaf_hist

        def partition(x_l, perm_l, begin_l, count_l, feat, thr, dl, dbin, mt,
                      nb, is_cat, bits, padded):
            N_l = perm_l.shape[0]
            lane = jnp.arange(padded, dtype=jnp.int32)
            idx = begin_l[0] + lane
            safe = jnp.clip(idx, 0, N_l - 1)
            rows = perm_l[safe]
            valid = lane < count_l[0]
            bv = x_l[rows, feat]
            go_left = decision_go_left(bv, thr, dl, dbin, mt, nb, is_cat, bits)
            go_left = go_left & valid
            key = jnp.where(go_left, 0, jnp.where(valid, 1, 2)).astype(jnp.int32)
            order = jnp.argsort(key * padded + lane)
            new_perm = perm_l.at[idx].set(rows[order], mode="drop")
            return new_perm, jnp.sum(go_left, dtype=jnp.int32)[None]

        self._partition_fn = partition
        self._partition_ops: Dict[int, callable] = {}

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=specs("score", "perm", "leaf_begin", "leaf_count",
                           "leaf_values"),
            out_specs=spec("score"), check_vma=False)
        def score_update(score_l, perm_l, leaf_begin, leaf_count, leaf_values):
            # per-shard leaf layout: [D, L] arrays indexed by my axis position
            d = jax.lax.axis_index(DATA_AXIS)
            N_l = score_l.shape[0]
            L = leaf_begin.shape[1]
            # leaves empty on this shard would duplicate another leaf's begin
            # offset; push them past the end so searchsorted never picks them
            lb = jnp.where(leaf_count[d] > 0, leaf_begin[d],
                           N_l + jnp.arange(L, dtype=leaf_begin.dtype))
            order = jnp.argsort(lb)
            sorted_begin = lb[order]
            which = jnp.searchsorted(
                sorted_begin, jnp.arange(N_l, dtype=lb.dtype), side="right") - 1
            vals = leaf_values[order[which]]
            return score_l.at[perm_l].add(vals)

        self._score_update_op = jax.jit(score_update)

    def _leaf_hist_op(self, padded: int):
        if padded not in self._leaf_hist_ops:
            fn = functools.partial(self._leaf_hist_fn, padded=padded)
            self._leaf_hist_ops[padded] = jax.jit(shard_map(
                fn, mesh=self.mesh,
                in_specs=specs("x_rows", "perm", "grad", "hess", "row_mask",
                               "begin", "count"),
                out_specs=spec("hist"), check_vma=False))
        return self._leaf_hist_ops[padded]

    def _root_totals(self, hist_root):
        """Global (g, h, count) totals from the root histogram."""
        return jnp.sum(hist_root[0], axis=0)

    def _partition_op(self, padded: int):
        if padded not in self._partition_ops:
            fn = functools.partial(self._partition_fn, padded=padded)
            self._partition_ops[padded] = jax.jit(shard_map(
                fn, mesh=self.mesh,
                in_specs=specs("x_rows", "perm", "begin", "count")
                + specs(*["scalar"] * 8),
                out_specs=specs("perm", "count"), check_vma=False))
        return self._partition_ops[padded]

    # ------------------------------------------------------------------
    def train(self, grad: jax.Array, hess: jax.Array,
              row_mask: Optional[jax.Array] = None) -> Tree:
        cfg = self.config
        if self.forced_json is not None:
            from ..utils import log
            log.warning("forcedsplits_filename is not supported by the "
                        "host-loop tree_learner=data/voting learners (use "
                        "the fused data-parallel learner); forced splits "
                        "ignored")
            self.forced_json = None
        num_leaves = cfg.num_leaves
        max_depth = cfg.max_depth
        tree = Tree(max_leaves=num_leaves)
        fmask = self._feature_mask()
        D = self.n_dev

        g = self.shard_grad(grad)
        h = self.shard_grad(hess)
        m = self.combine_mask(row_mask)

        perm = self.perm0_local
        # per-shard leaf bookkeeping (host): [D, L]
        leaf_begin = np.zeros((D, num_leaves), dtype=np.int64)
        leaf_count = np.zeros((D, num_leaves), dtype=np.int64)
        leaf_count[:, 0] = self.n_loc

        hist_root = self._root_hist_op(self.x_sharded, g, h, m)
        totals = self._root_totals(hist_root)
        from ..models.learner import _leaf_output_scalar
        root_out = _leaf_output_scalar(totals[0], totals[1], totals[2],
                                       self.params)
        hists: Dict[int, jax.Array] = {0: hist_root}
        best: Dict[int, _HostSplit] = {
            0: self._best(hist_root, totals[0], totals[1], totals[2],
                          root_out, fmask)}
        # NaN-tolerant count conversion (same contract as the serial
        # learner): non-finite gradients must reach the guard's iteration
        # boundary instead of crashing the host loop here
        # graftlint: disable=R1 — root-stat D2H, ONE batched pytree get
        # per tree (value/weight/count on a single sync, not three);
        # graftir's I2 audit shows the distributed hot programs lower with
        # zero host-boundary ops, so the host loop's explicit per-split
        # sync below is the only remaining transfer on this path
        root_out_h, root_w, root_cnt = (
            float(v) for v in
            jax.device_get((root_out, totals[1], totals[2])))
        tree.leaf_value[0] = root_out_h
        tree.leaf_weight[0] = root_w
        tree.leaf_count[0] = int(root_cnt) if np.isfinite(root_cnt) else 0

        def shard_scalars(vals: np.ndarray) -> jax.Array:
            return jax.device_put(jnp.asarray(vals.astype(np.int32)),
                                  NamedSharding(self.mesh,
                                                spec("shard_scalar")))

        for _ in range(num_leaves - 1):
            cand = [(s.gain_f, leaf) for leaf, s in best.items()
                    if np.isfinite(s.gain_f) and s.gain_f > 0
                    and (max_depth <= 0 or tree.leaf_depth[leaf] < max_depth)]
            if not cand:
                break
            _, leaf = max(cand)
            s = best.pop(leaf)

            counts_here = leaf_count[:, leaf]
            P_pad = min(max(_next_pow2(int(counts_here.max())), 64), self.n_loc)
            feat = int(s.feature)
            perm, left_counts_dev = self._partition_op(P_pad)(
                self.x_sharded, perm,
                shard_scalars(leaf_begin[:, leaf]),
                shard_scalars(counts_here),
                jnp.int32(feat), jnp.int32(s.threshold),
                jnp.asarray(bool(s.default_left)),
                self.default_bins_arr[feat], self.missing_types_arr[feat],
                self.num_bins_arr[feat], jnp.asarray(bool(s.is_categorical)),
                jnp.asarray(s.cat_bitset))
            # graftlint: disable=R1 — the per-split partition sync this
            # learner's host loop is architected around (left counts gate
            # the leaf bookkeeping for the NEXT split); graftir's I2 audit
            # confirms the partition program itself lowers transfer-free,
            # so this is the loop's one designed D2H, not a stray
            left_counts = np.asarray(
                jax.device_get(left_counts_dev)).astype(np.int64)
            right_counts = counts_here - left_counts
            # global child populations come from the histogram count channel
            gl_left = float(s.left_count)
            gl_right = float(s.right_count)
            if gl_left <= 0 or gl_right <= 0:
                log.warning("Degenerate distributed split on leaf %d; skipping", leaf)
                continue

            j = self.dataset.used_features[feat]
            mapper = self.dataset.mappers[j]
            mt_code = {"None": 0, "Zero": 1, "NaN": 2}[mapper.missing_type]
            cat_real = (self._cat_bitset_real(feat, s.cat_bitset)
                        if s.is_categorical else None)
            right_leaf = tree.split(
                leaf, feature=j, feature_inner=feat,
                threshold_bin=int(s.threshold),
                threshold_real=mapper.bin_to_value(int(s.threshold)),
                default_left=bool(s.default_left), missing_type=mt_code,
                gain=s.gain_f,
                left_value=float(s.left_output), right_value=float(s.right_output),
                left_weight=float(s.left_sum_h), right_weight=float(s.right_sum_h),
                left_count=int(gl_left), right_count=int(gl_right),
                is_categorical=bool(s.is_categorical),
                cat_bitset=np.asarray(s.cat_bitset), cat_bitset_real=cat_real)

            leaf_begin[:, right_leaf] = leaf_begin[:, leaf] + left_counts
            leaf_count[:, right_leaf] = right_counts
            leaf_count[:, leaf] = left_counts

            parent_hist = hists.pop(leaf)
            l_sums = (jnp.float32(s.left_sum_g), jnp.float32(s.left_sum_h),
                      jnp.float32(s.left_count), jnp.float32(s.left_output))
            r_sums = (jnp.float32(s.right_sum_g), jnp.float32(s.right_sum_h),
                      jnp.float32(s.right_count), jnp.float32(s.right_output))
            if tree.num_leaves >= num_leaves:
                break

            small_is_left = gl_left <= gl_right
            small_leaf = leaf if small_is_left else right_leaf
            large_leaf = right_leaf if small_is_left else leaf
            sc = leaf_count[:, small_leaf]
            Ph = min(max(_next_pow2(int(sc.max())), 64), self.n_loc)
            hist_small = self._leaf_hist_op(Ph)(
                self.x_sharded, perm, g, h, m,
                shard_scalars(leaf_begin[:, small_leaf]),
                shard_scalars(sc))
            hist_large = parent_hist - hist_small
            s_sums = l_sums if small_is_left else r_sums
            g_sums = r_sums if small_is_left else l_sums
            hists[small_leaf] = hist_small
            hists[large_leaf] = hist_large
            best[small_leaf] = self._best(hist_small, *s_sums, fmask)
            best[large_leaf] = self._best(hist_large, *g_sums, fmask)

        self.last_perm = perm
        self.last_leaf_begin = leaf_begin[:, :tree.num_leaves].copy()
        self.last_leaf_count = leaf_count[:, :tree.num_leaves].copy()
        return tree

    # ------------------------------------------------------------------
    def update_scores(self, score: jax.Array, leaf_values: jax.Array) -> jax.Array:
        """Add the just-trained tree to the training score [N] (unpadded in,
        unpadded out); the scatter itself runs sharded."""
        pad = self.n_pad - self.num_data
        s = jnp.pad(score, (0, pad)) if pad else score
        s = jax.device_put(s, NamedSharding(self.mesh, spec("score")))
        out = self._score_update_op(
            s, self.last_perm,
            jnp.asarray(self.last_leaf_begin.astype(np.int32)),
            jnp.asarray(self.last_leaf_count.astype(np.int32)),
            leaf_values)
        return out[:self.num_data]
