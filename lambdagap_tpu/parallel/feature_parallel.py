"""Feature-parallel tree learner.

(reference: src/treelearner/feature_parallel_tree_learner.cpp — every rank
holds all rows; features are partitioned for histogram work; local best
splits are argmax-merged with SyncUpGlobalBestSplit
(parallel_tree_learner.h:209); then all ranks apply the winning split on
full data.)

TPU shape: data stays replicated, the histogram op runs under ``shard_map``
with each device slicing its static feature block and an ``all_gather``
reassembling the full histogram; the reference's Allgather-of-SplitInfo is
subsumed by running the argmax on the (replicated) gathered histogram.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_old(*args, **kwargs)
from jax.sharding import Mesh, PartitionSpec as P

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.learner import SerialTreeLearner
from ..ops.histogram import histogram_from_rows
from .mesh import DATA_AXIS, make_mesh


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Serial loop + feature-blocked histogram construction."""

    # feature-blocked histogram hooks read the shared column layout;
    # explicit opt-out of the physically sorted row layout
    supports_sorted_layout = False
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__(dataset, config)
        self.mesh = mesh if mesh is not None else make_mesh(config.tpu_num_devices)
        self.n_dev = int(self.mesh.devices.size)
        F = self.num_features
        self.f_pad = ((F + self.n_dev - 1) // self.n_dev) * self.n_dev
        self.f_loc = self.f_pad // self.n_dev
        if self.f_pad != F:
            xb = np.asarray(dataset.binned)
            xb = np.pad(xb, ((0, 0), (0, self.f_pad - F)))
            self.x_binned = jnp.asarray(xb)
        self._hist_cache = {}

    def _hist_op(self, padded: int):
        if padded in self._hist_cache:
            return self._hist_cache[padded]
        B = self.B
        rpb = self.rows_per_block
        prec = self.config.tpu_hist_precision
        f_loc = self.f_loc
        F = self.num_features
        # shards tile the padded column axis exactly, so the per-shard
        # dynamic-slice start d*f_loc can never clamp
        assert f_loc * self.n_dev == self.f_pad

        def hist_blocked(x, perm, g, h, begin, count, row_mask):
            d = jax.lax.axis_index(DATA_AXIS)
            lane = jnp.arange(padded, dtype=jnp.int32)
            idx = jnp.clip(begin + lane, 0, perm.shape[0] - 1)
            rows = perm[idx]
            valid = (lane < count) & row_mask[rows]
            block = jax.lax.dynamic_slice(
                x[rows], (0, d * f_loc), (padded, f_loc))
            local = histogram_from_rows(block, g[rows], h[rows], valid, B, rpb,
                                        precision=prec)
            full = jax.lax.all_gather(local, DATA_AXIS, tiled=True)
            return full[:F]

        op = jax.jit(shard_map(
            hist_blocked, mesh=self.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P()),
            out_specs=P(), check_vma=False))
        self._hist_cache[padded] = op
        return op

    # hook points used by SerialTreeLearner.train ------------------------
    def _root_histogram(self, grad, hess, row_mask):
        N = self.num_data
        op = self._hist_op(self._pad_size(N))
        return op(self.x_binned, self.perm0, grad, hess,
                  jnp.int32(0), jnp.int32(N),
                  row_mask if row_mask is not None
                  else jnp.ones(N, dtype=bool))

    def _leaf_histogram(self, perm, grad, hess, begin, count, padded, row_mask):
        op = self._hist_op(padded)
        return op(self.x_binned, perm, grad, hess,
                  jnp.int32(begin), jnp.int32(count),
                  row_mask if row_mask is not None
                  else jnp.ones(perm.shape[0], dtype=bool))
