"""Feature-parallel tree learner.

(reference: src/treelearner/feature_parallel_tree_learner.cpp — every rank
holds all rows; features are partitioned for histogram work; local best
splits are argmax-merged with SyncUpGlobalBestSplit
(parallel_tree_learner.h:209); then all ranks apply the winning split on
full data.)

TPU shape: data stays replicated, the histogram op runs under ``shard_map``
with each device slicing its static feature block and an ``all_gather``
reassembling the full histogram; the reference's Allgather-of-SplitInfo is
subsumed by running the argmax on the (replicated) gathered histogram.

Devices sit on the ``feature`` axis of the registry mesh (a ``(1, D)``
placement of :func:`lambdagap_tpu.parallel.sharding.make_mesh`) — column
ownership is the partition spec, not a hand-rolled block table.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.learner import SerialTreeLearner
from ..ops.histogram import histogram_from_rows
from ..utils import log
from .sharding import FEATURE_AXIS, make_mesh, shard_map, spec, specs


class FeatureParallelTreeLearner(SerialTreeLearner):
    """Serial loop + feature-blocked histogram construction."""

    # feature-blocked histogram hooks read the shared column layout;
    # explicit opt-out of the physically sorted row layout
    supports_sorted_layout = False
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        super().__init__(dataset, config)
        self.mesh = mesh if mesh is not None else make_mesh(
            config.tpu_num_devices, mesh_shape=config.mesh_shape,
            shard_axis=FEATURE_AXIS)
        if int(self.mesh.shape.get("data", 1)) > 1:
            log.fatal("tree_learner=feature shards columns; mesh_shape=%s "
                      "places devices on the data axis", config.mesh_shape)
        self.n_dev = int(self.mesh.shape[FEATURE_AXIS])
        F = self.num_features
        self.f_pad = ((F + self.n_dev - 1) // self.n_dev) * self.n_dev
        self.f_loc = self.f_pad // self.n_dev
        if self.f_pad != F:
            xb = np.asarray(dataset.binned)
            xb = np.pad(xb, ((0, 0), (0, self.f_pad - F)))
            self.x_binned = jnp.asarray(xb)
        self._hist_cache = {}

    def _hist_op(self, padded: int):
        if padded in self._hist_cache:
            return self._hist_cache[padded]
        B = self.B
        rpb = self.rows_per_block
        prec = self.config.tpu_hist_precision
        f_loc = self.f_loc
        F = self.num_features
        # shards tile the padded column axis exactly, so the per-shard
        # dynamic-slice start d*f_loc can never clamp
        assert f_loc * self.n_dev == self.f_pad

        def hist_blocked(x, perm, g, h, begin, count, row_mask):
            d = jax.lax.axis_index(FEATURE_AXIS)
            lane = jnp.arange(padded, dtype=jnp.int32)
            idx = jnp.clip(begin + lane, 0, perm.shape[0] - 1)
            rows = perm[idx]
            valid = (lane < count) & row_mask[rows]
            block = jax.lax.dynamic_slice(
                x[rows], (0, d * f_loc), (padded, f_loc))
            local = histogram_from_rows(block, g[rows], h[rows], valid, B, rpb,
                                        precision=prec)
            full = jax.lax.all_gather(local, FEATURE_AXIS, tiled=True)
            return full[:F]

        op = jax.jit(shard_map(
            hist_blocked, mesh=self.mesh,
            # rows replicated: the per-row specs shard over the data axis,
            # whose extent is 1 on the (1, D) feature placement; begin /
            # count are replicated scalars here (not the per-shard vectors
            # of the data-parallel loop)
            in_specs=(spec("x_replicated"), spec("perm"), spec("grad"),
                      spec("hess"), spec("scalar"), spec("scalar"),
                      spec("row_mask")),
            out_specs=spec("hist"), check_vma=False))
        self._hist_cache[padded] = op
        return op

    # hook points used by SerialTreeLearner.train ------------------------
    def _root_histogram(self, grad, hess, row_mask):
        N = self.num_data
        op = self._hist_op(self._pad_size(N))
        return op(self.x_binned, self.perm0, grad, hess,
                  jnp.int32(0), jnp.int32(N),
                  row_mask if row_mask is not None
                  else jnp.ones(N, dtype=bool))

    def _leaf_histogram(self, perm, grad, hess, begin, count, padded, row_mask):
        op = self._hist_op(padded)
        return op(self.x_binned, perm, grad, hess,
                  jnp.int32(begin), jnp.int32(count),
                  row_mask if row_mask is not None
                  else jnp.ones(perm.shape[0], dtype=bool))
