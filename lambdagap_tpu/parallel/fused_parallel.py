"""Fused data-parallel learner: the whole-tree program under shard_map.

The multi-chip production path. The host-loop distributed learners
(``data_parallel.py``) re-introduce a D2H sync per split — exactly the
latency the fused learner exists to kill (models/fused_learner.py:8-11). Here
the ENTIRE leaf-wise tree build runs as one jitted shard_map program over the
``data`` mesh axis: rows are sharded, each shard runs the fused per-split
step on its local rows, and the only cross-shard traffic is one histogram
``psum`` per split (the TPU answer to the reference's
ReduceScatter+HistogramSumReducer,
reference: src/treelearner/data_parallel_tree_learner.cpp:283-298). The
best-split scan and leaf argmax run replicated on every shard from the
psum-ed histograms — identical inputs through identical arithmetic — which
subsumes SyncUpGlobalBestSplit (reference:
src/treelearner/parallel_tree_learner.h:209); zero per-split host syncs.

Sharding invariants the per-shard body maintains (see
FusedTreeLearner._train_tree_impl):

- ``perm`` / ``leaf_i`` begin/count are LOCAL (per-shard row partition);
- ``leaf_f`` aggregates, gains and chosen splits are GLOBAL (derived from
  psum-ed histograms — bit-identical across shards);
- the smaller-child choice uses the scan's global counts, never the local
  partition counts (shards must agree which side each psum describes);
- local chunk loops may run different trip counts per shard, but every
  shard reaches the per-split psum exactly once.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.fused_learner import DeviceTree, FusedTreeLearner
from ..models.learner import _next_pow2
from ..utils import log
from .mesh import shard_rows
from .sharding import (DATA_AXIS, FEATURE_AXIS, make_mesh, shard_map, spec,
                       specs)
from .multiprocess import global_array_from_local

_DEBUG_CHECKS = os.environ.get("LAMBDAGAP_DEBUG", "0") not in ("0", "",
                                                               "false")


class FusedDataParallelTreeLearner(FusedTreeLearner):
    """Rows sharded over the mesh; one whole tree per dispatch."""

    # the shard_map program keeps per-shard matrices device-resident;
    # out-of-core streaming is a single-chip mode for now (ROADMAP 1 x 4)
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        # mesh geometry first: the base-class init places the binned matrix
        # through _place_binned, which shards it directly (no host round-trip)
        self.proc_sharded = bool(getattr(dataset, "process_sharded", False))
        if self.proc_sharded:
            # pre_partition=true: this process holds only its own rows;
            # every process is padded to a common per-process block so the
            # global leading axis splits evenly over all devices
            # (reference: per-rank data with synced mappers,
            # src/io/dataset_loader.cpp:1072)
            self.mesh = mesh if mesh is not None else make_mesh(
                0, mesh_shape=config.mesh_shape)
            self._check_data_placement(config)
            self.n_dev = int(self.mesh.shape[DATA_AXIS])
            n_proc = jax.process_count()
            ldev = max(self.n_dev // n_proc, 1)
            max_cnt = int(np.max(dataset.global_row_counts))
            self.proc_pad = -(-max_cnt // ldev) * ldev
            self.n_pad = self.proc_pad * n_proc
            self.n_loc = self.proc_pad // ldev
            super().__init__(dataset, config)
            self.axis = DATA_AXIS
            real = np.zeros(self.proc_pad, dtype=bool)
            real[:dataset.num_data] = True
            self.real_mask = global_array_from_local(real, self.mesh,
                                                     spec("row_mask"))
        else:
            self.mesh = mesh if mesh is not None else make_mesh(
                config.tpu_num_devices, mesh_shape=config.mesh_shape)
            self._check_data_placement(config)
            self.n_dev = int(self.mesh.shape[DATA_AXIS])
            N = dataset.num_data
            pad = (-N) % self.n_dev
            self.n_pad = N + pad
            self.n_loc = self.n_pad // self.n_dev
            super().__init__(dataset, config)
            self.axis = DATA_AXIS

            # pad-row mask from shard_rows' explicit mask channel — the
            # one place padding is decided (ISSUE-8 satellite)
            self.real_mask = shard_rows(self.mesh,
                                        jnp.ones(N, dtype=bool))[1]

        # the whole-tree program as a shard_map body. check_vma off: the
        # replicated outputs (split structure, leaf values) are replicated
        # by construction from psum-ed histograms, but they share carried
        # state matrices with local values (leaf_i begin/count), which the
        # static replication tracker cannot see through.
        body = functools.partial(self._train_tree_impl, has_mask=True)
        qspec = spec("gq") if self.quant else spec("rep")
        # tree_layout=sorted: the leaf-ordered packed buffer is built by a
        # separate shard_map pre-pass (rows sharded, per-shard W pad rows
        # included in the global layout) and consumed by the training body
        # as one more row-sharded input; everything the per-split
        # permutation-apply touches is shard-local, so the histogram psum
        # stays the only collective per split
        srows_spec = spec("srows") if self.layout == "sorted" \
            else spec("rep")
        if self.layout == "sorted":
            self._layout_jit_dp = jax.jit(shard_map(
                functools.partial(self._build_sorted_impl, has_mask=True),
                mesh=self.mesh,
                in_specs=specs("grad", "hess", "row_mask", "x_rows")
                + (qspec, qspec),
                out_specs=spec("srows"), check_vma=False))
        in_specs = specs("grad", "hess", "row_mask", "fmask", "x_rows",
                         "x_cols") + (srows_spec, qspec, qspec) \
            + specs("scalar", "scalar", "ekey")
        out_specs = DeviceTree(**{
            f: spec("row_leaf") if f == "row_leaf" else spec("tree")
            for f in DeviceTree._fields})
        self._train_jit_dp = jax.jit(shard_map(
            body, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False))

    def _check_data_placement(self, config: Config) -> None:
        if int(self.mesh.shape.get(FEATURE_AXIS, 1)) > 1:
            log.fatal("the fused data/voting-parallel learners shard rows; "
                      "mesh_shape=%s places devices on the feature axis",
                      config.mesh_shape)

    # -- device-layout hooks -------------------------------------------
    def _place_binned(self, hx: np.ndarray) -> None:
        if self.proc_sharded:
            pad = self.proc_pad - hx.shape[0]
            if pad:
                hx = np.pad(hx, ((0, pad), (0, 0)))
            self.hx_rows = global_array_from_local(hx, self.mesh,
                                                   spec("x_rows"))
            self.x_cols = global_array_from_local(
                np.ascontiguousarray(hx.T), self.mesh, spec("x_cols"))
            return
        pad = self.n_pad - hx.shape[0]
        if pad:
            hx = np.pad(hx, ((0, pad), (0, 0)))
        self.hx_rows = jax.device_put(
            jnp.asarray(hx), NamedSharding(self.mesh, spec("x_rows")))
        self.x_cols = jax.device_put(
            jnp.asarray(np.ascontiguousarray(hx.T)),
            NamedSharding(self.mesh, spec("x_cols")))

    def _pick_chunk(self) -> int:
        # sized off LOCAL rows, not the global count, and with a lower floor
        # than the serial learner's 4096: per-shard leaf populations are
        # n_dev-times smaller, so a wide window is mostly padding (measured
        # 3.2x -> 1.2x vs serial fused on the 8-CPU mesh). The per-leaf
        # estimate is HALVED like the serial learner's — the leaf-wise tree
        # splits every population in two, so a full-per-leaf window pays
        # ~2x padding on every shard from depth 1 on (measured 50 -> 42
        # s/iter at the 512k-row multichip shape on the 8-virtual-CPU
        # mesh; window size cannot change quantized results — integer
        # accumulation is window-invariant — and f32 histograms remain
        # reduction-order-equal)
        forced = self._chunk_override()
        if forced is not None:
            return forced
        cap = max(int(self.config.tpu_rows_per_block) * 16, 1 << 12)
        per_leaf = self.n_loc // max(self.config.num_leaves, 8)
        return min(max(_next_pow2(max(per_leaf // 2, 1)), 1 << 10), cap)

    # ------------------------------------------------------------------
    def _shard_vec(self, v: jax.Array) -> jax.Array:
        if self.proc_sharded:
            # v is this process's LOCAL rows (boosting state is per-rank,
            # like the reference's per-machine Boosting object). Pad and
            # split on device — no host round-trip on the per-tree hot path.
            if isinstance(v, jax.Array) and not v.is_fully_addressable:
                if v.sharding.is_fully_replicated:
                    # replicated global array (e.g. state that passed
                    # through a shard_map output): take this process's copy
                    v = v.addressable_data(0)
                else:
                    from ..utils import log
                    log.fatal(
                        "pre-partitioned boosting state must be rank-local "
                        "(or replicated), got a cross-process sharded array "
                        "%s", v.sharding)
            v = jnp.asarray(v)
            if v.shape[0] == self.n_pad and self.n_pad != self.proc_pad:
                # GLOBAL-length replicated state: take this rank's block
                # (rank blocks tile the global axis exactly, so the
                # dynamic-slice start can never clamp)
                assert self.n_pad % self.proc_pad == 0
                p = jax.process_index() * self.proc_pad
                v = lax.dynamic_slice_in_dim(v, p, self.proc_pad, axis=0)
            pad = self.proc_pad - v.shape[0]
            if pad:
                v = jnp.pad(v, [(0, pad)] + [(0, 0)] * (v.ndim - 1))
            gshape = (self.n_pad,) + v.shape[1:]
            sharding = NamedSharding(self.mesh,
                                     spec("row_mask", ndim=v.ndim))
            p0 = jax.process_index() * self.proc_pad
            blocks = []
            for d, idx in sharding.addressable_devices_indices_map(
                    gshape).items():
                lo = (idx[0].start or 0) - p0
                blocks.append(jax.device_put(v[lo:lo + self.n_loc], d))
            return jax.make_array_from_single_device_arrays(
                gshape, sharding, blocks)
        return shard_rows(self.mesh, v)[0]

    def _check_shard_agreement(self, rec: DeviceTree) -> None:
        """LAMBDAGAP_DEBUG cross-shard divergence check. The tree record is
        nominally replicated — every shard derives it from identically
        psum-ed histograms — but ``check_vma=False`` on the shard_map means
        the static checker never proves it: a dropped psum on a new code
        path would silently corrupt training. Here each device's copy of
        the per-split decisions is compared bit-for-bit (the runtime analog
        of the reference's SyncUpGlobalBestSplit all-reduce agreeing on one
        winner, src/treelearner/parallel_tree_learner.h:209)."""
        from ..utils import log
        for name in ("node_feature", "node_threshold", "node_gain",
                     "leaf_value", "num_leaves"):
            arr = getattr(rec, name)
            shards = getattr(arr, "addressable_shards", None)
            if not shards:
                continue
            ref = np.asarray(shards[0].data)
            for s in shards[1:]:
                got = np.asarray(s.data)
                if not np.array_equal(ref, got, equal_nan=True):
                    bad = np.nonzero(ref != got)[0][:8] if ref.ndim else []
                    log.fatal(
                        "cross-shard divergence in %s on device %s "
                        "(first diverging indices %s): shards disagreed on "
                        "the split sequence — a collective is missing from "
                        "the fused program", name, s.device, list(bad))

    def train_device(self, grad: jax.Array, hess: jax.Array,
                     row_mask: Optional[jax.Array] = None) -> DeviceTree:
        fmask = self._feature_mask()
        g = self._shard_vec(grad)
        h = self._shard_vec(hess)
        if row_mask is None:
            m = self.real_mask
        elif self.proc_sharded:
            m = self._shard_vec(row_mask) & self.real_mask
        else:
            # in-bag + pad-row masks combine in shard_rows' mask channel
            m = shard_rows(self.mesh, row_mask, mask=row_mask)[1]
        if self.quant:
            from ..ops.hist_pallas import quantize_gradients
            self._qkey, sub = jax.random.split(self._qkey)
            gmax = hmax = None
            if self.proc_sharded and jax.process_count() > 1:
                # every rank holds different rows: agree on GLOBAL |grad| /
                # hess maxima before deriving quantization scales, else the
                # psum-ed int32 histograms would mix incompatible units
                from jax.experimental import multihost_utils
                # graftlint: disable=R1 — one cross-host max sync per TREE
                # (not per split); quantization scales must agree globally
                lm = np.asarray(
                    [float(jnp.max(jnp.abs(grad))), float(jnp.max(hess))],
                    np.float32)
                gm = np.asarray(multihost_utils.process_allgather(
                    lm)).reshape(-1, 2).max(axis=0)
                gmax = jnp.float32(max(float(gm[0]), 1e-12))
                hmax = jnp.float32(max(float(gm[1]), 1e-12))
            gq, hq, gs, hs = quantize_gradients(
                grad, hess, sub, self.config.num_grad_quant_bins,
                self.config.stochastic_rounding, gmax=gmax, hmax=hmax)
            gq, hq = self._shard_vec(gq), self._shard_vec(hq)
        else:
            gq = hq = jnp.zeros(1, jnp.int8)
            gs = hs = jnp.float32(1.0)
        if self._need_step_keys:
            self._ekey, e = jax.random.split(self._ekey)
            self._bkey, b = jax.random.split(self._bkey)
            ekey = jnp.stack([e, b])            # [2, 2]: extra / by-node
        else:
            ekey = jnp.zeros((2, 2), jnp.uint32)
        if self.layout == "sorted":
            with self.telemetry.phase("layout_apply"):
                srows = self._layout_jit_dp(g, h, m, self.hx_rows, gq, hq)
        else:
            srows = self._srows_dummy
        rec = self._train_jit_dp(g, h, m, fmask, self.hx_rows, self.x_cols,
                                 srows, gq, hq, gs, hs, ekey)
        if _DEBUG_CHECKS:
            self._check_shard_agreement(rec)
        # consumers (score update, leaf renewal) see an unpadded [N] leaf map
        if self.proc_sharded:
            # hand back this process's LOCAL rows: the booster's score
            # update stays rank-local (one D2H per tree, not per split).
            # leaf_value is localized too (replicated global -> this
            # process's copy) so downstream boosting state never becomes a
            # cross-process array.
            from .multiprocess import local_block
            rec = rec._replace(
                row_leaf=jnp.asarray(local_block(rec.row_leaf,
                                                 self.num_data)),
                leaf_value=jnp.asarray(rec.leaf_value.addressable_data(0)))
        else:
            rec = rec._replace(row_leaf=rec.row_leaf[:self.num_data])
        self.last_row_leaf = rec.row_leaf
        return rec


class FusedFeatureParallelTreeLearner(FusedTreeLearner):
    """Feature-parallel as ONE compiled whole-tree program (reference:
    src/treelearner/feature_parallel_tree_learner.cpp — every rank holds
    all rows, features are partitioned for histogram work, local best
    splits merge via SyncUpGlobalBestSplit, parallel_tree_learner.h:209):
    rows stay replicated, the binned matrix is sharded along the COLUMN
    axis, histograms and scans are shard-local, and the only per-split
    traffic is one all_gather of the D per-shard best-split tuples plus a
    psum broadcast of the winning feature's column for the partition —
    zero per-split host syncs (the host-loop variant in
    feature_parallel.py pays a D2H per split; this one does not)."""

    # the winning split's column lives on ONE shard and is psum-broadcast
    # for the (row-replicated) partition; the sorted layout's
    # decode-from-window shortcut cannot express that, so this learner
    # explicitly opts out and keeps the gather layout
    supports_sorted_layout = False
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        from ..utils import log
        if config.enable_bundle:
            # EFB bundles are columns; feature ownership under a bundled
            # shard would decouple from feature ids. Keep ownership trivial
            # (feat // C_loc) — the config copy avoids mutating the caller
            import copy
            config = copy.copy(config)
            config.enable_bundle = False
            log.info("EFB bundling is disabled under the fused "
                     "feature-parallel learner (column ownership must "
                     "follow feature ids)")
        self.mesh = mesh if mesh is not None else make_mesh(
            config.tpu_num_devices, mesh_shape=config.mesh_shape,
            shard_axis=FEATURE_AXIS)
        if int(self.mesh.shape.get(DATA_AXIS, 1)) > 1:
            log.fatal("the fused feature-parallel learner shards columns; "
                      "mesh_shape=%s places devices on the data axis",
                      config.mesh_shape)
        self.n_dev = int(self.mesh.shape[FEATURE_AXIS])
        super().__init__(dataset, config)
        if self.forced_seq is not None:
            # unreachable via the factory (gbdt._create_learner routes
            # forced-splits configs to the fused data-parallel learner)
            log.fatal("forced splits are not supported by the fused "
                      "feature-parallel learner; use tree_learner=data")
        self.feat_axis = FEATURE_AXIS
        # pad the per-feature meta arrays to the sharded width so the
        # per-shard dynamic slices stay in range; padded features can
        # never win (fmask False, 2-bin histograms of zeros)
        Fp = self._Fp
        pad = Fp - self.num_features
        if pad:
            self._real_F = self.num_features
            self.num_features = Fp
            z = lambda a, v: jnp.concatenate(
                [a, jnp.full((pad,), v, a.dtype)])
            self.num_bins_arr = z(self.num_bins_arr, 2)
            self.default_bins_arr = z(self.default_bins_arr, 0)
            self.missing_types_arr = z(self.missing_types_arr, 0)
            self.is_categorical_arr = z(self.is_categorical_arr, False)
            self.mono_arr = z(self.mono_arr, 0)
            self.nb_minus1_arr = z(self.nb_minus1_arr, 1)
            if self.contri_arr is not None:
                self.contri_arr = z(self.contri_arr, 1.0)
        else:
            self._real_F = self.num_features

        def sharded(grad, hess, mask, fmask, xr, xc, srows, gq, hq, gs, hs,
                    ekey, *, has_mask):
            body = functools.partial(self._train_tree_impl,
                                     has_mask=has_mask)
            # the SAME registry rules as the data-parallel program: on this
            # (1, D) feature placement the per-row specs' data axis has
            # extent 1 (rows replicated) while x_rows/x_cols shard columns
            return shard_map(
                body, mesh=self.mesh,
                in_specs=specs("grad", "hess", "row_mask", "fmask",
                               "x_rows", "x_cols", "rep", "gq", "hq",
                               "scalar", "scalar", "ekey"),
                out_specs=DeviceTree(
                    *([spec("tree")] * len(DeviceTree._fields))),
                check_vma=False)(grad, hess, mask, fmask, xr, xc, srows,
                                 gq, hq, gs, hs, ekey)

        self._train_jit = jax.jit(sharded, static_argnames=("has_mask",))

    def _place_binned(self, hx: np.ndarray) -> None:
        C = hx.shape[1]
        pad = (-C) % self.n_dev
        if pad:
            hx = np.pad(hx, ((0, 0), (0, pad)))
        self._Fp = C + pad
        self.hx_rows = jax.device_put(
            jnp.asarray(hx), NamedSharding(self.mesh, spec("x_rows")))
        self.x_cols = jax.device_put(
            jnp.asarray(np.ascontiguousarray(hx.T)),
            NamedSharding(self.mesh, spec("x_cols")))

    def _feature_mask(self) -> jax.Array:
        # sample over the REAL features only (num_features is the padded
        # program width), then pad False so pad columns can never win
        saved = self.num_features
        self.num_features = self._real_F
        try:
            m = super()._feature_mask()
        finally:
            self.num_features = saved
        pad = self.num_features - m.shape[0]
        if pad > 0:
            m = jnp.concatenate([m, jnp.zeros(pad, dtype=bool)])
        return m


class FusedVotingParallelTreeLearner(FusedDataParallelTreeLearner):
    """Voting-parallel as ONE compiled whole-tree program (reference:
    src/treelearner/voting_parallel_tree_learner.cpp — GlobalVoting :151-175
    + CopyLocalHistogram/Allreduce :184): histograms stay shard-local, each
    split step all_gathers the shards' top-k feature votes and psums only
    the voted columns — O(D·top_k·B) bytes per split instead of O(F·B) —
    with zero per-split host syncs (the host-loop variant in
    voting_parallel.py pays a D2H per split; this one does not)."""

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        from ..utils import log
        super().__init__(dataset, config, mesh)
        if self.forced_seq is not None:
            # unreachable via the factory (gbdt._create_learner routes
            # forced-splits configs to the fused data-parallel learner);
            # guards direct construction
            log.fatal("forced splits need global histograms, which voting "
                      "keeps local; use the fused data-parallel learner")
        self.voting = True
        self.vote_k = max(1, min(int(config.top_k), self.num_features))
        if self.quant and self.quant_exact:
            # voting stores RAW integer level sums in the float32 per-leaf
            # histogram state until the voted-column psum (the full-histogram
            # paths scale immediately after their psum), so exactness is
            # bounded by the f32 integer range, not the int32 accumulator —
            # i.e. the one-hot limit regardless of the configured kernel
            from ..ops.hist_pallas import exact_accum_limit
            qb = config.num_grad_quant_bins
            self.quant_exact = (dataset.num_data * qb
                                < exact_accum_limit("onehot"))
            if not self.quant_exact:
                log.warning("quantized voting-parallel level sums may exceed "
                            "the float32-exact range (%d rows x %d levels); "
                            "using per-chunk scaled float32 accumulation",
                            dataset.num_data, qb)
