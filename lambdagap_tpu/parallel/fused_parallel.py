"""Fused data-parallel learner: the whole-tree program under shard_map.

The multi-chip production path. The host-loop distributed learners
(``data_parallel.py``) re-introduce a D2H sync per split — exactly the
latency the fused learner exists to kill (models/fused_learner.py:8-11). Here
the ENTIRE leaf-wise tree build runs as one jitted shard_map program over the
``data`` mesh axis: rows are sharded, each shard runs the fused per-split
step on its local rows, and the only cross-shard traffic is one histogram
``psum`` per split (the TPU answer to the reference's
ReduceScatter+HistogramSumReducer,
reference: src/treelearner/data_parallel_tree_learner.cpp:283-298). The
best-split scan and leaf argmax run replicated on every shard from the
psum-ed histograms — identical inputs through identical arithmetic — which
subsumes SyncUpGlobalBestSplit (reference:
src/treelearner/parallel_tree_learner.h:209); zero per-split host syncs.

Sharding invariants the per-shard body maintains (see
FusedTreeLearner._train_tree_impl):

- ``perm`` / ``leaf_i`` begin/count are LOCAL (per-shard row partition);
- ``leaf_f`` aggregates, gains and chosen splits are GLOBAL (derived from
  psum-ed histograms — bit-identical across shards);
- the smaller-child choice uses the scan's global counts, never the local
  partition counts (shards must agree which side each psum describes);
- local chunk loops may run different trip counts per shard, but every
  shard reaches the per-split psum exactly once.
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.fused_learner import HIST_C, DeviceTree, FusedTreeLearner
from ..models.learner import _next_pow2
from ..ops.split import (K_MIN_SCORE, calculate_leaf_output, leaf_gain,
                         per_feature_best)
from ..utils import log
from .mesh import shard_rows
from .sharding import (DATA_AXIS, FEATURE_AXIS, make_mesh, shard_map, spec,
                       specs)
from .multiprocess import global_array_from_local

_DEBUG_CHECKS = os.environ.get("LAMBDAGAP_DEBUG", "0") not in ("0", "",
                                                               "false")


class FusedDataParallelTreeLearner(FusedTreeLearner):
    """Rows sharded over the mesh; one whole tree per dispatch."""

    # this shard_map program keeps per-shard matrices device-resident;
    # stream x tree_learner=data now routes to Fused2DTreeLearner's
    # composed out-of-core program BEFORE this class is constructed, so
    # the opt-out only fires for pre-partitioned multi-process data
    # (process-local rows have no host-shard pump) — still a loud demote
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        # mesh geometry first: the base-class init places the binned matrix
        # through _place_binned, which shards it directly (no host round-trip)
        self.proc_sharded = bool(getattr(dataset, "process_sharded", False))
        if self.proc_sharded:
            # pre_partition=true: this process holds only its own rows;
            # every process is padded to a common per-process block so the
            # global leading axis splits evenly over all devices
            # (reference: per-rank data with synced mappers,
            # src/io/dataset_loader.cpp:1072)
            self.mesh = mesh if mesh is not None else make_mesh(
                0, mesh_shape=config.mesh_shape)
            self._check_data_placement(config)
            self.n_dev = int(self.mesh.shape[DATA_AXIS])
            n_proc = jax.process_count()
            ldev = max(self.n_dev // n_proc, 1)
            max_cnt = int(np.max(dataset.global_row_counts))
            self.proc_pad = -(-max_cnt // ldev) * ldev
            self.n_pad = self.proc_pad * n_proc
            self.n_loc = self.proc_pad // ldev
            super().__init__(dataset, config)
            self.axis = DATA_AXIS
            real = np.zeros(self.proc_pad, dtype=bool)
            real[:dataset.num_data] = True
            self.real_mask = global_array_from_local(real, self.mesh,
                                                     spec("row_mask"))
        else:
            self.mesh = mesh if mesh is not None else make_mesh(
                config.tpu_num_devices, mesh_shape=config.mesh_shape)
            self._check_data_placement(config)
            self.n_dev = int(self.mesh.shape[DATA_AXIS])
            N = dataset.num_data
            pad = (-N) % self.n_dev
            self.n_pad = N + pad
            self.n_loc = self.n_pad // self.n_dev
            super().__init__(dataset, config)
            self.axis = DATA_AXIS

            # pad-row mask from shard_rows' explicit mask channel — the
            # one place padding is decided (ISSUE-8 satellite)
            self.real_mask = shard_rows(self.mesh,
                                        jnp.ones(N, dtype=bool))[1]

        # the whole-tree program as a shard_map body. check_vma off: the
        # replicated outputs (split structure, leaf values) are replicated
        # by construction from psum-ed histograms, but they share carried
        # state matrices with local values (leaf_i begin/count), which the
        # static replication tracker cannot see through.
        body = functools.partial(self._train_tree_impl, has_mask=True)
        qspec = spec("gq") if self.quant else spec("rep")
        # tree_layout=sorted: the leaf-ordered packed buffer is built by a
        # separate shard_map pre-pass (rows sharded, per-shard W pad rows
        # included in the global layout) and consumed by the training body
        # as one more row-sharded input; everything the per-split
        # permutation-apply touches is shard-local, so the histogram psum
        # stays the only collective per split
        srows_spec = spec("srows") if self.layout == "sorted" \
            else spec("rep")
        if self.layout == "sorted":
            self._layout_jit_dp = jax.jit(shard_map(
                functools.partial(self._build_sorted_impl, has_mask=True),
                mesh=self.mesh,
                in_specs=specs("grad", "hess", "row_mask", "x_rows")
                + (qspec, qspec),
                out_specs=spec("srows"), check_vma=False))
        in_specs = specs("grad", "hess", "row_mask", "fmask", "x_rows",
                         "x_cols") + (srows_spec, qspec, qspec) \
            + specs("scalar", "scalar", "ekey")
        out_specs = DeviceTree(**{
            f: spec("row_leaf") if f == "row_leaf" else spec("tree")
            for f in DeviceTree._fields})
        self._train_jit_dp = jax.jit(shard_map(
            body, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False))

    def _check_data_placement(self, config: Config) -> None:
        if int(self.mesh.shape.get(FEATURE_AXIS, 1)) > 1:
            log.fatal("the fused data/voting-parallel learners shard rows; "
                      "mesh_shape=%s places devices on the feature axis",
                      config.mesh_shape)

    # -- device-layout hooks -------------------------------------------
    def _place_binned(self, hx: np.ndarray) -> None:
        if self.proc_sharded:
            pad = self.proc_pad - hx.shape[0]
            if pad:
                hx = np.pad(hx, ((0, pad), (0, 0)))
            self.hx_rows = global_array_from_local(hx, self.mesh,
                                                   spec("x_rows"))
            self.x_cols = global_array_from_local(
                np.ascontiguousarray(hx.T), self.mesh, spec("x_cols"))
            return
        pad = self.n_pad - hx.shape[0]
        if pad:
            hx = np.pad(hx, ((0, pad), (0, 0)))
        self.hx_rows = jax.device_put(
            jnp.asarray(hx), NamedSharding(self.mesh, spec("x_rows")))
        self.x_cols = jax.device_put(
            jnp.asarray(np.ascontiguousarray(hx.T)),
            NamedSharding(self.mesh, spec("x_cols")))

    def _pick_chunk(self) -> int:
        # sized off LOCAL rows, not the global count, and with a lower floor
        # than the serial learner's 4096: per-shard leaf populations are
        # n_dev-times smaller, so a wide window is mostly padding (measured
        # 3.2x -> 1.2x vs serial fused on the 8-CPU mesh). The per-leaf
        # estimate is HALVED like the serial learner's — the leaf-wise tree
        # splits every population in two, so a full-per-leaf window pays
        # ~2x padding on every shard from depth 1 on (measured 50 -> 42
        # s/iter at the 512k-row multichip shape on the 8-virtual-CPU
        # mesh; window size cannot change quantized results — integer
        # accumulation is window-invariant — and f32 histograms remain
        # reduction-order-equal)
        forced = self._chunk_override()
        if forced is not None:
            return forced
        cap = max(int(self.config.tpu_rows_per_block) * 16, 1 << 12)
        per_leaf = self.n_loc // max(self.config.num_leaves, 8)
        return min(max(_next_pow2(max(per_leaf // 2, 1)), 1 << 10), cap)

    # ------------------------------------------------------------------
    def _shard_vec(self, v: jax.Array) -> jax.Array:
        if self.proc_sharded:
            # v is this process's LOCAL rows (boosting state is per-rank,
            # like the reference's per-machine Boosting object). Pad and
            # split on device — no host round-trip on the per-tree hot path.
            if isinstance(v, jax.Array) and not v.is_fully_addressable:
                if v.sharding.is_fully_replicated:
                    # replicated global array (e.g. state that passed
                    # through a shard_map output): take this process's copy
                    v = v.addressable_data(0)
                else:
                    from ..utils import log
                    log.fatal(
                        "pre-partitioned boosting state must be rank-local "
                        "(or replicated), got a cross-process sharded array "
                        "%s", v.sharding)
            v = jnp.asarray(v)
            if v.shape[0] == self.n_pad and self.n_pad != self.proc_pad:
                # GLOBAL-length replicated state: take this rank's block
                # (rank blocks tile the global axis exactly, so the
                # dynamic-slice start can never clamp)
                assert self.n_pad % self.proc_pad == 0
                p = jax.process_index() * self.proc_pad
                v = lax.dynamic_slice_in_dim(v, p, self.proc_pad, axis=0)
            pad = self.proc_pad - v.shape[0]
            if pad:
                v = jnp.pad(v, [(0, pad)] + [(0, 0)] * (v.ndim - 1))
            gshape = (self.n_pad,) + v.shape[1:]
            sharding = NamedSharding(self.mesh,
                                     spec("row_mask", ndim=v.ndim))
            p0 = jax.process_index() * self.proc_pad
            blocks = []
            for d, idx in sharding.addressable_devices_indices_map(
                    gshape).items():
                lo = (idx[0].start or 0) - p0
                blocks.append(jax.device_put(v[lo:lo + self.n_loc], d))
            return jax.make_array_from_single_device_arrays(
                gshape, sharding, blocks)
        return shard_rows(self.mesh, v)[0]

    def _check_shard_agreement(self, rec: DeviceTree) -> None:
        """LAMBDAGAP_DEBUG cross-shard divergence check. The tree record is
        nominally replicated — every shard derives it from identically
        psum-ed histograms — but ``check_vma=False`` on the shard_map means
        the static checker never proves it: a dropped psum on a new code
        path would silently corrupt training. Here each device's copy of
        the per-split decisions is compared bit-for-bit (the runtime analog
        of the reference's SyncUpGlobalBestSplit all-reduce agreeing on one
        winner, src/treelearner/parallel_tree_learner.h:209)."""
        from ..utils import log
        for name in ("node_feature", "node_threshold", "node_gain",
                     "leaf_value", "num_leaves"):
            arr = getattr(rec, name)
            shards = getattr(arr, "addressable_shards", None)
            if not shards:
                continue
            ref = np.asarray(shards[0].data)
            for s in shards[1:]:
                got = np.asarray(s.data)
                if not np.array_equal(ref, got, equal_nan=True):
                    bad = np.nonzero(ref != got)[0][:8] if ref.ndim else []
                    log.fatal(
                        "cross-shard divergence in %s on device %s "
                        "(first diverging indices %s): shards disagreed on "
                        "the split sequence — a collective is missing from "
                        "the fused program", name, s.device, list(bad))

    def train_device(self, grad: jax.Array, hess: jax.Array,
                     row_mask: Optional[jax.Array] = None) -> DeviceTree:
        fmask = self._feature_mask()
        g = self._shard_vec(grad)
        h = self._shard_vec(hess)
        if row_mask is None:
            m = self.real_mask
        elif self.proc_sharded:
            m = self._shard_vec(row_mask) & self.real_mask
        else:
            # in-bag + pad-row masks combine in shard_rows' mask channel
            m = shard_rows(self.mesh, row_mask, mask=row_mask)[1]
        if self.quant:
            from ..ops.hist_pallas import quantize_gradients
            self._qkey, sub = jax.random.split(self._qkey)
            gmax = hmax = None
            if self.proc_sharded and jax.process_count() > 1:
                # every rank holds different rows: agree on GLOBAL |grad| /
                # hess maxima before deriving quantization scales, else the
                # psum-ed int32 histograms would mix incompatible units
                from jax.experimental import multihost_utils
                # graftlint: disable=R1 — one cross-host max sync per TREE
                # (not per split); quantization scales must agree globally
                lm = np.asarray(
                    [float(jnp.max(jnp.abs(grad))), float(jnp.max(hess))],
                    np.float32)
                gm = np.asarray(multihost_utils.process_allgather(
                    lm)).reshape(-1, 2).max(axis=0)
                gmax = jnp.float32(max(float(gm[0]), 1e-12))
                hmax = jnp.float32(max(float(gm[1]), 1e-12))
            gq, hq, gs, hs = quantize_gradients(
                grad, hess, sub, self.config.num_grad_quant_bins,
                self.config.stochastic_rounding, gmax=gmax, hmax=hmax)
            gq, hq = self._shard_vec(gq), self._shard_vec(hq)
        else:
            gq = hq = jnp.zeros(1, jnp.int8)
            gs = hs = jnp.float32(1.0)
        if self._need_step_keys:
            self._ekey, e = jax.random.split(self._ekey)
            self._bkey, b = jax.random.split(self._bkey)
            ekey = jnp.stack([e, b])            # [2, 2]: extra / by-node
        else:
            ekey = jnp.zeros((2, 2), jnp.uint32)
        if self.layout == "sorted":
            with self.telemetry.phase("layout_apply"):
                srows = self._layout_jit_dp(g, h, m, self.hx_rows, gq, hq)
        else:
            srows = self._srows_dummy
        rec = self._train_jit_dp(g, h, m, fmask, self.hx_rows, self.x_cols,
                                 srows, gq, hq, gs, hs, ekey)
        if _DEBUG_CHECKS:
            self._check_shard_agreement(rec)
        # consumers (score update, leaf renewal) see an unpadded [N] leaf map
        if self.proc_sharded:
            # hand back this process's LOCAL rows: the booster's score
            # update stays rank-local (one D2H per tree, not per split).
            # leaf_value is localized too (replicated global -> this
            # process's copy) so downstream boosting state never becomes a
            # cross-process array.
            from .multiprocess import local_block
            rec = rec._replace(
                row_leaf=jnp.asarray(local_block(rec.row_leaf,
                                                 self.num_data)),
                leaf_value=jnp.asarray(rec.leaf_value.addressable_data(0)))
        else:
            rec = rec._replace(row_leaf=rec.row_leaf[:self.num_data])
        self.last_row_leaf = rec.row_leaf
        return rec


class FusedFeatureParallelTreeLearner(FusedTreeLearner):
    """Feature-parallel as ONE compiled whole-tree program (reference:
    src/treelearner/feature_parallel_tree_learner.cpp — every rank holds
    all rows, features are partitioned for histogram work, local best
    splits merge via SyncUpGlobalBestSplit, parallel_tree_learner.h:209):
    rows stay replicated, the binned matrix is sharded along the COLUMN
    axis, histograms and scans are shard-local, and the only per-split
    traffic is one all_gather of the D per-shard best-split tuples plus a
    psum broadcast of the winning feature's column for the partition —
    zero per-split host syncs (the host-loop variant in
    feature_parallel.py pays a D2H per split; this one does not)."""

    # the winning split's column lives on ONE shard and is psum-broadcast
    # for the (row-replicated) partition; the sorted layout's
    # decode-from-window shortcut cannot express that, so this learner
    # explicitly opts out and keeps the gather layout
    supports_sorted_layout = False
    supports_stream = False

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        from ..utils import log
        if config.enable_bundle:
            # EFB bundles are columns; feature ownership under a bundled
            # shard would decouple from feature ids. Keep ownership trivial
            # (feat // C_loc) — the config copy avoids mutating the caller
            import copy
            config = copy.copy(config)
            config.enable_bundle = False
            log.info("EFB bundling is disabled under the fused "
                     "feature-parallel learner (column ownership must "
                     "follow feature ids)")
        self.mesh = mesh if mesh is not None else make_mesh(
            config.tpu_num_devices, mesh_shape=config.mesh_shape,
            shard_axis=FEATURE_AXIS)
        if int(self.mesh.shape.get(DATA_AXIS, 1)) > 1:
            log.fatal("the fused feature-parallel learner shards columns; "
                      "mesh_shape=%s places devices on the data axis",
                      config.mesh_shape)
        self.n_dev = int(self.mesh.shape[FEATURE_AXIS])
        super().__init__(dataset, config)
        if self.forced_seq is not None:
            # unreachable via the factory (gbdt._create_learner routes
            # forced-splits configs to the fused data-parallel learner)
            log.fatal("forced splits are not supported by the fused "
                      "feature-parallel learner; use tree_learner=data")
        self.feat_axis = FEATURE_AXIS
        # pad the per-feature meta arrays to the sharded width so the
        # per-shard dynamic slices stay in range; padded features can
        # never win (fmask False, 2-bin histograms of zeros)
        Fp = self._Fp
        pad = Fp - self.num_features
        if pad:
            self._real_F = self.num_features
            self.num_features = Fp
            z = lambda a, v: jnp.concatenate(
                [a, jnp.full((pad,), v, a.dtype)])
            self.num_bins_arr = z(self.num_bins_arr, 2)
            self.default_bins_arr = z(self.default_bins_arr, 0)
            self.missing_types_arr = z(self.missing_types_arr, 0)
            self.is_categorical_arr = z(self.is_categorical_arr, False)
            self.mono_arr = z(self.mono_arr, 0)
            self.nb_minus1_arr = z(self.nb_minus1_arr, 1)
            if self.contri_arr is not None:
                self.contri_arr = z(self.contri_arr, 1.0)
        else:
            self._real_F = self.num_features

        def sharded(grad, hess, mask, fmask, xr, xc, srows, gq, hq, gs, hs,
                    ekey, *, has_mask):
            body = functools.partial(self._train_tree_impl,
                                     has_mask=has_mask)
            # the SAME registry rules as the data-parallel program: on this
            # (1, D) feature placement the per-row specs' data axis has
            # extent 1 (rows replicated) while x_rows/x_cols shard columns
            return shard_map(
                body, mesh=self.mesh,
                in_specs=specs("grad", "hess", "row_mask", "fmask",
                               "x_rows", "x_cols", "rep", "gq", "hq",
                               "scalar", "scalar", "ekey"),
                out_specs=DeviceTree(
                    *([spec("tree")] * len(DeviceTree._fields))),
                check_vma=False)(grad, hess, mask, fmask, xr, xc, srows,
                                 gq, hq, gs, hs, ekey)

        self._train_jit = jax.jit(sharded, static_argnames=("has_mask",))

    def _place_binned(self, hx: np.ndarray) -> None:
        C = hx.shape[1]
        pad = (-C) % self.n_dev
        if pad:
            hx = np.pad(hx, ((0, 0), (0, pad)))
        self._Fp = C + pad
        self.hx_rows = jax.device_put(
            jnp.asarray(hx), NamedSharding(self.mesh, spec("x_rows")))
        self.x_cols = jax.device_put(
            jnp.asarray(np.ascontiguousarray(hx.T)),
            NamedSharding(self.mesh, spec("x_cols")))

    def _feature_mask(self) -> jax.Array:
        # sample over the REAL features only (num_features is the padded
        # program width), then pad False so pad columns can never win
        saved = self.num_features
        self.num_features = self._real_F
        try:
            m = super()._feature_mask()
        finally:
            self.num_features = saved
        pad = self.num_features - m.shape[0]
        if pad > 0:
            m = jnp.concatenate([m, jnp.zeros(pad, dtype=bool)])
        return m


class FusedVotingParallelTreeLearner(FusedDataParallelTreeLearner):
    """Voting-parallel as ONE compiled whole-tree program (reference:
    src/treelearner/voting_parallel_tree_learner.cpp — GlobalVoting :151-175
    + CopyLocalHistogram/Allreduce :184): histograms stay shard-local, each
    split step all_gathers the shards' top-k feature votes and psums only
    the voted columns — O(D·top_k·B) bytes per split instead of O(F·B) —
    with zero per-split host syncs (the host-loop variant in
    voting_parallel.py pays a D2H per split; this one does not)."""

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        from ..utils import log
        super().__init__(dataset, config, mesh)
        if self.forced_seq is not None:
            # unreachable via the factory (gbdt._create_learner routes
            # forced-splits configs to the fused data-parallel learner);
            # guards direct construction
            log.fatal("forced splits need global histograms, which voting "
                      "keeps local; use the fused data-parallel learner")
        self.voting = True
        self.vote_k = max(1, min(int(config.top_k), self.num_features))
        if self.quant and self.quant_exact:
            # voting stores RAW integer level sums in the float32 per-leaf
            # histogram state until the voted-column psum (the full-histogram
            # paths scale immediately after their psum), so exactness is
            # bounded by the f32 integer range, not the int32 accumulator —
            # i.e. the one-hot limit regardless of the configured kernel
            from ..ops.hist_pallas import exact_accum_limit
            qb = config.num_grad_quant_bins
            self.quant_exact = (dataset.num_data * qb
                                < exact_accum_limit("onehot"))
            if not self.quant_exact:
                log.warning("quantized voting-parallel level sums may exceed "
                            "the float32-exact range (%d rows x %d levels); "
                            "using per-chunk scaled float32 accumulation",
                            dataset.num_data, qb)


class Fused2DTreeLearner(FusedTreeLearner):
    """The fused 2-D ``data x feature`` program (ISSUE 15): rows shard
    over the ``data`` mesh axis AND histogram columns shard over the
    ``feature`` axis, in ONE compiled whole-tree program.

    Per split the collectives are exactly the registry's decomposition:

    - one histogram ``psum`` over ``data`` — each device accumulates its
      row block's partial histogram for its column block; the psum
      completes every column block (reference: the ReduceScatter +
      HistogramSumReducer of data_parallel_tree_learner.cpp:283-298);
    - one ``all_gather`` over ``feature`` of the per-shard best-split
      tuples + a replicated argmax — the voting-parallel hybrid's
      SyncUpGlobalBestSplit (parallel_tree_learner.h:209);
    - one ``psum`` broadcast over ``feature`` of the winning feature's
      (row-sharded) column for the shard-local partition.

    Every array spec comes from parallel/sharding.py RULES — the same
    rules the 1-D learners run at degenerate geometries; this class is
    the registry's ``(dd, ff)`` consumer, so ``make_mesh`` no longer
    gates ``dd>1 && ff>1``. Selected by an explicit 2-D ``mesh_shape``
    ("4x2", "1x8", ...) — degenerate grids (dd=1 or ff=1) run the same
    program, which is what makes the bench's grid sweep one learner.

    ``data_residency=stream`` COMPOSES with the mesh (the stream x
    distributed cell flips from loud demotion to supported): per-host
    ``ShardedBinnedDataset`` shards feed the ShardRing with
    mesh-sharded ``device_put`` (one put lands each data block's window
    slice on its own device), and the per-tree build is the host-driven
    loop of small shard_map kernels in ``_train_tree_stream2d`` — the
    same kernels-as-the-fused-program mirror contract as the serial
    stream mode, so streamed 2-D trees are bit-identical to resident
    2-D trees on the same grid.
    """

    # the winning column reaches the partition via the feature-axis psum
    # broadcast; the sorted layout's decode-from-window shortcut cannot
    # express a column another shard owns
    supports_sorted_layout = False
    supports_stream = True

    def __init__(self, dataset: BinnedDataset, config: Config,
                 mesh: Optional[Mesh] = None) -> None:
        if config.enable_bundle:
            # EFB bundles are columns; ownership under a bundled shard
            # would decouple from feature ids (the fused feature-parallel
            # precedent). The config copy avoids mutating the caller.
            import copy
            config = copy.copy(config)
            config.enable_bundle = False
            log.info("EFB bundling is disabled under the fused 2-D "
                     "learner (column ownership must follow feature ids)")
        self.mesh = mesh if mesh is not None else make_mesh(
            config.tpu_num_devices, mesh_shape=config.mesh_shape)
        self.dd = int(self.mesh.shape[DATA_AXIS])
        self.ff = int(self.mesh.shape[FEATURE_AXIS])
        self.n_dev = self.dd * self.ff
        N = dataset.num_data
        self.n_pad = N + ((-N) % self.dd)
        self.n_loc = self.n_pad // self.dd
        super().__init__(dataset, config)
        if self.forced_seq is not None:
            log.fatal("forced splits need the full histogram of the "
                      "forced leaf on every shard; the 2-D mesh shards "
                      "histogram columns — use mesh_shape=%dx1",
                      self.n_dev)
        self.axis = DATA_AXIS
        self.feat_axis = FEATURE_AXIS
        # pad the per-feature metadata to the column-sharded width Fp so
        # per-shard dynamic slices stay in range; pad columns can never
        # win (fmask False, 2-bin histograms of zeros) — the fused
        # feature-parallel recipe
        if self.residency == "stream":
            C = self.num_features
            self._Fp = C + ((-C) % self.ff)
        Fp = self._Fp
        pad = Fp - self.num_features
        self._real_F = self.num_features
        if pad:
            self.num_features = Fp
            z = lambda a, v: jnp.concatenate(
                [a, jnp.full((pad,), v, a.dtype)])
            self.num_bins_arr = z(self.num_bins_arr, 2)
            self.default_bins_arr = z(self.default_bins_arr, 0)
            self.missing_types_arr = z(self.missing_types_arr, 0)
            self.is_categorical_arr = z(self.is_categorical_arr, False)
            self.mono_arr = z(self.mono_arr, 0)
            self.nb_minus1_arr = z(self.nb_minus1_arr, 1)
            if self.contri_arr is not None:
                self.contri_arr = z(self.contri_arr, 1.0)
        # pad-row mask (False pads -> exact-zero histogram contributions)
        real = np.zeros(self.n_pad, dtype=bool)
        real[:N] = True
        self.real_mask = jax.device_put(
            jnp.asarray(real), NamedSharding(self.mesh, spec("row_mask")))
        if self.residency == "stream":
            self._stream2d_setup()
            return

        body = functools.partial(self._train_tree_impl, has_mask=True)
        qspec = spec("gq") if self.quant else spec("rep")
        in_specs = specs("grad", "hess", "row_mask", "fmask", "x_rows",
                         "x_cols") + (spec("rep"), qspec, qspec) \
            + specs("scalar", "scalar", "ekey")
        out_specs = DeviceTree(**{
            f: spec("row_leaf") if f == "row_leaf" else spec("tree")
            for f in DeviceTree._fields})
        self._train_jit_2d = jax.jit(shard_map(
            body, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False))

    # -- device-layout hooks -------------------------------------------
    def _place_binned(self, hx: np.ndarray) -> None:
        rpad = self.n_pad - hx.shape[0]
        C = hx.shape[1]
        cpad = (-C) % self.ff
        self._Fp = C + cpad
        if rpad or cpad:
            hx = np.pad(hx, ((0, rpad), (0, cpad)))
        self.hx_rows = jax.device_put(
            jnp.asarray(hx), NamedSharding(self.mesh, spec("x_rows")))
        self.x_cols = jax.device_put(
            jnp.asarray(np.ascontiguousarray(hx.T)),
            NamedSharding(self.mesh, spec("x_cols")))

    def _pick_chunk(self) -> int:
        # sized off LOCAL rows (the fused data-parallel rationale at
        # fused_parallel.py FusedDataParallelTreeLearner._pick_chunk);
        # stream and hbm residencies MUST agree on W per grid — it is the
        # accumulation-order contract the stream mirror replays
        forced = self._chunk_override()
        if forced is not None:
            return forced
        cap = max(int(self.config.tpu_rows_per_block) * 16, 1 << 12)
        per_leaf = self.n_loc // max(self.config.num_leaves, 8)
        return min(max(_next_pow2(max(per_leaf // 2, 1)), 1 << 10), cap)

    def _feature_mask(self) -> jax.Array:
        # sample over the REAL features only, pad False (pad columns can
        # never win)
        saved = self.num_features
        self.num_features = self._real_F
        try:
            m = super()._feature_mask()
        finally:
            self.num_features = saved
        pad = self.num_features - m.shape[0]
        if pad > 0:
            m = jnp.concatenate([m, jnp.zeros(pad, dtype=bool)])
        return m

    def _shard_vec(self, v: jax.Array) -> jax.Array:
        pad = self.n_pad - v.shape[0]
        if pad:
            v = jnp.pad(v, [(0, pad)] + [(0, 0)] * (v.ndim - 1))
        return jax.device_put(
            v, NamedSharding(self.mesh, spec("row_mask", ndim=v.ndim)))

    # ------------------------------------------------------------------
    def train_device(self, grad: jax.Array, hess: jax.Array,
                     row_mask: Optional[jax.Array] = None) -> DeviceTree:
        if self.residency == "stream":
            rec = self._train_tree_stream2d(grad, hess, row_mask)
            self.last_row_leaf = rec.row_leaf
            return rec
        fmask = self._feature_mask()
        if row_mask is None:
            m = self.real_mask
        else:
            m = self._shard_vec(row_mask) & self.real_mask
        if self.quant:
            from ..ops.hist_pallas import quantize_gradients
            self._qkey, sub = jax.random.split(self._qkey)
            gq, hq, gs, hs = quantize_gradients(
                grad, hess, sub, self.config.num_grad_quant_bins,
                self.config.stochastic_rounding)
            gq, hq = self._shard_vec(gq), self._shard_vec(hq)
        else:
            gq = hq = jnp.zeros(1, jnp.int8)
            gs = hs = jnp.float32(1.0)
        if self._need_step_keys:
            self._ekey, e = jax.random.split(self._ekey)
            self._bkey, b = jax.random.split(self._bkey)
            ekey = jnp.stack([e, b])
        else:
            ekey = jnp.zeros((2, 2), jnp.uint32)
        g = self._shard_vec(grad)
        h = self._shard_vec(hess)
        from ..obs import costplane
        rec = costplane.observed_call(
            "train.fused2d", self._train_jit_2d,
            (g, h, m, fmask, self.hx_rows, self.x_cols,
             self._srows_dummy, gq, hq, gs, hs, ekey),
            bucket=int(g.shape[0]), phase="tree",
            shard_spec=",".join(f"{a}={self.mesh.shape[a]}"
                                for a in self.mesh.axis_names))
        rec = rec._replace(row_leaf=rec.row_leaf[:self.num_data])
        self.last_row_leaf = rec.row_leaf
        return rec

    # ------------------------------------------------------------------
    # data_residency=stream x 2-D mesh: the composed out-of-core path
    # ------------------------------------------------------------------
    # The binned matrix lives in host shards (ShardedBinnedDataset); the
    # devices keep only O(N)-scalar per-row state, sharded over ``data``.
    # Each tree is the host-driven loop of small shard_map kernels whose
    # traced math replicates the fused 2-D program's split step
    # op-for-op (the serial stream mode's mirror contract, composed with
    # the mesh): per-device window accumulation in the resident W-chunk
    # order, ONE psum over ``data`` per histogram, the feature-sharded
    # scan + all_gather of _s2_best_of, and per-data-shard partitions
    # whose go_left flags keep the per-shard host permutation mirrors in
    # lockstep. Row windows reach the devices through the ShardRing with
    # mesh shardings: one ``put`` lands every data block's slice on its
    # own device (the per-host H2D ring of ROADMAP item 1), under the
    # usual h2d_prefetch/chunk_wait phases; GOSS/bagging masks compact
    # each block's transfer independently.

    def _stream2d_setup(self) -> None:
        self._W2 = self._window(self.n_loc)
        self._bins_dtype = self.sdata.shards[0].dtype
        mesh = self.mesh
        self._ring_shardings = (
            NamedSharding(mesh, spec("win_bins")),
            NamedSharding(mesh, spec("win_cvals", ndim=2)))
        self._cvals_sharding = NamedSharding(mesh, spec("win_cvals",
                                                        ndim=2))
        self._acc_sharding = NamedSharding(mesh, spec("hist_grid"))
        self._vec_sharding = NamedSharding(mesh, spec("count"))
        base = np.concatenate([np.arange(self.n_loc, dtype=np.int32),
                               np.zeros(self._W2, np.int32)])
        self._perm0_2d = jax.device_put(
            jnp.asarray(np.tile(base, self.dd)),
            NamedSharding(mesh, spec("perm")))

    def _init_stream_jits(self) -> None:
        # called from the base stream early-return; the mesh is already
        # set (Fused2DTreeLearner.__init__ builds it before super())
        mesh = self.mesh
        st = dict(perm=spec("perm"), perm_buf=spec("perm"),
                  leaf_f=spec("rep"), leaf_i=spec("leaf_local", ndim=3),
                  leaf_bits=spec("rep"), node_f=spec("rep"),
                  node_i=spec("rep"), node_bits=spec("rep"),
                  hist=spec("hist_state", ndim=4), num_leaves=spec("rep"))
        R = spec("rep")
        grid = spec("hist_grid", ndim=4)
        bins = spec("win_bins", ndim=3)
        vec = spec("count")
        sm = functools.partial(shard_map, mesh=mesh, check_vma=False)
        self._sj2_chunk_full = jax.jit(sm(
            functools.partial(self._s2_chunk_body, compacted=False),
            in_specs=(grid, bins) + specs("perm", "grad", "hess",
                                          "row_mask")
            + (vec, R, vec),
            out_specs=grid))
        self._sj2_chunk_compact = jax.jit(sm(
            functools.partial(self._s2_chunk_body, compacted=True),
            in_specs=(grid, bins, spec("win_pos", ndim=2))
            + specs("perm", "grad", "hess", "row_mask") + (vec, R, vec),
            out_specs=grid))
        self._sj2_init = jax.jit(sm(
            self._s2_init_body, in_specs=(grid, spec("fmask")),
            out_specs=st))
        self._sj2_pick = jax.jit(sm(
            self._s2_pick_body, in_specs=(st,),
            out_specs=(R, R, R, R, R, spec("begin"), spec("count"))))
        self._sj2_part = jax.jit(sm(
            self._s2_part_body,
            in_specs=(st, spec("win_cvals", ndim=2)),
            out_specs=(st, spec("win_lanes", ndim=2), spec("count"))))
        self._sj2_finish = jax.jit(sm(
            self._s2_finish_body,
            in_specs=(st, grid, vec, spec("fmask")), out_specs=st))
        self._sj2_final = jax.jit(sm(
            self._s2_final_body, in_specs=(st,),
            out_specs=DeviceTree(**{
                f: spec("row_leaf") if f == "row_leaf" else spec("tree")
                for f in DeviceTree._fields})))

    # -- per-device kernel bodies (local views inside shard_map) --------
    def _s2_best_of(self, hist, pg, ph, pc, pout, depth, fm):
        """Feature-sharded best split of the 2-D program restricted to
        the stream option subset (no voting/extra/monotone/contri/
        bundle) — the surviving ops replicate ``best_of_feat`` verbatim
        so gains, tie-breaks and outputs match the resident 2-D program
        bit-for-bit."""
        p = self.params
        C_loc = hist.shape[0]
        off = lax.axis_index(FEATURE_AXIS) * C_loc

        def sl(arr):
            # shards tile the padded feature axis exactly: no clamp
            assert arr.shape[0] % C_loc == 0
            return lax.dynamic_slice_in_dim(arr, off, C_loc, axis=0)

        gain, thr, dl, lg, lh, lc, bits = per_feature_best(
            hist, pg, ph, pc, pout, sl(self.num_bins_arr),
            sl(self.default_bins_arr), sl(self.missing_types_arr),
            sl(self.is_categorical_arr), sl(fm), p, self.has_categorical,
            constraints=None, rand_thresholds=None)
        parent_gain = leaf_gain(pg, ph, p, pc, pout)
        shift = parent_gain + p.min_gain_to_split
        fl = jnp.argmax(gain, axis=0).astype(jnp.int32)
        lout_l = calculate_leaf_output(lg[fl], lh[fl], p, lc[fl], pout)
        rout_l = calculate_leaf_output(pg - lg[fl], ph - lh[fl], p,
                                       pc - lc[fl], pout)
        fields = (gain[fl], off + fl, thr[fl], dl[fl].astype(jnp.int32),
                  sl(self.is_categorical_arr)[fl].astype(jnp.int32),
                  bits[fl], lg[fl], lh[fl], lc[fl], lout_l, rout_l)
        gathered = [lax.all_gather(x, FEATURE_AXIS) for x in fields]
        win = jnp.argmax(gathered[0], axis=0).astype(jnp.int32)
        gw = gathered[0][win]
        g = gw - shift
        ok = jnp.isfinite(gw) & (g > 0.0)
        if self.config.max_depth > 0:
            ok = ok & (depth < self.config.max_depth)
        return (jnp.where(ok, g, K_MIN_SCORE), gathered[1][win],
                gathered[2][win], gathered[3][win].astype(bool),
                gathered[4][win].astype(bool), gathered[5][win],
                gathered[6][win], gathered[7][win], gathered[8][win],
                gathered[9][win], gathered[10][win])

    def _s2_chunk_body(self, acc, bins_up, *args, compacted: bool):
        """One window's histogram contribution per device: the uploaded
        bins block (optionally compacted to in-bag lanes) against the
        device-resident gradient channels — same kernels, same values,
        same ``acc + part`` order as the resident program's chunk_hist.
        Shards whose trip count ended (done >= count) leave their
        accumulator bit-untouched, exactly like the resident per-shard
        while_loop that never runs those trips."""
        if compacted:
            pos, perm, grad, hess, mask, begin, done, count = args
        else:
            perm, grad, hess, mask, begin, done, count = args
            pos = None
        from ..ops.histogram import gh_contract
        W = self._W2
        C_loc = acc.shape[1]
        lane = jnp.arange(W, dtype=jnp.int32)
        b = bins_up[0]
        if pos is not None:
            # re-expand the compacted transfer into its window lanes:
            # out-of-bag lanes keep zero bins — their gh channels are
            # exactly 0.0 below, so each contributes the same exact +0.0
            # the resident program adds for masked rows
            bins = jnp.zeros((W, C_loc), b.dtype).at[pos[0]].set(
                b, mode="drop")
        else:
            bins = b
        begin_s = begin[0]
        count_s = count[0]
        # same pad invariant as the resident perm windows: begin + done
        # <= begin + count <= n_loc and perm carries W tail pad rows
        assert perm.shape[0] == self.n_loc + W
        valid = (done + lane) < count_s
        rows = lax.dynamic_slice(perm, (begin_s + done,), (W,))
        g = grad[rows]
        h = hess[rows]
        valid = valid & mask[rows]
        if self.hist_impl == "pallas":
            from ..ops.hist_pallas import hist_pallas, pack_gh8
            live = jnp.clip(count_s - done, 0, W)
            gh8 = pack_gh8(g, h, valid)
            part = hist_pallas(bins, gh8, self.Bb, live)
        else:
            g0 = jnp.where(valid, g, 0.0)
            h0 = jnp.where(valid, h, 0.0)
            gh = jnp.stack([g0, h0, valid.astype(jnp.float32)], axis=1)
            bin_iota = jnp.arange(self.Bb, dtype=bins.dtype)
            onehot = (bins[:, :, None] == bin_iota).astype(jnp.bfloat16)
            part = gh_contract(gh, onehot.reshape(W, C_loc * self.Bb),
                               self.hist_precision)
            part = part.reshape(HIST_C, C_loc, self.Bb).transpose(1, 2, 0)
        return jnp.where(done < count_s, acc[0] + part, acc[0])[None]

    def _s2_init_body(self, acc, fmask):
        """State init of the 2-D program: ONE psum over ``data``
        completes every column block's root histogram, shard 0's totals
        broadcast over ``feature`` (the resident program's aggregate
        contract), feature-sharded root best split."""
        cfg = self.config
        N = self.n_loc
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        W = self._W2
        p = self.params
        f32, i32 = jnp.float32, jnp.int32
        hist_root = lax.psum(acc[0], DATA_AXIS)
        totals = jnp.sum(hist_root[0], axis=0)
        fidx = lax.axis_index(FEATURE_AXIS)
        totals = lax.psum(jnp.where(fidx == 0, totals,
                                    jnp.zeros_like(totals)), FEATURE_AXIS)
        root_out = calculate_leaf_output(totals[0], totals[1], p,
                                         totals[2], 0.0)
        neg_inf = jnp.float32(-jnp.inf)
        pos_inf = jnp.float32(jnp.inf)
        (bg0, bf0, bt0, bdl0, bcat0, bbits0, blg0, blh0, blc0, blout0,
         brout0) = self._s2_best_of(hist_root, totals[0], totals[1],
                                    totals[2], root_out, i32(0), fmask)
        iota_l1 = jnp.arange(L + 1, dtype=i32)
        leaf_f = jnp.zeros((L + 1, 12), f32)
        leaf_f = leaf_f.at[:, 4].set(K_MIN_SCORE) \
                       .at[:, 10].set(-jnp.inf).at[:, 11].set(jnp.inf)
        leaf_f = leaf_f.at[0].set(jnp.stack(
            [totals[0], totals[1], totals[2], root_out, bg0, blg0, blh0,
             blc0, blout0, brout0, neg_inf, pos_inf]))
        leaf_i = jnp.zeros((L + 1, 9), i32)
        leaf_i = leaf_i.at[:, 0].set(N + iota_l1).at[:, 3].set(-1)
        leaf_i = leaf_i.at[0].set(jnp.stack(
            [i32(0), i32(N), i32(0), i32(-1), i32(0), bf0, bt0,
             bdl0.astype(i32), bcat0.astype(i32)]))
        return dict(
            perm=jnp.concatenate([jnp.arange(N, dtype=i32),
                                  jnp.zeros(W, i32)]),
            perm_buf=jnp.zeros(N + W, i32),
            leaf_f=leaf_f, leaf_i=leaf_i[None],
            leaf_bits=jnp.zeros((L + 1, 8), jnp.uint32).at[0].set(bbits0),
            node_f=jnp.zeros((NODES + 1, 4), f32),
            node_i=jnp.zeros((NODES + 1, 6), i32).at[:, 4:6].set(~0),
            node_bits=jnp.zeros((NODES + 1, 8), jnp.uint32),
            hist=jnp.zeros((L + 1, hist_root.shape[0], self.Bb, HIST_C),
                           f32).at[0].set(hist_root),
            num_leaves=jnp.int32(1),
        )

    def _s2_pick_body(self, state):
        """The pending split (replicated) plus every data shard's local
        begin/count — the one D2H the host loop pays per split."""
        L = self.config.num_leaves
        leaf_f = state["leaf_f"]
        leaf = jnp.argmax(leaf_f[:L, 4]).astype(jnp.int32)
        lf = leaf_f[leaf]
        li = state["leaf_i"][0, leaf]
        ok = lf[4] > 0.0
        return (leaf, ok, li[5], lf[7], lf[2],
                li[0][None], jnp.where(ok, li[1], 0)[None])

    def _s2_part_body(self, state, cvals):
        """pbody + cbody of the fused split step per data shard, with
        the split feature's bin values arriving as the uploaded per-block
        ``cvals`` rows. Returns the per-lane go_left flags and the local
        left count so the host mirrors the two-monotone-run placement
        onto each shard's permutation mirror."""
        from ..ops.partition import decision_go_left
        N = self.n_loc
        W = self._W2
        PV = cvals.shape[1]
        assert state["perm"].shape[0] == N + W
        assert state["perm_buf"].shape[0] == N + W
        assert PV % W == 0 and PV >= W
        lane = jnp.arange(W, dtype=jnp.int32)
        i32 = jnp.int32
        L = self.config.num_leaves
        leaf = jnp.argmax(state["leaf_f"][:L, 4]).astype(i32)
        lf = state["leaf_f"][leaf]
        li = state["leaf_i"][0, leaf]
        ok = lf[4] > 0.0
        feat = li[5]
        thrv, dlv, catv = li[6], li[7].astype(bool), li[8].astype(bool)
        bitsv = state["leaf_bits"][leaf]
        begin = li[0]
        count_eff = jnp.where(ok, li[1], 0)
        nch = (count_eff + W - 1) // W
        perm_in = state["perm"]
        cv_flat = cvals[0]

        def pbody(s):
            c, lcur, rcur, pbuf, gbuf = s
            live = jnp.clip(count_eff - c * W, 0, W)
            valid = lane < live
            rows = lax.dynamic_slice(perm_in, (begin + c * W,), (W,))
            cv = lax.dynamic_slice(cv_flat, (c * W,), (W,)).astype(i32)
            gl = decision_go_left(
                cv, thrv, dlv, self.default_bins_arr[feat],
                self.missing_types_arr[feat], self.num_bins_arr[feat],
                catv, bitsv) & valid
            cums = jnp.cumsum(gl.astype(i32))
            nl = cums[W - 1]
            prefix_valid = jnp.minimum(lane + 1, live)
            lpos = lcur + cums - 1
            rpos = rcur - (prefix_valid - cums)
            pos = jnp.where(gl, lpos, jnp.where(valid, rpos, N))
            pbuf = pbuf.at[pos].set(rows, mode="drop")
            gbuf = lax.dynamic_update_slice(gbuf, gl, (c * W,))
            return c + 1, lcur + nl, rcur - (live - nl), pbuf, gbuf

        _, lend, _, pbuf, gbuf = lax.while_loop(
            lambda s: s[0] < nch, pbody,
            (i32(0), begin, begin + count_eff, state["perm_buf"],
             jnp.zeros(PV, bool)))
        left_count = lend - begin

        def cbody(s):
            c, pm = s
            start = begin + c * W
            valid = (c * W + lane) < count_eff
            vals = jnp.where(valid,
                             lax.dynamic_slice(pbuf, (start,), (W,)),
                             lax.dynamic_slice(pm, (start,), (W,)))
            return c + 1, lax.dynamic_update_slice(pm, vals, (start,))

        _, perm = lax.while_loop(lambda s: s[0] < nch, cbody,
                                 (i32(0), perm_in))
        new_state = dict(state)
        new_state["perm"] = perm
        new_state["perm_buf"] = pbuf
        return new_state, gbuf[None], left_count[None]

    def _s2_finish_body(self, state, acc, left_counts, fmask):
        """The tail of the fused 2-D split step: the one histogram psum
        over ``data``, parent pointers, subtraction trick with the
        GLOBAL smaller-side choice, both children's feature-sharded
        scans, consolidated state writes."""
        cfg = self.config
        F = self.num_features
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        i32 = jnp.int32
        hist_small = lax.psum(acc[0], DATA_AXIS)
        leaf_f = state["leaf_f"]
        leaf_i_l = state["leaf_i"][0]
        leaf_bits = state["leaf_bits"]
        leaf = jnp.argmax(leaf_f[:L, 4]).astype(i32)
        lf = leaf_f[leaf]
        li = leaf_i_l[leaf]
        ok = lf[4] > 0.0
        bgain = lf[4]
        feat = li[5]
        thrv, dlv, catv = li[6], li[7].astype(bool), li[8].astype(bool)
        bitsv = leaf_bits[leaf]
        blg, blh, blc = lf[5], lf[6], lf[7]
        blout, brout = lf[8], lf[9]
        begin = li[0]
        count_eff = jnp.where(ok, li[1], 0)
        left_count = left_counts[0]
        right_count = count_eff - left_count

        new_leaf = state["num_leaves"]
        nidx = new_leaf - 1
        wl = jnp.where(ok, leaf, L)
        wn = jnp.where(ok, new_leaf, L)
        wk = jnp.where(ok, nidx, NODES)

        pnode = li[3]
        was_left = li[4].astype(bool)
        safe_p = jnp.where((pnode >= 0) & ok, pnode, NODES)
        prow = state["node_i"][safe_p]
        prow = jnp.where(was_left, prow.at[4].set(nidx),
                         prow.at[5].set(nidx))
        node_i = state["node_i"].at[safe_p].set(prow)

        pg, ph, pc = lf[0], lf[1], lf[2]
        lg, lh, lc = blg, blh, blc
        rg, rh, rc = pg - lg, ph - lh, pc - lc
        lout, rout = blout, brout
        depth = li[2] + 1

        pmin, pmax = lf[10], lf[11]
        mono_f = self.mono_arr[feat]
        lcap = rcap = (lout + rout) * 0.5
        lmin = jnp.where(mono_f < 0, jnp.maximum(pmin, lcap), pmin)
        lmax = jnp.where(mono_f > 0, jnp.minimum(pmax, lcap), pmax)
        rmin = jnp.where(mono_f > 0, jnp.maximum(pmin, rcap), pmin)
        rmax = jnp.where(mono_f < 0, jnp.minimum(pmax, rcap), pmax)

        node_f = state["node_f"].at[wk].set(
            jnp.stack([bgain, lf[3], ph, pc]))
        node_i = node_i.at[wk].set(jnp.stack(
            [feat, thrv, dlv.astype(i32), catv.astype(i32),
             ~leaf, ~new_leaf]))
        node_bits = state["node_bits"].at[wk].set(bitsv)

        # the side choice must be identical on every shard (each shard's
        # local partial fed the one psum); local partition counts differ
        # per shard, the scan's global (in-bag) counts do not
        small_is_left = lc <= pc - lc
        hist_large = state["hist"][leaf] - hist_small
        hist_left = jnp.where(small_is_left, hist_small, hist_large)
        hist_right = jnp.where(small_is_left, hist_large, hist_small)
        hist = state["hist"].at[wl].set(hist_left).at[wn].set(hist_right)

        fms = jnp.broadcast_to(fmask, (2, F))
        best_children = jax.vmap(self._s2_best_of,
                                 in_axes=(0, 0, 0, 0, 0, None, 0))
        (bg2, bf2, bt2, bdl2, bcat2, bbits2, blg2, blh2, blc2,
         blout2, brout2) = best_children(
            jnp.stack([hist_left, hist_right]),
            jnp.stack([lg, rg]), jnp.stack([lh, rh]),
            jnp.stack([lc, rc]), jnp.stack([lout, rout]), depth, fms)

        lrow_f = jnp.stack([lg, lh, lc, lout, bg2[0], blg2[0], blh2[0],
                            blc2[0], blout2[0], brout2[0], lmin, lmax])
        rrow_f = jnp.stack([rg, rh, rc, rout, bg2[1], blg2[1], blh2[1],
                            blc2[1], blout2[1], brout2[1], rmin, rmax])
        lrow_i = jnp.stack([begin, left_count, depth, nidx, i32(1),
                            bf2[0], bt2[0], bdl2[0].astype(i32),
                            bcat2[0].astype(i32)])
        rrow_i = jnp.stack([begin + left_count, right_count, depth, nidx,
                            i32(0), bf2[1], bt2[1], bdl2[1].astype(i32),
                            bcat2[1].astype(i32)])

        out = dict(state)
        out["leaf_f"] = leaf_f.at[wl].set(lrow_f).at[wn].set(rrow_f)
        out["leaf_i"] = leaf_i_l.at[wl].set(lrow_i).at[wn].set(
            rrow_i)[None]
        out["leaf_bits"] = leaf_bits.at[wl].set(bbits2[0]) \
                                    .at[wn].set(bbits2[1])
        out["node_f"] = node_f
        out["node_i"] = node_i
        out["node_bits"] = node_bits
        out["hist"] = hist
        out["num_leaves"] = state["num_leaves"] + ok.astype(i32)
        return out

    def _s2_final_body(self, state):
        """Per-shard row->leaf resolution + DeviceTree assembly (the 2-D
        program's epilogue; quantized-leaf renewal is excluded by the
        stream option subset)."""
        cfg = self.config
        N = self.n_loc
        L = cfg.num_leaves
        NODES = max(L - 1, 1)
        leaf_i_l = state["leaf_i"][0]
        leaf_begin = jnp.where(leaf_i_l[:L, 1] > 0, leaf_i_l[:L, 0],
                               N + jnp.arange(L, dtype=jnp.int32))
        order = jnp.argsort(leaf_begin)
        sorted_begin = leaf_begin[order]
        which = jnp.searchsorted(sorted_begin,
                                 jnp.arange(N, dtype=jnp.int32),
                                 side="right") - 1
        pos_leaf = order[which]
        row_leaf = jnp.zeros(N, jnp.int32).at[
            state["perm"][:N]].set(pos_leaf)
        node_f = state["node_f"]
        node_i = state["node_i"]
        leaf_f = state["leaf_f"]
        leaf_value_out = jnp.where(state["num_leaves"] > 1,
                                   leaf_f[:L, 3],
                                   jnp.zeros_like(leaf_f[:L, 3]))
        return DeviceTree(
            node_feature=node_i[:NODES, 0],
            node_threshold=node_i[:NODES, 1],
            node_default_left=node_i[:NODES, 2].astype(bool),
            node_is_cat=node_i[:NODES, 3].astype(bool),
            node_cat_bits=state["node_bits"][:NODES],
            node_left=node_i[:NODES, 4],
            node_right=node_i[:NODES, 5],
            node_gain=node_f[:NODES, 0],
            node_value=node_f[:NODES, 1],
            node_weight=node_f[:NODES, 2],
            node_count=node_f[:NODES, 3],
            leaf_value=leaf_value_out,
            leaf_weight=leaf_f[:L, 1],
            leaf_count=leaf_f[:L, 2],
            leaf_depth=leaf_i_l[:L, 2],
            leaf_parent_node=leaf_i_l[:L, 3],
            num_leaves=state["num_leaves"],
            row_leaf=row_leaf,
        )

    # -- the host-driven composed loop ----------------------------------
    def _s2_pump(self, perms, begins, counts, perm_dev, g, h, m, mask_np):
        """Histogram window pump over every data block at once: the host
        builds one stacked ``[dd, W, Fp]`` buffer per window (per-block
        shard gathers, compacted to in-bag rows when a sampling mask is
        live), ONE mesh-sharded ``device_put`` through the ring lands
        each block's slice on its own device, and the jitted chunk
        kernel accumulates per device in the resident W-chunk order."""
        from ..data.stream import stream_windows
        dd, W = self.dd, self._W2
        Fp = self.num_features
        rF = self._real_F
        n_loc = self.n_loc
        Nr = self.num_data
        dtype = self._bins_dtype
        nch = int(max(-(-int(c) // W) for c in counts)) if counts.max() \
            else 0
        acc = [jax.device_put(
            jnp.zeros((dd, Fp, self.Bb, HIST_C), jnp.float32),
            self._acc_sharding)]
        if nch == 0:
            return acc[0]
        bvec = jax.device_put(jnp.asarray(begins, jnp.int32),
                              self._vec_sharding)
        cvec = jax.device_put(jnp.asarray(counts, jnp.int32),
                              self._vec_sharding)

        def block_rows(d, rows_l, buf_rows):
            ds_rows = d * n_loc + rows_l
            real = ds_rows < Nr
            if real.any():
                buf_rows[real, :rF] = self.sdata.gather_rows(ds_rows[real])

        def fetch(c):
            sel = None
            if mask_np is not None:
                sel = []
                for d in range(dd):
                    lo = int(begins[d]) + c * W
                    live = min(W, int(counts[d]) - c * W)
                    if live <= 0:
                        sel.append((np.empty(0, np.int64),
                                    np.empty(0, np.int64)))
                        continue
                    rows_l = perms[d][lo:lo + live]
                    inb = mask_np[d][rows_l]
                    sel.append((rows_l[inb], np.arange(live)[inb]))
                nsel = max(len(s[0]) for s in sel)
                if nsel <= (W * 7) // 8:
                    wc = max(_next_pow2(max(nsel, 1)), 256)
                    buf = np.zeros((dd, wc, Fp), dtype=dtype)
                    pos = np.full((dd, wc), W, np.int32)
                    for d in range(dd):
                        rows_l, lanes = sel[d]
                        k = len(rows_l)
                        if k:
                            pos[d, :k] = lanes
                            block_rows(d, rows_l, buf[d, :k])
                    return (buf, pos)
            buf = np.zeros((dd, W, Fp), dtype=dtype)
            for d in range(dd):
                lo = int(begins[d]) + c * W
                live = min(W, int(counts[d]) - c * W)
                if live > 0:
                    block_rows(d, perms[d][lo:lo + live], buf[d, :live])
            return (buf,)

        def consume(c, bins_dev, *rest):
            done = jnp.int32(c * W)
            if rest:
                acc[0] = self._sj2_chunk_compact(
                    acc[0], bins_dev, rest[0], perm_dev, g, h, m, bvec,
                    done, cvec)
            else:
                acc[0] = self._sj2_chunk_full(
                    acc[0], bins_dev, perm_dev, g, h, m, bvec, done, cvec)

        stream_windows(nch, fetch, consume, self.telemetry,
                       self.config.stream_prefetch_depth,
                       shardings=self._ring_shardings)
        return acc[0]

    def _train_tree_stream2d(self, grad, hess, row_mask) -> DeviceTree:
        """Grow one tree out-of-core on the 2-D mesh: root histogram over
        all blocks, then per split — pick (one small D2H), per-block
        column fetch + per-shard device partition, go_left mirror
        update, streamed small-child histogram, jitted finish."""
        cfg = self.config
        dd, W = self.dd, self._W2
        n_loc = self.n_loc
        Nr = self.num_data
        NODES = max(cfg.num_leaves - 1, 1)
        fmask = self._feature_mask()
        if row_mask is None:
            m = self.real_mask
        else:
            m = self._shard_vec(row_mask) & self.real_mask
        g = self._shard_vec(grad)
        h = self._shard_vec(hess)
        mask_np = None
        if row_mask is not None and cfg.stream_goss_compact:
            # one D2H of the in-bag mask per tree drives window compaction
            # graftlint: disable=R1 — per-tree (not per-chunk) fetch; the
            # mask is the host-side input of the GOSS working-set shrink
            mask_np = np.asarray(jax.device_get(m)).reshape(dd, n_loc)
        perms = [np.arange(n_loc, dtype=np.int64) for _ in range(dd)]

        acc = self._s2_pump(perms, np.zeros(dd, np.int64),
                            np.full(dd, n_loc, np.int64),
                            self._perm0_2d, g, h, m, mask_np)
        state = self._sj2_init(acc, fmask)

        for _k in range(NODES if cfg.num_leaves > 1 else 0):
            # graftlint: disable=R1 — the composed stream mode's
            # per-split sync: the host must learn which leaf/feature to
            # fetch from its shards (and each data block's local slice);
            # the capacity-for-latency trade the mode IS
            pick = jax.device_get(self._sj2_pick(state))
            leaf, ok, feat = int(pick[0]), bool(pick[1]), int(pick[2])
            blc, pc = float(pick[3]), float(pick[4])
            begins = np.asarray(pick[5], np.int64)
            counts = np.asarray(pick[6], np.int64)
            if not ok:
                break

            # split column values per block slice: 1-2 B/row over the
            # link, pad rows bin 0 (exactly the resident hx padding)
            PV = max(_next_pow2(max(int(counts.max()), 1)), W)
            cv = np.zeros((dd, PV), dtype=self._bins_dtype)
            for d in range(dd):
                cnt = int(counts[d])
                if cnt:
                    rows_l = perms[d][int(begins[d]):int(begins[d]) + cnt]
                    ds_rows = d * n_loc + rows_l
                    real = ds_rows < Nr
                    if real.any():
                        cv[d, :cnt][real] = self.sdata.gather_col(
                            feat, ds_rows[real])
            with self.telemetry.phase("h2d_prefetch"):
                cvals = jax.device_put(cv, self._cvals_sharding)
            state, gbuf, lc_dev = self._sj2_part(state, cvals)
            # graftlint: disable=R1 — go_left + left counts drive the
            # per-shard host mirrors; one small D2H per split
            gl, lcs = jax.device_get((gbuf, lc_dev))
            lcs = np.asarray(lcs, np.int64)
            for d in range(dd):
                cnt = int(counts[d])
                b = int(begins[d])
                if cnt:
                    gld = np.asarray(gl[d])[:cnt]
                    rs = perms[d][b:b + cnt]
                    # mirror the fused pbody placement: lefts stable
                    # ascending, rights filled backward (reversed)
                    perms[d][b:b + cnt] = np.concatenate(
                        [rs[gld], rs[~gld][::-1]])

            # GLOBAL smaller side from the scan's in-bag counts (the
            # device f32 compare replayed on the fetched f32 values)
            small_is_left = np.float32(blc) <= np.float32(pc) \
                - np.float32(blc)
            if small_is_left:
                sb, sc = begins, lcs
            else:
                sb, sc = begins + lcs, counts - lcs
            acc = self._s2_pump(perms, sb, sc, state["perm"], g, h, m,
                                mask_np)
            state = self._sj2_finish(state, acc, lc_dev, fmask)

        rec = self._sj2_final(state)
        return rec._replace(row_leaf=rec.row_leaf[:Nr])


# ---------------------------------------------------------------------------
# graftir IR contracts (`python -m lambdagap_tpu.analysis --ir`): the
# declared collective schedule of every program this module jits, verified
# against the lowered jaxpr across all four virtual grids. Editing this
# file invalidates exactly these programs' cached verdicts.
from ..analysis.ir.contracts import all_gather, psum, register_program


def _hist_bytes(d):
    # per-shard leaf histogram: ceil(F/ff) features x bins x {g,h,cnt}
    return -(-d["features"] // d["ff"]) * d["bins"] * d["hist_item"]


def _rowflag_bytes(d):
    # go-left partition flags: one byte per shard-resident row
    return -(-d["rows"] // d["dd"])


register_program(
    "FusedDataParallelTreeLearner._train_tree_impl",
    quant_int_reduction=True,
    step_collectives=(psum("data", 1, "leaf histogram", _hist_bytes),),
    setup_collectives=(psum("data", 1, "root histogram", _hist_bytes),),
    notes="one histogram psum per split step; splits are chosen locally "
          "on the replicated reduced histograms — no other wire traffic")

register_program(
    "FusedVotingParallelTreeLearner._train_tree_impl",
    step_collectives=(psum("data", 1, "voted histogram columns"),
                      all_gather("data", 1, "local top-k votes")),
    setup_collectives=(psum("data", 2, "root histogram + vote meta"),
                       all_gather("data", 1, "root votes")),
    notes="PV-Tree schedule: local votes gathered over data, then only "
          "the voted feature columns are psum-ed")

register_program(
    "FusedFeatureParallelTreeLearner.__init__.sharded",
    step_collectives=(
        psum("feature", 1, "go-left row flags", _rowflag_bytes),
        all_gather("feature", 11, "best-split tuple (11 fields)")),
    setup_collectives=(
        all_gather("feature", 11, "root best-split tuple"),),
    notes="rows replicated, features sharded: the winning split is "
          "all_gather-ed over feature and partition flags psum-ed so "
          "every shard keeps the full row->leaf map")

register_program(
    "Fused2DTreeLearner._train_tree_impl",
    quant_int_reduction=True,
    step_collectives=(
        psum("data", 1, "leaf histogram", _hist_bytes),
        psum("feature", 1, "go-left row flags", _rowflag_bytes),
        all_gather("feature", 11, "best-split tuple (11 fields)")),
    setup_collectives=(
        psum("data", 1, "root histogram", _hist_bytes),
        psum("feature", 1, "per-feature meta", lambda d: d["features"]),
        all_gather("feature", 11, "root best-split tuple")),
    notes="the PR 15 invariant: three logical collectives per split step "
          "— hist psum over data, row-flag psum over feature, best-split "
          "all_gather over feature (11 eqns = 11 tuple fields) — with "
          "payload bytes grid-invariant-by-formula over 1x8/2x4/4x2/8x1")

# streaming split-step bodies: the split loop is driven from host, so each
# body's collectives sit at loop depth 0 (= the whole program IS one step)
register_program(
    "Fused2DTreeLearner._s2_init_body",
    setup_collectives=(
        psum("data", 1, "root histogram", _hist_bytes),
        psum("feature", 1, "per-feature meta", lambda d: d["features"]),
        all_gather("feature", 11, "root best-split tuple")))
register_program(
    "Fused2DTreeLearner._s2_finish_body",
    setup_collectives=(
        psum("data", 1, "sibling-subtracted child histogram", _hist_bytes),
        all_gather("feature", 11, "best-split tuple")))
register_program("Fused2DTreeLearner._s2_chunk_body", collective_free=True,
                 max_traces=2,
                 notes="full + compact payload layouts are two programs")
register_program("Fused2DTreeLearner._s2_pick_body", collective_free=True)
register_program("Fused2DTreeLearner._s2_part_body", collective_free=True)
register_program("Fused2DTreeLearner._s2_final_body", collective_free=True)
