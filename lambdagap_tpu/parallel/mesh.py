"""Device mesh helpers.

The TPU replacement for the reference's Network layer
(reference: src/network/ — socket/MPI Linkers, Bruck allgather,
recursive-halving reduce-scatter, network.h:89-275 collectives): here the
"network" is a ``jax.sharding.Mesh`` over ICI/DCN and every collective is an
XLA op (``psum``/``all_gather``/``psum_scatter``) emitted inside
``shard_map``; schedules (ring vs tree vs Bruck) are XLA's problem, not ours
(SURVEY.md §2.6).

Axis names and per-array ``PartitionSpec`` come from the rule registry in
:mod:`lambdagap_tpu.parallel.sharding` — this module only keeps the
placement helpers (and re-exports the axis constant for back-compat).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding

from .sharding import (DATA_AXIS, FEATURE_AXIS, MESH_AXES,  # noqa: F401
                       make_mesh, mesh_geometry, spec)


def shard_rows(mesh: Mesh, array, pad_value=0, mask=None
               ) -> Tuple[jax.Array, jax.Array, int]:
    """Pad the leading dim to a device multiple and shard it over the
    ``data`` mesh axis (registry rule: per-row state).

    Returns ``(sharded, mask_sharded, pad)``. ``mask_sharded`` is the
    explicit in-bag/validity mask the histogram and count kernels must
    consume: the caller's ``mask`` (all-True when None) padded with False
    rows — so pad rows contribute exact zeros to histograms and root
    counts by construction instead of each caller re-deriving a "real
    rows" mask ad hoc (tests/test_distributed.py pad-row test).
    """
    import jax.numpy as jnp
    n_dev = int(mesh.devices.size)
    n = array.shape[0]
    pad = (-n) % n_dev
    if mask is None:
        mask = jnp.ones(n, dtype=bool)
    elif mask.shape[0] != n:
        raise ValueError(f"mask length {mask.shape[0]} != rows {n}")
    if pad:
        pad_widths = [(0, pad)] + [(0, 0)] * (array.ndim - 1)
        array = jnp.pad(array, pad_widths, constant_values=pad_value)
        mask = jnp.pad(mask, (0, pad), constant_values=False)
    sharded = jax.device_put(
        array, NamedSharding(mesh, spec("row_mask", ndim=array.ndim)))
    mask_sharded = jax.device_put(
        mask, NamedSharding(mesh, spec("row_mask")))
    return sharded, mask_sharded, pad


def replicated(mesh: Mesh, array):
    return jax.device_put(array, NamedSharding(mesh, spec("rep")))
