"""Device mesh helpers.

The TPU replacement for the reference's Network layer
(reference: src/network/ — socket/MPI Linkers, Bruck allgather,
recursive-halving reduce-scatter, network.h:89-275 collectives): here the
"network" is a ``jax.sharding.Mesh`` over ICI/DCN and every collective is an
XLA op (``psum``/``all_gather``/``psum_scatter``) emitted inside
``shard_map``; schedules (ring vs tree vs Bruck) are XLA's problem, not ours
(SURVEY.md §2.6).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: int = 0, devices: Optional[Sequence] = None) -> Mesh:
    """1-D data mesh. ``num_devices=0`` uses all visible devices."""
    if devices is None:
        devices = jax.devices()
    if num_devices and num_devices > 0:
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def shard_rows(mesh: Mesh, array, pad_value=0):
    """Pad the leading dim to a device multiple and shard it over the mesh."""
    import jax.numpy as jnp
    n_dev = mesh.devices.size
    n = array.shape[0]
    pad = (-n) % n_dev
    if pad:
        pad_widths = [(0, pad)] + [(0, 0)] * (array.ndim - 1)
        array = jnp.pad(array, pad_widths, constant_values=pad_value)
    spec = P(DATA_AXIS, *([None] * (array.ndim - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec)), pad


def replicated(mesh: Mesh, array):
    import jax
    return jax.device_put(array, NamedSharding(mesh, P()))
