"""Multi-process (multi-host) distributed initialization.

The DCN-scale analog of the reference's socket/MPI ``Linkers`` transport
(reference: src/network/linkers_socket.cpp — machine list + listen port +
pairwise TCP connect; src/network/linkers_mpi.cpp): one
``init_distributed`` call per process wires every process into a single
JAX runtime, after which ``jax.devices()`` is the GLOBAL device list and
the mesh-based learners' ``psum``/``all_gather`` collectives ride DCN
between hosts and ICI within them — the reference's hand-written
Bruck/recursive-halving schedules (src/network/linker_topo.cpp) are XLA's
responsibility here.

Config mapping from the reference's parameters:
- ``machines`` ("ip:port,ip:port,...") -> the first entry is the
  coordinator address (JAX is coordinator-based, not all-pairs).
- ``num_machines`` -> num_processes.
- ``machine_rank`` (new; the reference infers rank by matching the local
  IP against the machine list) -> process_id.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..config import Config
from ..utils import log


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     config: Optional[Config] = None) -> None:
    """Join this process into a multi-process JAX runtime.

    Call once per process before building datasets/boosters, mirroring the
    reference's ``Network::Init`` at application start
    (reference: src/application/application.cpp InitTrain ->
    Network::Init). Arguments may come from an explicit ``Config`` carrying
    the reference's ``machines``/``num_machines`` parameters.
    """
    if config is not None:
        machines = config.machines
        file_count = 0
        if not machines and config.machine_list_filename:
            # reference: mlist.txt, one host per line
            # (src/network/linkers_socket.cpp machine-list file)
            with open(config.machine_list_filename) as fh:
                entries = [ln.strip() for ln in fh if ln.strip()]
            machines = ",".join(entries)
            file_count = len(entries)
        if coordinator_address is None and machines:
            coordinator_address = machines.split(",")[0].strip()
        if num_processes is None and config.num_machines > 1:
            # num_machines governs; the machine list may list spare hosts
            num_processes = config.num_machines
        elif num_processes is None and file_count > 1:
            num_processes = file_count
        if process_id is None and config.machine_rank >= 0:
            process_id = config.machine_rank
    if num_processes is None or num_processes <= 1:
        log.info("init_distributed: single process (no coordinator needed)")
        return
    # "already joined" must be detected WITHOUT touching the backend:
    # jax.process_count() initializes XLA, which would make the
    # jax.distributed.initialize below fail for not-yet-joined callers
    try:
        from jax._src import distributed as _dist
        already = _dist.global_state.client is not None
    except Exception:   # pragma: no cover - private-API drift
        already = False
    if already:
        # the CLI joins pre-import in __main__, before any
        # backend-initializing jnp constant
        log.info("init_distributed: already connected (process %d/%d)",
                 jax.process_index(), jax.process_count())
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("Connected to distributed runtime: process %d/%d, "
             "%d global devices (%d local)",
             jax.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))


def global_array_from_local(local: np.ndarray, mesh, spec):
    """Assemble a globally-sharded array from this process's row block —
    the ``pre_partition=true`` ingestion path (reference:
    Metadata partitioning for pre-partitioned distributed data,
    src/io/metadata.cpp; every process passes only its own rows)."""
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, local)


def local_block(global_arr, n_real: Optional[int] = None) -> np.ndarray:
    """This process's contiguous row block of a leading-axis-sharded global
    array (inverse of :func:`global_array_from_local`)."""
    shards = sorted(global_arr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    block = np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return block[:n_real] if n_real is not None else block


def merge_sketches_across_processes(sketches, budget: int):
    """The psum-analog sketch reduction: allgather every rank's fixed-size
    sketch state and merge in rank order, so all ranks end with the SAME
    summary of the GLOBAL value stream (reference: the GlobalSyncUp of bin
    boundaries, src/io/dataset_loader.cpp:1072; "XGBoost: Scalable GPU
    Accelerated Learning" arXiv:1806.11248 §5 — quantile summaries, not
    rows, cross the interconnect). Single-process calls return the input
    sketches unchanged — the 1-device special case.
    """
    from ..data.binning import QuantileSketch
    if jax.process_count() <= 1:
        return list(sketches)
    from jax.experimental import multihost_utils
    state = np.stack([sk.state_vector() for sk in sketches])   # [F, 3+2b]
    gathered = np.asarray(multihost_utils.process_allgather(state))
    gathered = gathered.reshape(jax.process_count(), *state.shape)
    merged = []
    for j in range(state.shape[0]):
        sk = QuantileSketch.from_state_vector(gathered[0, j], budget)
        for r in range(1, gathered.shape[0]):
            sk.merge(QuantileSketch.from_state_vector(gathered[r, j],
                                                      budget))
        merged.append(sk)
    return merged


def load_pre_partitioned(path: str, config: Config):
    """``pre_partition=true`` ingestion: each process loads ITS OWN data
    file and sketches EVERY local row (one bounded-memory QuantileSketch
    per feature); the sketches are allgather-merged in rank order, every
    rank finalizes identical bin boundaries from the merged summaries, and
    each rank bins its own shard locally — sharded dataset construction
    with only O(F * budget) summary bytes on the wire, no sample matrix
    (reference: src/io/dataset_loader.cpp:1072
    ConstructBinMappersFromTextData + GlobalSyncUp; ISSUE 8). Boundaries
    are exact (not sampled) whenever per-feature distinct counts fit
    ``stream_sketch_budget``.

    Returns a local BinnedDataset carrying the process-sharding metadata
    (``process_sharded`` / ``global_row_counts`` / ``global_num_data``)
    that routes training onto the fused data-parallel learner over the
    multi-process mesh. Boosting state (scores, gradients, bagging) stays
    process-local, exactly like the reference's per-rank Boosting object;
    only histogram reduction crosses processes.
    """
    from ..data.binning import QuantileSketch
    from ..data.dataset import BinnedDataset, _mappers_from_sketches
    from ..data.loader import _parse_text_file
    from jax.experimental import multihost_utils

    X, y, weight, qgroups, fnames = _parse_text_file(path, config)
    n_local = len(X)
    if n_local == 0:
        log.fatal("pre_partition: %s holds no rows for process %d",
                  path, jax.process_index())
    nproc = jax.process_count()
    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([n_local], np.int64))).reshape(-1)

    from ..data.loader import resolve_categorical
    categorical = resolve_categorical(config, fnames)

    # sketch ALL local rows block-wise, then reduce across ranks
    F = X.shape[1]
    budget = config.stream_sketch_budget
    local = [QuantileSketch(budget=budget) for _ in range(F)]
    for lo in range(0, n_local, 65536):
        blk = np.asarray(X[lo:lo + 65536], np.float64)
        for j in range(F):
            local[j].push(blk[:, j])
    merged = merge_sketches_across_processes(local, budget)

    # identical merged summaries on every rank -> identical mappers
    mapper_ref = BinnedDataset()
    mapper_ref.num_data = int(counts.sum())
    mapper_ref.num_total_features = F
    mapper_ref.max_bin = config.max_bin
    mapper_ref.feature_names = (list(fnames) if fnames
                                else [f"Column_{i}" for i in range(F)])
    _mappers_from_sketches(mapper_ref, merged, config, set(categorical))
    ds = BinnedDataset.from_matrix(
        X, config, label=y, weight=weight, group=qgroups,
        categorical_features=categorical, reference=mapper_ref)
    ds.process_sharded = True
    ds.global_row_counts = counts
    ds.global_num_data = int(counts.sum())
    # global label/weight vectors (small): boost_from_average must use the
    # GLOBAL statistics or ranks bake different init scores into tree 0
    # (reference: GBDT::BoostFromAverage syncs sums over Network)
    max_cnt = int(counts.max())

    def _gather_ragged(v, dtype):
        pad = np.zeros(max_cnt, dtype=dtype)
        pad[:n_local] = v
        g = np.asarray(multihost_utils.process_allgather(pad))
        return np.concatenate([g[r, :counts[r]] for r in range(nproc)])

    ds.global_label = _gather_ragged(y, np.float32)
    has_w = np.asarray(multihost_utils.process_allgather(
        np.asarray([0 if weight is None else 1], np.int64))).reshape(-1)
    if has_w.any() and not has_w.all():
        # every rank sees the same allgathered flags, so ALL ranks fail
        # together — an asymmetric exit would leave the others hanging in
        # the next collective
        log.fatal("pre_partition: weight sidecar present on some ranks "
                  "but not others")
    ds.global_weight = (_gather_ragged(weight, np.float32)
                        if weight is not None else None)
    has_g = np.asarray(multihost_utils.process_allgather(
        np.asarray([0 if qgroups is None else 1], np.int64))).reshape(-1)
    if has_g.any() and not has_g.all():
        log.fatal("pre_partition: query/group information present on some "
                  "ranks but not others")
    ds.global_group = None
    if has_g.all():
        # ragged per-rank group-size vectors -> one global sizes vector
        # (ranking objectives need GLOBAL query stats for init, like the
        # global label/weight above)
        sizes = np.asarray(qgroups, np.int64)
        ngs = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(sizes)], np.int64))).reshape(-1)
        pad = np.zeros(int(ngs.max()), np.int64)
        pad[:len(sizes)] = sizes
        g = np.asarray(multihost_utils.process_allgather(pad))
        ds.global_group = np.concatenate(
            [g[r, :ngs[r]] for r in range(nproc)])
    log.info("pre_partition: process %d/%d holds %d of %d rows",
             jax.process_index(), nproc, n_local, ds.global_num_data)
    return ds
