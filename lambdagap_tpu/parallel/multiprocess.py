"""Multi-process (multi-host) distributed initialization.

The DCN-scale analog of the reference's socket/MPI ``Linkers`` transport
(reference: src/network/linkers_socket.cpp — machine list + listen port +
pairwise TCP connect; src/network/linkers_mpi.cpp): one
``init_distributed`` call per process wires every process into a single
JAX runtime, after which ``jax.devices()`` is the GLOBAL device list and
the mesh-based learners' ``psum``/``all_gather`` collectives ride DCN
between hosts and ICI within them — the reference's hand-written
Bruck/recursive-halving schedules (src/network/linker_topo.cpp) are XLA's
responsibility here.

Config mapping from the reference's parameters:
- ``machines`` ("ip:port,ip:port,...") -> the first entry is the
  coordinator address (JAX is coordinator-based, not all-pairs).
- ``num_machines`` -> num_processes.
- ``machine_rank`` (new; the reference infers rank by matching the local
  IP against the machine list) -> process_id.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from ..config import Config
from ..utils import log


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     config: Optional[Config] = None) -> None:
    """Join this process into a multi-process JAX runtime.

    Call once per process before building datasets/boosters, mirroring the
    reference's ``Network::Init`` at application start
    (reference: src/application/application.cpp InitTrain ->
    Network::Init). Arguments may come from an explicit ``Config`` carrying
    the reference's ``machines``/``num_machines`` parameters.
    """
    if config is not None:
        if coordinator_address is None and config.machines:
            coordinator_address = config.machines.split(",")[0].strip()
        if num_processes is None and config.num_machines > 1:
            num_processes = config.num_machines
        if process_id is None and config.machine_rank >= 0:
            process_id = config.machine_rank
    if num_processes is None or num_processes <= 1:
        log.info("init_distributed: single process (no coordinator needed)")
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("Connected to distributed runtime: process %d/%d, "
             "%d global devices (%d local)",
             jax.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))


def global_array_from_local(local: np.ndarray, mesh, spec):
    """Assemble a globally-sharded array from this process's row block —
    the ``pre_partition=true`` ingestion path (reference:
    Metadata partitioning for pre-partitioned distributed data,
    src/io/metadata.cpp; every process passes only its own rows)."""
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, local)
