"""Unified partition-rule registry: the one source of truth for how every
array in the training state is laid out over the device mesh.

Before this module each parallel learner declared its own ad-hoc
``PartitionSpec`` literals (data_parallel / fused_parallel / voting_parallel
/ feature_parallel all hardcoded ``P(DATA_AXIS, ...)`` tuples), so the same
logical array — the packed binned matrix, a gradient buffer, a histogram —
was sharded by four independent spellings, and a 2-D (data x feature) mesh
could not even be expressed. Here every logical array NAME resolves through
one ordered rule table (the ``match_partition_rules`` regex pattern of
SNIPPETS.md [3], over the mesh-helper shape of [1]) against a mesh that
always declares BOTH axes::

    Mesh(devices.reshape(dd, ff), ("data", "feature"))

A data-parallel placement is ``(D, 1)``, a feature-parallel placement is
``(1, D)``, and a future 2-D run is ``(dd, ff)`` — the RULES never change,
only the mesh geometry does, because a ``PartitionSpec`` axis over a
size-1 mesh dimension is a no-op. That is what makes the registry the 2-D
unlock: ``x_rows -> P("data", "feature")`` already says "rows over the
data axis AND columns over the feature axis"; today's learners simply run
it at geometries where one of the two is trivial.

graftlint R6 reads ``MESH_AXES`` below as the collective-axis universe
(analysis/rules/r6_collective_axis.py): a ``psum``/``all_gather`` naming an
axis this registry does not declare is flagged without running any code.

The feature->rank ownership tables of the reference's distributed learners
(reference: src/treelearner/data_parallel_tree_learner.cpp:71-121
PrepareBufferPos) have no analog here: ownership IS the partition spec.
"""
from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_old(*args, **kwargs)

# the axis universe. Rows of the training matrix shard over "data"
# (histograms psum over it); columns shard over "feature" (histogram
# blocks all_gather / winning columns psum over it).
DATA_AXIS = "data"
FEATURE_AXIS = "feature"
MESH_AXES = (DATA_AXIS, FEATURE_AXIS)

# ---------------------------------------------------------------------------
# the rule table
# ---------------------------------------------------------------------------
# name-regex -> PartitionSpec template, first match wins (SNIPPETS.md [3]).
# Templates name MESH_AXES members or None per array dimension; a template
# shorter than the array rank is padded with None (trailing dims
# replicated). Every array the parallel learners move through shard_map
# has a named rule here — an unmatched name raises, never guesses.
RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    # packed binned matrix, row-major [rows, features]
    (r"^(x|hx)_rows$|^x_sharded$", (DATA_AXIS, FEATURE_AXIS)),
    # column-major copy [features, rows] (partition-pass column reads)
    (r"^(x|hx)_cols$", (FEATURE_AXIS, DATA_AXIS)),
    # fully replicated matrix (the host-loop feature learner keeps all
    # rows everywhere and block-slices columns by axis_index itself)
    (r"^x_replicated$", ()),
    # sorted-leaf payload [rows + W, lanes]: lanes ride with their row
    (r"^srows$|^sorted_(rows|payload)$", (DATA_AXIS, None)),
    # per-row training state: gh buffers, quantized gh levels, sample /
    # pad masks, permutations, scores, row->leaf maps
    (r"^(grad|hess|gq|hq)$|^(row_|real_)?mask$|^perm$|^score$|^row_leaf$",
     (DATA_AXIS,)),
    # per-shard scalar bookkeeping distributed one-per-device along the
    # data axis (leaf begin/count blocks of the host-loop learners)
    (r"^(begin|count)$|^shard_scalar$", (DATA_AXIS,)),
    # device-stacked local histograms [D*F, B, 3] (voting keeps histograms
    # shard-local and psums only voted columns)
    (r"^hist_(local|stack)$", (DATA_AXIS,)),
    # 2-D program arrays (the fused data x feature learner + its stream
    # mirror): histogram COLUMN blocks shard over "feature" while their
    # row partials psum over "data" —
    #   hist_cols  [C, B, 3]            one leaf's histogram, psum-ed over
    #                                   data, column-sharded
    #   hist_state [L+1, C, B, 3]       the carried per-leaf histogram state
    #   hist_grid  [dd, C, B, 3]        per-(data,feature)-device partial
    #                                   accumulator of the stream pump
    #   win_bins   [dd, W, C]           one uploaded row window per data
    #                                   block, columns sharded
    #   win_cvals  [dd, PV]             per-block per-lane values (split
    #                                   column / compaction positions)
    #   leaf_local [dd, L+1, k]         per-data-shard leaf bookkeeping
    #                                   (begin/count are row-partition
    #                                   quantities — local per data block,
    #                                   replicated over feature)
    (r"^hist_cols$", (FEATURE_AXIS,)),
    (r"^hist_state$", (None, FEATURE_AXIS)),
    (r"^hist_grid$", (DATA_AXIS, FEATURE_AXIS)),
    (r"^win_bins$", (DATA_AXIS, None, FEATURE_AXIS)),
    (r"^win_(cvals|pos|lanes)$", (DATA_AXIS,)),
    (r"^leaf_local$", (DATA_AXIS,)),
    # predict_stream batch-scoring arrays (infer/stream.py): scoring is
    # collective-free and strictly per-row, so window rows shard over the
    # WHOLE flattened grid — both mesh axes on the row dim — and every
    # dd x ff factorization (1x8, 2x4, 8x1) runs the one program on its
    # local rows:
    #   pred_win    [W, F]   one padded scoring window, rows sharded,
    #                        features replicated
    #   pred_scores [K, W]   its score tile riding the D2H ring back,
    #                        rows sharded the same way
    (r"^pred_win$", ((DATA_AXIS, FEATURE_AXIS), None)),
    (r"^pred_scores$", (None, (DATA_AXIS, FEATURE_AXIS))),
    # replicated state: psum-ed histograms, split results, node/leaf
    # tables, per-feature metadata, feature sampling masks, rng keys,
    # scalars. Derived from collectives on every shard -> identical
    # everywhere by construction.
    (r"^hist(ogram)?(_root)?$|^fmask$|^(feature|bin)_meta$|^node(_\w+)?$"
     r"|^leaf(_\w+)?$|^tree(_record)?$|^(e|q|b)?key$|^scalar$"
     r"|^rep(licated)?$", ()),
)


def spec(name: str, ndim: Optional[int] = None) -> P:
    """The :class:`PartitionSpec` for the logical array ``name``.

    ``ndim`` pads the matched template with trailing ``None`` dims (a
    per-row rule applied to an ``[N, k]`` array); templates are never
    truncated. Unknown names raise — the registry must stay exhaustive
    (same contract as SNIPPETS.md [3] ``match_partition_rules``).
    """
    for pattern, template in RULES:
        if re.search(pattern, name):
            if ndim is not None:
                if ndim < len(template):
                    raise ValueError(
                        f"array {name!r} has rank {ndim} but its partition "
                        f"rule spans {len(template)} dims")
                template = template + (None,) * (ndim - len(template))
            return P(*template)
    raise ValueError(
        f"no partition rule for array {name!r}; add one to "
        "lambdagap_tpu/parallel/sharding.py RULES")


def specs(*names: str) -> Tuple[P, ...]:
    """``spec`` over several names — the ``in_specs=specs(...)`` helper."""
    return tuple(spec(n) for n in names)


def sharding(mesh: Mesh, name: str, ndim: Optional[int] = None
             ) -> NamedSharding:
    return NamedSharding(mesh, spec(name, ndim))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# mesh construction
# ---------------------------------------------------------------------------
def parse_mesh_shape(mesh_shape: str) -> Optional[Tuple[int, int]]:
    """``mesh_shape`` knob -> (data, feature) extents. ``""`` -> None
    (learner picks its natural 1-D placement); ``"8"`` -> (8, 1);
    ``"4x2"`` -> (4, 2). ``0`` in either slot means "all remaining
    devices on this axis"."""
    s = str(mesh_shape).strip().lower()
    if not s:
        return None
    parts = s.replace("*", "x").split("x")
    try:
        dims = [int(p) for p in parts]
    except ValueError:
        raise ValueError(f"mesh_shape must look like '8' or '4x2', "
                         f"got {mesh_shape!r}")
    if len(dims) == 1:
        dims.append(1)
    if len(dims) != 2 or any(d < 0 for d in dims):
        raise ValueError(f"mesh_shape must be 1-D or 2-D non-negative, "
                         f"got {mesh_shape!r}")
    return dims[0], dims[1]


def resolve_mesh_shape(mesh_shape: str, num_devices: int
                       ) -> Optional[Tuple[int, int]]:
    """Resolve the ``mesh_shape`` knob against an actual device count:
    wildcard extents (``"0x4"`` / ``"2x0"`` — "all remaining devices on
    this axis") are filled in, divisibility and capacity are checked, and
    every rejection names ``mesh_shape`` (the ``num_grad_quant_bins``
    error-message precedent). ``""`` -> None (the learner picks its
    natural 1-D placement)."""
    shape = parse_mesh_shape(mesh_shape)
    if shape is None:
        return None
    dd, ff = shape
    if dd == 0 and ff == 0:
        raise ValueError("mesh_shape cannot be 0x0 (at most one wildcard "
                         "extent)")
    if dd == 0:
        if num_devices % max(ff, 1):
            raise ValueError(
                f"mesh_shape {mesh_shape!r}: the wildcard data extent "
                f"needs the device count ({num_devices}) divisible by the "
                f"feature extent ({ff})")
        dd = num_devices // ff
        if dd == 0:
            raise ValueError(
                f"mesh_shape {mesh_shape!r} needs at least {ff} devices, "
                f"have {num_devices}")
    if ff == 0:
        if num_devices % max(dd, 1):
            raise ValueError(
                f"mesh_shape {mesh_shape!r}: the wildcard feature extent "
                f"needs the device count ({num_devices}) divisible by the "
                f"data extent ({dd})")
        ff = num_devices // dd
        if ff == 0:
            raise ValueError(
                f"mesh_shape {mesh_shape!r} needs at least {dd} devices, "
                f"have {num_devices}")
    if dd * ff > num_devices:
        raise ValueError(
            f"mesh_shape {mesh_shape!r} ({dd}x{ff}) needs {dd * ff} "
            f"devices, have {num_devices}")
    return dd, ff


def make_mesh(num_devices: int = 0, devices: Optional[Sequence] = None,
              mesh_shape: str = "", shard_axis: str = DATA_AXIS) -> Mesh:
    """The registry mesh: ALWAYS 2-D named ``("data", "feature")``.

    ``mesh_shape=""`` places ``num_devices`` (0 = all visible) on
    ``shard_axis`` — the learner's natural 1-D geometry: data/voting
    learners shard rows (``(D, 1)``), feature learners shard columns
    (``(1, D)``). An explicit ``mesh_shape`` overrides both knobs —
    including genuine 2-D ``dd x ff`` grids, executed by the fused 2-D
    learner (rows shard over ``data``, histogram columns over
    ``feature``; parallel/fused_parallel.py Fused2DTreeLearner).
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    shape = resolve_mesh_shape(mesh_shape, len(devices))
    if shape is None:
        if num_devices and num_devices > 0:
            devices = devices[:num_devices]
        d = len(devices)
        shape = (d, 1) if shard_axis == DATA_AXIS else (1, d)
    else:
        devices = devices[:shape[0] * shape[1]]
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, MESH_AXES)


def mesh_geometry(mesh: Mesh) -> dict:
    """JSON-able mesh description for snapshot sidecars / bench records /
    telemetry run headers (guard elastic resume reads it back)."""
    shape = dict(mesh.shape)
    return {
        "axes": list(mesh.axis_names),
        "shape": [int(shape.get(a, 1)) for a in mesh.axis_names],
        "n_devices": int(mesh.devices.size),
        "platform": str(mesh.devices.reshape(-1)[0].platform),
    }
