"""Voting-parallel tree learner.

(reference: src/treelearner/voting_parallel_tree_learner.cpp — data-parallel
with communication held constant: each rank proposes its top-k features by
local gain, votes are Allgathered, GlobalVoting (:151-175) picks the union,
and only the voted features' histograms are summed (:184 CopyLocalHistogram
+ Allreduce) before the global best is chosen.)

TPU shape: leaf histograms stay *local* (sharded ``[D*F, B, 3]``); the vote is
a ``top_k`` + ``all_gather`` of feature ids, and the final reduction is a
``psum`` over only the voted columns — O(2k·B) bytes on the wire instead of
O(F·B).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..data.dataset import BinnedDataset
from ..models.learner import _HostSplit
from ..ops.histogram import histogram_from_rows
from ..ops.split import SplitParams, find_best_split, per_feature_best
from .data_parallel import DataParallelTreeLearner
from .sharding import DATA_AXIS, shard_map, spec, specs


class VotingParallelTreeLearner(DataParallelTreeLearner):
    """Data-parallel loop; histogram reduction replaced by top-k voting."""

    def _build_ops(self) -> None:
        super()._build_ops()
        mesh = self.mesh
        B = self.B
        rpb = self.rows_per_block
        prec = self.config.tpu_hist_precision
        F = self.num_features
        top_k = max(1, min(self.config.top_k, F))
        params = self.params
        has_cat = self.has_categorical

        # local histograms, stacked sharded over devices: [D*F, B, 3]
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=specs("x_rows", "grad", "hess", "row_mask"),
            out_specs=spec("hist_local"), check_vma=False)
        def root_hist_local(x_l, g_l, h_l, m_l):
            return histogram_from_rows(x_l, g_l, h_l, m_l, B, rpb,
                                        precision=prec)

        self._root_hist_op = jax.jit(root_hist_local)

        def leaf_hist_local(x_l, perm_l, g_l, h_l, m_l, begin_l, count_l,
                            padded):
            lane = jnp.arange(padded, dtype=jnp.int32)
            idx = jnp.clip(begin_l[0] + lane, 0, perm_l.shape[0] - 1)
            rows = perm_l[idx]
            valid = (lane < count_l[0]) & m_l[rows]
            return histogram_from_rows(x_l[rows], g_l[rows], h_l[rows],
                                       valid, B, rpb,
                                        precision=prec)

        self._leaf_hist_fn = leaf_hist_local
        self._leaf_hist_ops = {}

        meta = (self.num_bins_arr, self.default_bins_arr,
                self.missing_types_arr, self.is_categorical_arr)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(spec("hist_local"),),
            out_specs=spec("hist"), check_vma=False)
        def root_totals(hist_l):
            return jax.lax.psum(jnp.sum(hist_l[0], axis=0), DATA_AXIS)

        self._root_totals_op = jax.jit(root_totals)

        extra_on = self.extra_on
        in_specs = (spec("hist_local"),) + specs(*["scalar"] * 4) \
            + (spec("fmask"),)
        if extra_on:
            in_specs = in_specs + (spec("scalar"),)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=in_specs,
            out_specs=spec("rep"),
            check_vma=False)   # psum/all_gather make outputs replicated
        def voting_best(hist_l, pg, ph, pc, pout, fmask, *ext):
            """Local top-k vote -> psum of voted columns -> global best."""
            h0 = hist_l            # local [F, B, 3]
            num_bins, default_bins, missing_types, is_cat = meta
            # extra_trees: rand_t is replicated, so votes are scored by the
            # same randomized gain the final voted scan uses
            rand_t = ext[0] if extra_on else None
            # local parent sums for the vote (approximate, like the reference)
            lt = jnp.sum(h0[0], axis=0)
            lgain, *_ = per_feature_best(
                h0, lt[0], lt[1], lt[2], jnp.float32(0.0),
                num_bins, default_bins, missing_types, is_cat, fmask,
                params, has_cat, rand_thresholds=rand_t)
            _, local_top = jax.lax.top_k(lgain, top_k)
            votes = jax.lax.all_gather(local_top.astype(jnp.int32),
                                       DATA_AXIS, tiled=True)    # [D*k]
            hist_voted = jax.lax.psum(h0[votes], DATA_AXIS)      # [D*k, B, 3]
            cons = ((self.mono_arr[votes], jnp.float32(-jnp.inf),
                     jnp.float32(jnp.inf)) if self.mono_on else None)
            res = find_best_split(
                hist_voted, pg, ph, pc, pout,
                num_bins[votes], default_bins[votes], missing_types[votes],
                is_cat[votes], fmask[votes], params,
                has_categorical=has_cat, constraints=cons,
                rand_thresholds=rand_t[votes] if extra_on else None,
                gain_contri=(self.contri_arr[votes]
                             if self.contri_arr is not None else None))
            # remap the winning index back to the true feature id
            true_feat = votes[res.feature]
            return res._replace(feature=true_feat)

        self._voting_best_op = jax.jit(voting_best)

    def _leaf_hist_op(self, padded: int):
        if padded not in self._leaf_hist_ops:
            fn = functools.partial(self._leaf_hist_fn, padded=padded)
            self._leaf_hist_ops[padded] = jax.jit(shard_map(
                fn, mesh=self.mesh,
                in_specs=specs("x_rows", "perm", "grad", "hess", "row_mask",
                               "begin", "count"),
                out_specs=spec("hist_local"), check_vma=False))
        return self._leaf_hist_ops[padded]

    def _best(self, hist, pg, ph, pc, parent_output, fmask) -> _HostSplit:
        args = [hist, jnp.float32(pg), jnp.float32(ph), jnp.float32(pc),
                jnp.float32(parent_output), fmask]
        if self.extra_on:
            args.append(self._draw_extra_thresholds())
        res = self._voting_best_op(*args)
        return _HostSplit(jax.device_get(res))

    def _root_totals(self, hist_root):
        # local hists are partial sums: the global totals need a psum
        return self._root_totals_op(hist_root)
