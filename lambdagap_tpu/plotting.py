"""Plotting utilities (matplotlib-gated).

(reference: python-package/lightgbm/plotting.py — plot_importance,
plot_metric, plot_split_value_histogram, create_tree_digraph.)
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from .basic import Booster
from .utils import log


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        log.fatal("matplotlib is required for plotting; install it first")


def plot_importance(booster: Booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None, title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "split",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal-bar feature importances (reference: plotting.py
    plot_importance)."""
    plt = _check_matplotlib()
    imp = booster.feature_importance(importance_type)
    names = booster.feature_name()
    pairs = [(n, v) for n, v in zip(names, imp)
             if not (ignore_zero and v == 0)]
    pairs.sort(key=lambda p: p[1])
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    if not pairs:
        log.fatal("No features with non-zero importance to plot")
    labels, values = zip(*pairs)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if isinstance(x, float) else str(int(x)),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict[str, Any], "Booster"],
                metric: Optional[str] = None,
                dataset_names=None, ax=None, xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations", ylabel: str = "@metric@",
                figsize=None, dpi=None, grid: bool = True):
    """Plot recorded eval results (reference: plotting.py plot_metric).

    ``booster`` is the dict produced by ``callback.record_evaluation``.
    """
    plt = _check_matplotlib()
    if not isinstance(booster, dict):
        log.fatal("plot_metric needs the eval-results dict collected by "
                  "record_evaluation()")
    eval_results = booster
    if not eval_results:
        log.fatal("eval results are empty; pass record_evaluation to train()")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = dataset_names or list(eval_results.keys())
    picked = None
    for name in names:
        if name not in eval_results:
            log.warning("Dataset %r not found in eval results; skipping", name)
            continue
        metrics = eval_results[name]
        m = metric or next(iter(metrics))
        if m not in metrics:
            continue
        picked = m
        vals = metrics[m]
        ax.plot(np.arange(1, len(vals) + 1), vals, label=name)
    if picked is None:
        log.fatal("No matching (dataset, metric) pair to plot")
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", picked or "metric"))
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster: Booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title="Split value histogram for feature with "
                                     "@index/name@ @feature@",
                               xlabel="Feature split value", ylabel="Count",
                               figsize=None, dpi=None, grid: bool = True):
    """Histogram of a feature's split thresholds across the model
    (reference: plotting.py plot_split_value_histogram)."""
    plt = _check_matplotlib()
    names = booster.feature_name()
    fidx = names.index(feature) if isinstance(feature, str) else int(feature)
    values = [t.threshold_real[i]
              for t in booster._booster.host_models
              for i in range(t.num_internal)
              if t.split_feature[i] == fidx and not t.is_categorical[i]]
    if not values:
        log.fatal("Feature %s was not used in any numerical split", feature)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ax.hist(values, bins=bins or min(len(set(values)), 20), rwidth=width_coef)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title.replace("@index/name@",
                               "name" if isinstance(feature, str) else "index")
                 .replace("@feature@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster: Booster, tree_index: int = 0,
                        show_info=None, precision: int = 3, **kwargs):
    """Graphviz digraph of one tree (reference: plotting.py
    create_tree_digraph). Requires the ``graphviz`` package."""
    try:
        import graphviz
    except ImportError:
        log.fatal("graphviz is required for create_tree_digraph")
    tree = booster._booster.host_models[tree_index]
    names = booster.feature_name()
    g = graphviz.Digraph(**kwargs)

    def node_label(i):
        f = names[tree.split_feature[i]]
        if tree.is_categorical[i]:
            return f"{f} in set"
        return f"{f} <= {tree.threshold_real[i]:.{precision}g}"

    def add(node):
        if node < 0:
            leaf = ~node
            g.node(f"leaf{leaf}",
                   f"leaf {leaf}: {tree.leaf_value[leaf]:.{precision}g}")
            return f"leaf{leaf}"
        nid = f"split{node}"
        g.node(nid, node_label(node))
        for child, lbl in ((tree.left_child[node], "yes"),
                           (tree.right_child[node], "no")):
            cid = add(child)
            g.edge(nid, cid, label=lbl)
        return nid

    if tree.num_internal:
        add(0)
    else:
        add(~0)
    return g


def plot_tree(booster: Booster, tree_index: int = 0, ax=None, figsize=None,
              dpi=None, **kwargs):
    """Render one tree via graphviz into a matplotlib axes
    (reference: plotting.py plot_tree)."""
    plt = _check_matplotlib()
    g = create_tree_digraph(booster, tree_index, **kwargs)
    import io
    try:
        image = g.pipe(format="png")
    except Exception as e:  # graphviz binary missing
        log.fatal("graphviz rendering failed: %s", e)
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    img = plt.imread(io.BytesIO(image), format="png")
    ax.imshow(img)
    ax.axis("off")
    return ax
