"""lambdagap_tpu.serve — batched, hot-swappable, fleet-shaped TPU inference.

A serving layer above the one-shot predict ops: a multi-model registry of
device-resident compiled forests under an HBM budget (registry.py, LRU
eviction + re-admission), a micro-batching request queue with weighted
tenant fairness (batcher.py), per-model atomic generation-pointer hot-swap
(registry.py; swap.py keeps the PR 1 single-model controller), a serving
metrics layer (stats.py), a health-aware replica router with failover
(router.py), a newline-JSON socket front end (frontend.py), an
open-loop load generator (loadgen.py), and — behind
``serve_autonomics=true`` — a self-healing control loop (autonomics.py:
replica revival with backoff + probation, HBM-aware model placement
(placement.py), fleet-atomic delta hot-swap rollouts (delta.py), and a
goodput-knee autoscaler) — fronted by :class:`ForestServer` (server.py).
Entry points::

    server = booster.as_server()                  # Python API
    python -m lambdagap_tpu task=serve \
        input_model=model.txt data=requests.tsv   # CLI request loop
    python -m lambdagap_tpu task=serve \
        input_model=model.txt serve_port=0 serve_replicas=2   # TCP fleet

See docs/serving.md for bucket policy, registry/tenancy/router semantics
and the metrics schema.
"""
from ..guard.degrade import (ReplicaUnavailable, ServeOverloaded,
                             ServeTimeout, SwapFailed, SwapRejected)
from ..obs.fleet import FleetScraper, fleet_snapshot, merge_snapshots
from ..obs.signals import SignalPlane
from .autonomics import Autonomics, default_revive
from .batcher import FairQueue, MicroBatcher, Request
from .cache import DEFAULT_BUCKETS, CompiledForestCache
from .delta import DeltaMismatch, apply_delta, make_delta
from .frontend import FrontendClient, ServeFrontend
from .loadgen import arrival_times, run_open_loop, sweep
from .placement import plan_from_fleet, plan_placement
from .registry import DEFAULT_MODEL, ModelEntry, ModelRegistry
from .router import LocalReplica, RemoteReplica, Router
from .server import (ForestServer, ServeResult, parse_tenant_weights,
                     serve_loop)
from .shadow import ShadowMirror
from .stats import ServeStats
from .swap import SwapController, load_booster

__all__ = ["ForestServer", "ServeResult", "serve_loop", "MicroBatcher",
           "FairQueue", "Request", "CompiledForestCache", "DEFAULT_BUCKETS",
           "DEFAULT_MODEL", "ModelEntry", "ModelRegistry", "Router",
           "LocalReplica", "RemoteReplica", "ServeFrontend",
           "FrontendClient", "arrival_times", "run_open_loop", "sweep",
           "parse_tenant_weights", "ServeStats", "SwapController",
           "load_booster", "ServeOverloaded", "ServeTimeout", "SwapFailed",
           "SwapRejected", "ReplicaUnavailable", "FleetScraper",
           "fleet_snapshot", "merge_snapshots", "SignalPlane",
           "Autonomics", "default_revive", "DeltaMismatch", "make_delta",
           "apply_delta", "plan_placement", "plan_from_fleet",
           "ShadowMirror"]
