"""lambdagap_tpu.serve — batched, hot-swappable TPU inference.

A serving layer above the one-shot predict ops: a device-resident
compiled-forest cache with padding-bucket executables (cache.py), a
micro-batching request queue (batcher.py), atomic generation-pointer model
hot-swap (swap.py) and a serving metrics layer (stats.py), fronted by
:class:`ForestServer` (server.py). Entry points::

    server = booster.as_server()                  # Python API
    python -m lambdagap_tpu task=serve \
        input_model=model.txt data=requests.tsv   # CLI request loop

See docs/serving.md for bucket policy, swap semantics and the metrics
schema.
"""
from ..guard.degrade import (ServeOverloaded, ServeTimeout, SwapFailed,
                             SwapRejected)
from .batcher import MicroBatcher, Request
from .cache import DEFAULT_BUCKETS, CompiledForestCache
from .server import ForestServer, ServeResult, serve_loop
from .stats import ServeStats
from .swap import SwapController, load_booster

__all__ = ["ForestServer", "ServeResult", "serve_loop", "MicroBatcher",
           "Request", "CompiledForestCache", "DEFAULT_BUCKETS",
           "ServeStats", "SwapController", "load_booster",
           "ServeOverloaded", "ServeTimeout", "SwapFailed", "SwapRejected"]
