"""Fleet autonomics: the control loop that closes PR 12's signal plane.

The fleet was observable but static: a dead replica stayed dead until
the router was rebuilt, residency was decided by LRU accidents priced at
174-214x, and nothing reacted to the measured goodput knee (12.7k rps
"throughput" at 0.12 goodput). This module is the ACTUATION half of
ROADMAP item 2 — a background controller that consumes
:class:`~lambdagap_tpu.obs.signals.SignalPlane` ticks and the fleet
metric plane, and acts on the router with four behaviors:

- **replica revival + probation** (``_revive_tick``): a replica the
  router marked dead is reconnected (``RemoteReplica.reconnect``) or
  respawned (``LocalReplica.respawn`` — a fresh server warmed from the
  registry's host-retained models), under a per-replica bounded
  exponential backoff with deterministic jitter
  (:class:`~lambdagap_tpu.guard.backoff.Backoff`). A revived replica
  re-enters rotation at PROBATION — the router demotes it to the
  degraded tier — until ``probe_window`` consecutive healthy ticks at
  fleet goodput clear it (``_probation_tick``); a replica that dies
  again during probation pays the grown backoff, so a flapping host
  cannot convert the controller into a crash loop.
- **HBM-aware placement** (``_placement_tick``): the
  :mod:`~lambdagap_tpu.serve.placement` bin-pack over per-model traffic
  and bytes, actuated as ``prefetch`` (the readmission compile paid off
  the request path) THEN ``Router.set_placement`` (traffic follows the
  resident forest) — the cliff is paid by design, not by LRU accident.
- **delta hot-swap rollout** (:meth:`rollout_delta`): ship only the
  appended trees (serve/delta.py) to every live replica; on ANY
  per-replica failure, the already-committed replicas are swapped back
  to the base text — the fleet lands the new generation everywhere or
  nowhere (each per-replica failure still feeds that model's swap
  breaker, exactly like a full swap).
- **goodput-knee autoscaling** (``_autoscale_tick``): scale the local
  fleet out when ``knee_margin`` shrinks past ``scale_out_margin`` and
  in above ``scale_in_margin`` — hysteresis-guarded (the condition must
  hold ``hysteresis_ticks`` consecutive ticks) and rate-limited
  (``cooldown_s`` between scale actions), acting only on a demonstrated
  knee (``knee_rps > 0``): a cold fleet with no evidence is left alone.

Lock discipline (graftlint R9, the ``r9_scrape``/``r9_autonomics``
hazard class): the controller's own lock guards counters and plan maps
ONLY. Every reconnect, respawn, prefetch, compile, and swap happens with
NO lock held — router mutations go through router methods that lock
around pointer flips, never around the work. The controller thread is a
daemon; ``tick()`` is public and deterministic so tests and gates drive
the loop without wall-clock sleeps.

Everything here is off unless ``serve_autonomics=true``: with the knob
off no controller exists, no thread starts, and router/ServeStats
snapshots are byte-identical to the pre-autonomics schema
(docs/robustness.md "Fleet autonomics").
"""
from __future__ import annotations

import threading
import time
import zlib
from typing import Callable, Dict, List, Optional

from ..guard.backoff import Backoff
from ..guard.degrade import SwapFailed
from ..utils import log
from .placement import plan_changes, plan_from_fleet
from .registry import DEFAULT_MODEL


def _text_of_source(source) -> str:
    """Resolve a rollout source (path / model text / Booster / GBDT)
    into full model text — the delta publisher's input."""
    from ..models.model_text import read_model_source
    from .delta import model_text_of
    from .swap import load_booster
    if isinstance(source, str):
        return read_model_source(source)
    return model_text_of(load_booster(source))


def default_revive(name: str, replica):
    """The built-in revival primitive: reconnect a RemoteReplica's
    address, respawn a LocalReplica's server from its host-retained
    models. Raises while the endpoint is still down (the backoff's
    job to absorb)."""
    if hasattr(replica, "reconnect"):
        return replica.reconnect()
    if hasattr(replica, "respawn"):
        return replica.respawn()
    raise TypeError(f"replica {name!r} ({type(replica).__name__}) has no "
                    "reconnect/respawn primitive; pass revive= to "
                    "Autonomics")


class Autonomics:
    """The fleet controller. ``router`` is the actuation surface;
    ``signals`` (a SignalPlane) and ``scraper`` (a FleetScraper) are the
    sensing surfaces — either may be None, disabling the behaviors that
    need it (revival works from the router snapshot alone).

    ``revive(name, old_replica) -> replica`` overrides the revival
    primitive (the autonomics gate respawns task=serve subprocesses
    here); ``scale(index) -> replica`` supplies scale-out replicas (None
    disables the autoscaler's out direction).
    """

    def __init__(self, router, signals=None, scraper=None, *,
                 interval_s: float = 1.0,
                 revive: Optional[Callable] = None,
                 scale: Optional[Callable] = None,
                 revive_backoff_s: float = 0.5,
                 revive_backoff_max_s: float = 30.0,
                 probe_window: int = 3,
                 scale_out_margin: float = 0.1,
                 scale_in_margin: float = 0.5,
                 min_replicas: int = 1,
                 max_replicas: int = 0,
                 cooldown_s: float = 10.0,
                 hysteresis_ticks: int = 3,
                 placement: bool = True,
                 placement_budget_bytes: int = 0,
                 placement_spread: int = 1,
                 faults=None, recorder=None, seed: int = 0,
                 clock=time.monotonic) -> None:
        self.router = router
        self.signals = signals
        self.scraper = scraper
        self.interval_s = max(float(interval_s), 0.05)
        self._revive_fn = revive if revive is not None else default_revive
        self._scale_fn = scale
        self._backoff_base = float(revive_backoff_s)
        self._backoff_max = float(revive_backoff_max_s)
        self.probe_window = max(int(probe_window), 1)
        self.scale_out_margin = float(scale_out_margin)
        self.scale_in_margin = float(scale_in_margin)
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = max(float(cooldown_s), 0.0)
        self.hysteresis_ticks = max(int(hysteresis_ticks), 1)
        self.placement_enabled = bool(placement)
        self.placement_budget_bytes = int(placement_budget_bytes)
        self.placement_spread = max(int(placement_spread), 1)
        self._faults = faults
        if recorder is None:
            from ..obs import trace as obs_trace
            recorder = obs_trace.RECORDER
        self._recorder = recorder
        self.seed = int(seed)
        self._clock = clock
        self._lock = threading.Lock()    # counters/maps ONLY — never held
        self._backoffs: Dict[str, Backoff] = {}   # across actuation work
        self._probes: Dict[str, int] = {}
        self._plan: Dict[str, List[str]] = {}
        self._base_texts: Dict[str, str] = {}
        self._scaled: List[str] = []     # replicas this controller added
        self._scale_seq = 0
        self._out_streak = 0
        self._in_streak = 0
        self._last_scale_at: Optional[float] = None
        self.counters = {"ticks": 0, "revivals": 0, "revival_failures": 0,
                         "promotions": 0, "demotions": 0,
                         "placement_updates": 0, "prefetches": 0,
                         "scale_outs": 0, "scale_ins": 0,
                         "delta_rollouts": 0, "delta_rollbacks": 0,
                         "full_rollouts": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sensing helpers -------------------------------------------------
    def _backoff_for(self, name: str) -> Backoff:
        with self._lock:
            b = self._backoffs.get(name)
            if b is None:
                b = self._backoffs[name] = Backoff(
                    base_s=self._backoff_base, factor=2.0,
                    max_s=self._backoff_max, jitter=0.1,
                    seed=self.seed ^ zlib.crc32(name.encode()),
                    clock=self._clock)
            return b

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] += n

    # -- the control loop ------------------------------------------------
    def tick(self) -> Dict:
        """One deterministic control step: sense (signal plane + router
        snapshot), then actuate each behavior. Public so tests and the
        autonomics gate drive the loop without wall-clock coupling;
        the background thread calls exactly this."""
        sig = self.signals.snapshot() if self.signals is not None else None
        rsnap = self.router.snapshot()
        self._revive_tick(rsnap)
        self._probation_tick(rsnap, sig)
        if self.placement_enabled:
            self._placement_tick()
        self._autoscale_tick(sig)
        self._bump("ticks")
        return rsnap

    def _revive_tick(self, rsnap: Dict) -> None:
        for name, info in sorted(rsnap.get("replicas", {}).items()):
            if not info.get("dead"):
                continue
            b = self._backoff_for(name)
            if not b.ready():
                continue
            try:
                if self._faults is not None:
                    self._faults.revive_fault()
                old = self.router.replica(name)
                fresh = self._revive_fn(name, old)
                state = fresh.health()
                if state == "dead":
                    raise ConnectionError(
                        f"revived replica {name!r} reports dead health")
                # pointer flip only; the reconnect/respawn above ran with
                # no lock held (R9 discipline)
                self.router.replace_replica(name, fresh, probation=True)
            except Exception as e:
                delay = b.note_failure()
                self._bump("revival_failures")
                self._recorder.event("autonomics_revive_failed",
                                     replica=name, error=str(e),
                                     retry_in_s=round(delay, 3))
                log.warning("autonomics: revival of replica %r failed "
                            "(%s); retrying in %.2fs (attempt %d)",
                            name, e, delay, b.attempts)
                continue
            with self._lock:
                self._probes[name] = 0
            self._bump("revivals")
            self._recorder.event("autonomics_revived", replica=name,
                                 attempts=b.attempts)
            log.info("autonomics: replica %r revived; probation until "
                     "%d healthy ticks at fleet goodput", name,
                     self.probe_window)

    def _probation_tick(self, rsnap: Dict, sig: Optional[Dict]) -> None:
        good_ratio = (self.signals.knee.good_ratio
                      if self.signals is not None else 0.9)
        interval_good = 1.0
        if sig is not None:
            interval_good = float(
                sig.get("interval", {}).get("good_fraction", 1.0))
        for name, info in sorted(rsnap.get("replicas", {}).items()):
            if not info.get("probation"):
                continue
            healthy = (not info.get("dead")
                       and info.get("health") == "ok"
                       and interval_good >= good_ratio)
            with self._lock:
                streak = self._probes.get(name, 0)
                streak = streak + 1 if healthy else 0
                self._probes[name] = streak
            if streak < self.probe_window:
                continue
            self.router.set_probation(name, False)
            self._backoff_for(name).note_success()
            with self._lock:
                self._probes.pop(name, None)
            self._bump("promotions")
            self._recorder.event("autonomics_promoted", replica=name)
            log.info("autonomics: replica %r cleared probation after %d "
                     "healthy ticks; back in the ok tier", name,
                     self.probe_window)

    def _placement_tick(self) -> None:
        if self.scraper is None:
            return
        try:
            fleet = self.scraper.latest()
        except Exception as e:           # a scrape may race a dying replica
            log.warning("autonomics: placement skipped — no fleet "
                        "snapshot (%s)", e)
            return
        live = self.router.replica_names(live_only=True)
        n_models = ((fleet.get("merged") or {}).get("registry") or {}) \
            .get("registered_models", 0)
        if len(live) < 2 or n_models < 2:
            return                       # nothing to place
        plan = plan_from_fleet(fleet, live,
                               budget_bytes=self.placement_budget_bytes,
                               spread=self.placement_spread)
        with self._lock:
            if plan == self._plan:
                return
            changes = plan_changes(self._plan, plan)
            self._plan = plan
        # prefetch BEFORE routing flips: the readmission compile lands on
        # the replica while its traffic still flows elsewhere
        for model, names in sorted(changes.items()):
            for rname in names:
                try:
                    self.router.prefetch(model, rname)
                    self._bump("prefetches")
                except Exception as e:
                    log.warning("autonomics: prefetch of model %r on "
                                "replica %r failed: %s", model, rname, e)
        self.router.set_placement(plan)
        self._bump("placement_updates")
        self._recorder.event("autonomics_placement",
                             models=len(plan),
                             moves=sum(len(v) for v in changes.values()))

    def _autoscale_tick(self, sig: Optional[Dict]) -> None:
        if sig is None or self.max_replicas <= 0:
            return
        good = sig.get("goodput") or {}
        knee = float(good.get("knee_rps", 0.0))
        margin = float(good.get("knee_margin", 0.0))
        with self._lock:
            if knee <= 0.0:
                # no demonstrated knee: no evidence, no action
                self._out_streak = self._in_streak = 0
                return
            if margin <= self.scale_out_margin:
                self._out_streak += 1
                self._in_streak = 0
            elif margin >= self.scale_in_margin:
                self._in_streak += 1
                self._out_streak = 0
            else:
                self._out_streak = self._in_streak = 0
            out_due = self._out_streak >= self.hysteresis_ticks
            in_due = self._in_streak >= self.hysteresis_ticks
            cooled = (self._last_scale_at is None
                      or self._clock() - self._last_scale_at
                      >= self.cooldown_s)
        if not cooled:
            return
        live = self.router.replica_names(live_only=True)
        if out_due and self._scale_fn is not None \
                and len(live) < self.max_replicas:
            with self._lock:
                idx = self._scale_seq
                self._scale_seq += 1
            try:
                replica = self._scale_fn(idx)   # build/compile: no lock
            except Exception as e:
                log.warning("autonomics: scale-out replica build failed: "
                            "%s", e)
                return
            if replica is None:
                return
            self.router.add_replica(replica, probation=False)
            with self._lock:
                self._scaled.append(replica.name)
                self._last_scale_at = self._clock()
                self._out_streak = 0
            self._bump("scale_outs")
            self._recorder.event("autonomics_scale_out",
                                 replica=replica.name,
                                 knee_margin=round(margin, 4))
            log.info("autonomics: scaled OUT (+%r) at knee_margin %.3f "
                     "<= %.3f", replica.name, margin,
                     self.scale_out_margin)
        elif in_due and len(live) > self.min_replicas:
            with self._lock:
                name = self._scaled.pop() if self._scaled else None
            if name is None or name not in live:
                # only retire replicas this controller added: the
                # operator's configured fleet is a floor, not a pool
                return
            self.router.remove_replica(name, close=True)
            with self._lock:
                self._last_scale_at = self._clock()
                self._in_streak = 0
            self._bump("scale_ins")
            self._recorder.event("autonomics_scale_in", replica=name,
                                 knee_margin=round(margin, 4))
            log.info("autonomics: scaled IN (-%r) at knee_margin %.3f "
                     ">= %.3f", name, margin, self.scale_in_margin)

    # -- delta rollout ---------------------------------------------------
    def rollout_delta(self, source, model: Optional[str] = None,
                      base_source=None) -> Dict:
        """Fleet-atomic model rollout, appended trees only.

        Computes the delta from the deployed base text (cached from the
        previous rollout, or ``base_source``, or a live local replica's
        registry) to ``source``; applies it to every live replica IN
        ORDER; on any per-replica failure, the replicas that already
        committed are swapped BACK to the base text before the failure
        propagates — the fleet is never left mixed-generation. A source
        that does not extend the base falls back to a full fleet swap
        (same atomicity protocol). Returns a summary dict
        (mode/replicas/bytes)."""
        from .delta import delta_bytes, make_delta
        mname = model if model is not None else DEFAULT_MODEL
        new_text = _text_of_source(source)
        base_text = self._resolve_base(mname, base_source)
        delta = make_delta(base_text, new_text)
        names = self.router.replica_names(live_only=True)
        if not names:
            raise SwapFailed("delta rollout: no live replica")
        mode = "delta" if delta is not None else "full"
        applied: List[str] = []
        failure: Optional[Exception] = None
        failed_on = None
        for name in names:
            try:
                if delta is not None:
                    self.router.swap_delta_on(name, delta, model=model)
                else:
                    self.router.swap_on(name, new_text, model=model)
                applied.append(name)
            except Exception as e:
                failure, failed_on = e, name
                break
        if failure is not None:
            rolled = []
            for name in applied:         # un-commit: back to the base
                try:
                    self.router.swap_on(name, base_text, model=model)
                    rolled.append(name)
                except Exception as e:
                    log.warning("autonomics: rollback of replica %r "
                                "failed too (%s); it is now degraded "
                                "until the next successful rollout",
                                name, e)
            self._bump("delta_rollbacks")
            self._recorder.event("autonomics_rollout_rolled_back",
                                 model=mname, failed_on=failed_on,
                                 rolled_back=rolled)
            raise SwapFailed(
                f"{mode} rollout of model {mname!r} failed on replica "
                f"{failed_on!r} ({failure}); rolled back "
                f"{rolled or 'nothing'} — the fleet stays on the base "
                "generation") from failure
        with self._lock:
            self._base_texts[mname] = new_text
        self._bump("delta_rollouts" if delta is not None
                   else "full_rollouts")
        out = {"mode": mode, "model": mname, "replicas": list(names),
               "full_bytes": len(new_text.encode("utf-8"))}
        if delta is not None:
            out["delta_bytes"] = delta_bytes(delta)
            out["appended_trees_bytes"] = len(
                str(delta["append"]).encode("utf-8"))
        self._recorder.event("autonomics_rollout", **{
            k: v for k, v in out.items() if k != "replicas"})
        log.info("autonomics: %s rollout of model %r landed on %d "
                 "replica(s)%s", mode, mname, len(names),
                 f" ({out.get('delta_bytes', 0)} delta bytes vs "
                 f"{out['full_bytes']} full)" if delta is not None else "")
        return out

    def _resolve_base(self, mname: str, base_source) -> str:
        with self._lock:
            cached = self._base_texts.get(mname)
        if cached is not None:
            return cached
        if base_source is not None:
            text = _text_of_source(base_source)
        else:
            text = None
            for name in self.router.replica_names(live_only=True):
                r = self.router.replica(name)
                if hasattr(r, "server"):
                    text = r.server.model_text(mname)
                    break
            if text is None:
                raise SwapFailed(
                    f"delta rollout of model {mname!r} needs a base: no "
                    "prior rollout cached, no base_source given, and no "
                    "local replica to read the resident text from")
        with self._lock:
            self._base_texts.setdefault(mname, text)
        return text

    # -- lifecycle / reporting ------------------------------------------
    def start(self) -> "Autonomics":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="lambdagap-autonomics")
        self._thread.start()
        log.info("autonomics controller up: every %.2fs (probe window "
                 "%d, scale margins out<=%.2f in>=%.2f, replicas "
                 "[%d, %s])", self.interval_s, self.probe_window,
                 self.scale_out_margin, self.scale_in_margin,
                 self.min_replicas, self.max_replicas or "fixed")
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:       # the loop must outlive one bad tick
                log.warning("autonomics: tick failed (%s); continuing", e)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "probation": dict(self._probes),
                "backoffs": {n: b.snapshot()
                             for n, b in sorted(self._backoffs.items())
                             if b.attempts or not b.ready()},
                "placement_models": len(self._plan),
                "scaled_replicas": list(self._scaled),
                "streaks": {"out": self._out_streak,
                            "in": self._in_streak},
            }
