"""Micro-batching request queue: coalesce concurrent predicts into one
padded device dispatch.

A single worker thread drains a thread-safe queue under a
max-batch/max-latency policy (the classic dynamic-batching scheduler of
TF-Serving/Triton): the first request of a batch opens a window of
``max_delay_ms``; everything arriving inside the window joins, up to
``max_batch`` rows, then the whole batch runs as ONE compiled-forest
dispatch. Batch-size-1 request streams therefore pay one device dispatch
per ~``max_batch`` requests instead of one each — the coalescing half of
serve's throughput win (the compile-once half lives in cache.py).

All device work happens on the worker thread; ``submit`` only enqueues, so
any number of client threads can call it concurrently.

Multi-tenant fairness (docs/serving.md "Tenancy"): the queue is a
:class:`FairQueue` — per-tenant FIFO lanes drained by start-time fair
queuing (each tenant carries a virtual clock advanced by ``1/weight`` per
dequeued request), so a tenant flooding the queue cannot starve the
others: dequeue bandwidth converges to the weight ratio, not the arrival
ratio. On top of the bounded queue sits per-tenant admission control
(``max_share``): one tenant may hold at most that fraction of the queue's
capacity, and a submit beyond the quota is rejected at the door with
:class:`ServeOverloaded` naming the tenant — the hot tenant pays, not the
fleet.

Degradation contract (lambdagap_tpu.guard, docs/robustness.md): the queue
is bounded by ``max_queue`` requests with a ``reject``-or-``block``
backpressure policy (reject raises :class:`ServeOverloaded` at submit
time); each request carries an optional deadline (``timeout_ms``) and is
SHED before dispatch once expired — its future resolves with
:class:`ServeTimeout` instead of wasting a device batch on a response
nobody is waiting for. Submit-after-close raises immediately, and the
submit/close race is closed by a mutex: a submit that won the race is
strictly FIFO-before the shutdown sentinels (the fair queue hands out
sentinels only once every lane is empty), so its future always resolves.
Every submitted future therefore terminates: result, error, or timeout —
never a hang.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

import numpy as np

from ..guard.degrade import ServeOverloaded, ServeTimeout


class Request:
    """One queued predict: rows + the future its caller waits on, plus the
    registry model it targets, the tenant it bills to, and (when sampled)
    the trace context its spans parent to (obs/trace.py)."""

    __slots__ = ("x", "future", "t_submit", "t_wall", "deadline", "model",
                 "tenant", "trace")

    def __init__(self, x: np.ndarray, deadline: Optional[float] = None,
                 model: Optional[str] = None,
                 tenant: Optional[str] = None,
                 trace=None) -> None:
        self.x = x
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.t_wall = time.time()        # epoch twin: span t0s align across processes
        self.deadline = deadline         # absolute perf_counter time, or None
        self.model = model               # registry model name (None = default)
        self.tenant = tenant             # accounting/fairness key (optional)
        self.trace = trace               # TraceContext or None (untraced)

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.perf_counter())
                >= self.deadline)


_SENTINEL = object()


class Empty(Exception):
    """FairQueue.get timed out with nothing to hand out."""


class FairQueue:
    """Bounded multi-tenant queue: per-tenant FIFO lanes + weighted fair
    dequeue (start-time fair queuing) + per-tenant admission quotas.

    ``try_put`` returns ``"ok"``, ``"full"`` (global bound) or ``"quota"``
    (tenant over its ``max_share`` of capacity) instead of raising, so the
    caller owns the backpressure policy. Sentinels (worker shutdown
    markers) are handed out only once every lane is empty, which is what
    makes close() drain-safe: an accepted request is always dequeued
    before any worker sees its exit marker.
    """

    def __init__(self, maxsize: int = 0,
                 weights: Optional[Dict[str, float]] = None,
                 max_share: float = 0.0) -> None:
        self._cond = threading.Condition()
        self.maxsize = max(int(maxsize), 0)
        self._weights = {k: float(v) for k, v in (weights or {}).items()
                         if float(v) > 0}
        self.max_share = float(max_share)
        self._lanes: Dict[str, deque] = {}
        self._vt: Dict[str, float] = {}   # per-tenant virtual finish time
        self._vnow = 0.0                  # global virtual clock
        self._size = 0
        self._sentinels = 0

    def qsize(self) -> int:
        with self._cond:
            return self._size

    def _lane_key(self, req: Request) -> str:
        return req.tenant if req.tenant is not None else ""

    def try_put(self, req: Request) -> str:
        with self._cond:
            if self.maxsize and self._size >= self.maxsize:
                return "full"
            key = self._lane_key(req)
            lane = self._lanes.get(key)
            if (self.maxsize and self.max_share > 0.0
                    and lane is not None
                    and len(lane) >= max(1, int(self.max_share
                                                * self.maxsize))):
                return "quota"
            if lane is None:
                lane = self._lanes[key] = deque()
                # a tenant joining (or re-joining after idling) starts at
                # the current virtual clock: idle time earns no backlog
                # credit against the tenants that kept the device busy
                self._vt[key] = max(self._vt.get(key, 0.0), self._vnow)
            lane.append(req)
            self._size += 1
            self._cond.notify()
            return "ok"

    def put_sentinel(self, n: int = 1) -> None:
        with self._cond:
            self._sentinels += n
            self._cond.notify_all()

    def _pop_locked(self):
        best = None
        for key, lane in self._lanes.items():
            if lane and (best is None or self._vt[key] < self._vt[best]):
                best = key
        if best is not None:
            req = self._lanes[best].popleft()
            self._size -= 1
            if not self._lanes[best]:
                del self._lanes[best]    # vt survives for fairness history
            self._vnow = self._vt[best]
            self._vt[best] += 1.0 / self._weights.get(best, 1.0)
            return req
        if self._sentinels > 0:
            self._sentinels -= 1
            return _SENTINEL
        return None

    def get(self, timeout: Optional[float] = None):
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while True:
                item = self._pop_locked()
                if item is not None:
                    return item
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        item = self._pop_locked()
                        if item is not None:
                            return item
                        raise Empty
                    self._cond.wait(remaining)

    def get_nowait(self):
        with self._cond:
            item = self._pop_locked()
            if item is None:
                raise Empty
            return item


class MicroBatcher:
    """Coalesce submitted rows into batches for ``run_batch``.

    run_batch: callable(List[Request]) — must resolve every request's
    future (result or exception). Exceptions escaping it are fanned out to
    the batch's unresolved futures so no caller ever hangs.
    """

    def __init__(self, run_batch: Callable[[List[Request]], None],
                 max_batch: int = 4096, max_delay_ms: float = 2.0,
                 workers: int = 1, stats=None,
                 max_queue: int = 0, backpressure: str = "reject",
                 timeout_ms: float = 0.0, health=None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tenant_max_share: float = 0.0,
                 name: str = "lambdagap-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if backpressure not in ("reject", "block"):
            raise ValueError(f"unknown backpressure policy {backpressure!r}")
        self._run = run_batch
        self.max_batch = int(max_batch)
        self.max_delay = max(float(max_delay_ms), 0.0) / 1e3
        self.timeout = max(float(timeout_ms), 0.0) / 1e3
        self.backpressure = backpressure
        self.stats = stats
        self.health = health
        self._q = FairQueue(maxsize=max(int(max_queue), 0),
                            weights=tenant_weights,
                            max_share=tenant_max_share)
        self._closed = False
        # serializes the closed-flag check against enqueue: a submit that
        # saw _closed == False enqueued BEFORE close() put the sentinels,
        # so the fair queue's drain-first contract guarantees a worker
        # resolves it (the old check-then-put race could strand a future
        # on a dead queue forever)
        self._submit_lock = threading.Lock()
        # >1 workers overlap independent batch dispatches (jitted calls
        # release the GIL while executing); correctness is per-batch, so
        # workers share nothing but the queue and the stats lock
        self._threads = [threading.Thread(target=self._loop, daemon=True,
                                          name=f"{name}-{i}")
                         for i in range(max(int(workers), 1))]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray, model: Optional[str] = None,
               tenant: Optional[str] = None, trace=None) -> Future:
        """Enqueue [n, D] float32 rows; returns the Future the worker will
        resolve. Thread-safe. Raises ``RuntimeError`` after close and
        :class:`ServeOverloaded` when the bounded queue is full — or the
        tenant is over its admission quota — under the ``reject`` policy
        (``block`` waits for space instead)."""
        deadline = (time.perf_counter() + self.timeout
                    if self.timeout > 0 else None)
        req = Request(x, deadline=deadline, model=model, tenant=tenant,
                      trace=trace)
        while True:
            with self._submit_lock:
                if self._closed:
                    raise RuntimeError("batcher closed")
                verdict = self._q.try_put(req)
                if verdict == "ok":
                    return req.future
                if self.backpressure == "reject":
                    if self.stats is not None:
                        self.stats.record_rejected(tenant=tenant)
                    if verdict == "quota":
                        raise ServeOverloaded(
                            f"tenant {tenant!r} is over its admission quota "
                            f"({self._q.max_share:.0%} of "
                            f"{self._q.maxsize} queue slots); retry later "
                            "or raise serve_tenant_max_share") from None
                    raise ServeOverloaded(
                        f"serve queue full ({self._q.maxsize} requests); "
                        "retry later or raise serve_max_queue") from None
            # block policy: wait for the workers to drain, outside the lock
            # (never hold the submit lock across a blocking wait)
            time.sleep(0.0005)

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, flush everything already queued, join the
        workers. Queued requests are never dropped: the fair queue hands
        out shutdown sentinels only once every lane is empty, so a worker
        always drains accepted requests before exiting."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        self._q.put_sentinel(len(self._threads))
        for t in self._threads:
            t.join(timeout)

    # ------------------------------------------------------------------
    def _shed(self, req: Request) -> None:
        """Resolve an expired request with ServeTimeout (pre-dispatch)."""
        if not req.future.done():
            waited = time.perf_counter() - req.t_submit
            req.future.set_exception(ServeTimeout(
                f"request deadline expired after {waited * 1e3:.1f}ms in "
                "queue (serve_timeout_ms); shed before dispatch"))
        if self.stats is not None:
            self.stats.record_timeout(model=req.model, tenant=req.tenant)

    def _loop(self) -> None:
        drain = False
        while True:
            try:
                first = self._q.get(timeout=0.1)
            except Empty:
                if drain or self._closed:
                    break
                continue
            if first is _SENTINEL:
                break
            if first.expired():
                self._shed(first)
                continue
            batch = [first]
            rows = first.x.shape[0]
            deadline = first.t_submit + self.max_delay
            while rows < self.max_batch:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    # opportunistic non-blocking drain past the deadline:
                    # anything already queued still joins this dispatch
                    try:
                        nxt = self._q.get_nowait()
                    except Empty:
                        break
                else:
                    try:
                        nxt = self._q.get(timeout=wait)
                    except Empty:
                        break
                if nxt is _SENTINEL:
                    drain = True
                    break
                if nxt.expired():
                    self._shed(nxt)
                    continue
                batch.append(nxt)
                rows += nxt.x.shape[0]
            self._dispatch(batch, rows)
            if drain:
                break

    def _dispatch(self, batch: List[Request], rows: int) -> None:
        # final shed pass: a request can expire between joining the batch
        # window and the dispatch itself
        now = time.perf_counter()
        live = []
        for r in batch:
            if r.expired(now):
                self._shed(r)
            else:
                live.append(r)
        if not live:
            return
        if self.stats is not None:
            self.stats.record_batch(len(live), sum(r.x.shape[0]
                                                   for r in live))
        try:
            self._run(live)
        except BaseException as e:  # noqa: BLE001 — worker must survive
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            if self.stats is not None:
                self.stats.record_error()
            if self.health is not None:
                self.health.note_error()
        else:
            if self.health is not None:
                self.health.note_ok()
