"""Micro-batching request queue: coalesce concurrent predicts into one
padded device dispatch.

A single worker thread drains a thread-safe queue under a
max-batch/max-latency policy (the classic dynamic-batching scheduler of
TF-Serving/Triton): the first request of a batch opens a window of
``max_delay_ms``; everything arriving inside the window joins, up to
``max_batch`` rows, then the whole batch runs as ONE compiled-forest
dispatch. Batch-size-1 request streams therefore pay one device dispatch
per ~``max_batch`` requests instead of one each — the coalescing half of
serve's throughput win (the compile-once half lives in cache.py).

All device work happens on the worker thread; ``submit`` only enqueues, so
any number of client threads can call it concurrently.

Degradation contract (lambdagap_tpu.guard, docs/robustness.md): the queue
is bounded by ``max_queue`` requests with a ``reject``-or-``block``
backpressure policy (reject raises :class:`ServeOverloaded` at submit
time); each request carries an optional deadline (``timeout_ms``) and is
SHED before dispatch once expired — its future resolves with
:class:`ServeTimeout` instead of wasting a device batch on a response
nobody is waiting for. Submit-after-close raises immediately, and the
submit/close race is closed by a mutex: a submit that won the race is
strictly FIFO-before the shutdown sentinels, so its future always
resolves. Every submitted future therefore terminates: result, error, or
timeout — never a hang.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional

import numpy as np

from ..guard.degrade import ServeOverloaded, ServeTimeout


class Request:
    """One queued predict: rows + the future its caller waits on."""

    __slots__ = ("x", "future", "t_submit", "deadline")

    def __init__(self, x: np.ndarray,
                 deadline: Optional[float] = None) -> None:
        self.x = x
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline         # absolute perf_counter time, or None

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None else time.perf_counter())
                >= self.deadline)


_SENTINEL = object()


class MicroBatcher:
    """Coalesce submitted rows into batches for ``run_batch``.

    run_batch: callable(List[Request]) — must resolve every request's
    future (result or exception). Exceptions escaping it are fanned out to
    the batch's unresolved futures so no caller ever hangs.
    """

    def __init__(self, run_batch: Callable[[List[Request]], None],
                 max_batch: int = 4096, max_delay_ms: float = 2.0,
                 workers: int = 1, stats=None,
                 max_queue: int = 0, backpressure: str = "reject",
                 timeout_ms: float = 0.0, health=None,
                 name: str = "lambdagap-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if backpressure not in ("reject", "block"):
            raise ValueError(f"unknown backpressure policy {backpressure!r}")
        self._run = run_batch
        self.max_batch = int(max_batch)
        self.max_delay = max(float(max_delay_ms), 0.0) / 1e3
        self.timeout = max(float(timeout_ms), 0.0) / 1e3
        self.backpressure = backpressure
        self.stats = stats
        self.health = health
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(max_queue), 0))
        self._closed = False
        # serializes the closed-flag check against enqueue: a submit that
        # saw _closed == False enqueued BEFORE close() put the sentinels,
        # so FIFO guarantees a worker resolves it (the old check-then-put
        # race could strand a future on a dead queue forever)
        self._submit_lock = threading.Lock()
        # >1 workers overlap independent batch dispatches (jitted calls
        # release the GIL while executing); correctness is per-batch, so
        # workers share nothing but the queue and the stats lock
        self._threads = [threading.Thread(target=self._loop, daemon=True,
                                          name=f"{name}-{i}")
                         for i in range(max(int(workers), 1))]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray) -> Future:
        """Enqueue [n, D] float32 rows; returns the Future the worker will
        resolve. Thread-safe. Raises ``RuntimeError`` after close and
        :class:`ServeOverloaded` when the bounded queue is full under the
        ``reject`` policy (``block`` waits for space instead)."""
        deadline = (time.perf_counter() + self.timeout
                    if self.timeout > 0 else None)
        req = Request(x, deadline=deadline)
        while True:
            with self._submit_lock:
                if self._closed:
                    raise RuntimeError("batcher closed")
                try:
                    self._q.put_nowait(req)
                    return req.future
                except queue.Full:
                    if self.backpressure == "reject":
                        if self.stats is not None:
                            self.stats.record_rejected()
                        raise ServeOverloaded(
                            f"serve queue full ({self._q.maxsize} requests); "
                            "retry later or raise serve_max_queue") from None
            # block policy: wait for the workers to drain, outside the lock
            # (never hold the submit lock across a blocking wait)
            time.sleep(0.0005)

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, flush everything already queued, join the
        workers. Queued requests are never dropped: FIFO ordering puts the
        sentinels after every prior submit, and a worker that misses its
        sentinel still exits once the queue drains (closed + empty)."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        for _ in self._threads:
            # blocking put: on a bounded full queue, wait for the workers
            # to make room (they are draining toward these sentinels)
            self._q.put(_SENTINEL)
        for t in self._threads:
            t.join(timeout)

    # ------------------------------------------------------------------
    def _shed(self, req: Request) -> None:
        """Resolve an expired request with ServeTimeout (pre-dispatch)."""
        if not req.future.done():
            waited = time.perf_counter() - req.t_submit
            req.future.set_exception(ServeTimeout(
                f"request deadline expired after {waited * 1e3:.1f}ms in "
                "queue (serve_timeout_ms); shed before dispatch"))
        if self.stats is not None:
            self.stats.record_timeout()

    def _loop(self) -> None:
        drain = False
        while True:
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                if drain or self._closed:
                    break
                continue
            if first is _SENTINEL:
                break
            if first.expired():
                self._shed(first)
                continue
            batch = [first]
            rows = first.x.shape[0]
            deadline = first.t_submit + self.max_delay
            while rows < self.max_batch:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    # opportunistic non-blocking drain past the deadline:
                    # anything already queued still joins this dispatch
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                else:
                    try:
                        nxt = self._q.get(timeout=wait)
                    except queue.Empty:
                        break
                if nxt is _SENTINEL:
                    drain = True
                    break
                if nxt.expired():
                    self._shed(nxt)
                    continue
                batch.append(nxt)
                rows += nxt.x.shape[0]
            self._dispatch(batch, rows)
            if drain:
                break

    def _dispatch(self, batch: List[Request], rows: int) -> None:
        # final shed pass: a request can expire between joining the batch
        # window and the dispatch itself
        now = time.perf_counter()
        live = []
        for r in batch:
            if r.expired(now):
                self._shed(r)
            else:
                live.append(r)
        if not live:
            return
        if self.stats is not None:
            self.stats.record_batch(len(live), sum(r.x.shape[0]
                                                   for r in live))
        try:
            self._run(live)
        except BaseException as e:  # noqa: BLE001 — worker must survive
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            if self.stats is not None:
                self.stats.record_error()
            if self.health is not None:
                self.health.note_error()
        else:
            if self.health is not None:
                self.health.note_ok()
