"""Micro-batching request queue: coalesce concurrent predicts into one
padded device dispatch.

A single worker thread drains a thread-safe queue under a
max-batch/max-latency policy (the classic dynamic-batching scheduler of
TF-Serving/Triton): the first request of a batch opens a window of
``max_delay_ms``; everything arriving inside the window joins, up to
``max_batch`` rows, then the whole batch runs as ONE compiled-forest
dispatch. Batch-size-1 request streams therefore pay one device dispatch
per ~``max_batch`` requests instead of one each — the coalescing half of
serve's throughput win (the compile-once half lives in cache.py).

All device work happens on the worker thread; ``submit`` only enqueues, so
any number of client threads can call it concurrently.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List

import numpy as np


class Request:
    """One queued predict: rows + the future its caller waits on."""

    __slots__ = ("x", "future", "t_submit")

    def __init__(self, x: np.ndarray) -> None:
        self.x = x
        self.future: Future = Future()
        self.t_submit = time.perf_counter()


_SENTINEL = object()


class MicroBatcher:
    """Coalesce submitted rows into batches for ``run_batch``.

    run_batch: callable(List[Request]) — must resolve every request's
    future (result or exception). Exceptions escaping it are fanned out to
    the batch's unresolved futures so no caller ever hangs.
    """

    def __init__(self, run_batch: Callable[[List[Request]], None],
                 max_batch: int = 4096, max_delay_ms: float = 2.0,
                 workers: int = 1, stats=None,
                 name: str = "lambdagap-serve-batcher") -> None:
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._run = run_batch
        self.max_batch = int(max_batch)
        self.max_delay = max(float(max_delay_ms), 0.0) / 1e3
        self.stats = stats
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        # >1 workers overlap independent batch dispatches (jitted calls
        # release the GIL while executing); correctness is per-batch, so
        # workers share nothing but the queue and the stats lock
        self._threads = [threading.Thread(target=self._loop, daemon=True,
                                          name=f"{name}-{i}")
                         for i in range(max(int(workers), 1))]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------------
    def submit(self, x: np.ndarray) -> Future:
        """Enqueue [n, D] float32 rows; returns the Future the worker will
        resolve. Thread-safe."""
        if self._closed:
            raise RuntimeError("MicroBatcher is closed")
        req = Request(x)
        self._q.put(req)
        return req.future

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting work, flush everything already queued, join the
        workers. Queued requests are never dropped: FIFO ordering puts the
        sentinels after every prior submit, and a worker that misses its
        sentinel still exits once the queue drains (closed + empty)."""
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._q.put(_SENTINEL)
        for t in self._threads:
            t.join(timeout)

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        drain = False
        while True:
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                if drain or self._closed:
                    break
                continue
            if first is _SENTINEL:
                break
            batch = [first]
            rows = first.x.shape[0]
            deadline = first.t_submit + self.max_delay
            while rows < self.max_batch:
                wait = deadline - time.perf_counter()
                if wait <= 0:
                    # opportunistic non-blocking drain past the deadline:
                    # anything already queued still joins this dispatch
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                else:
                    try:
                        nxt = self._q.get(timeout=wait)
                    except queue.Empty:
                        break
                if nxt is _SENTINEL:
                    drain = True
                    break
                batch.append(nxt)
                rows += nxt.x.shape[0]
            self._dispatch(batch, rows)
            if drain:
                break

    def _dispatch(self, batch: List[Request], rows: int) -> None:
        if self.stats is not None:
            self.stats.record_batch(len(batch), rows)
        try:
            self._run(batch)
        except BaseException as e:  # noqa: BLE001 — worker must survive
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
            if self.stats is not None:
                self.stats.record_error()
