"""Device-resident compiled forest with padding-bucket executables.

The one-shot predict path converts the forest to device arrays on every
call; serving amortizes that to zero: ``CompiledForestCache`` stacks the
booster's trees into :class:`~lambdagap_tpu.ops.predict.TreeArrays` blocks
ONCE (they stay resident in HBM), and routes every request batch through a
small set of fixed padding buckets (default 1/8/64/512/4096 rows) so
arbitrary request sizes always hit an already-compiled XLA executable —
the serving analog of the reference's ``SingleRowPredictorInner`` keeping
one predictor object warm per booster (reference: src/c_api.cpp:63), but
for whole padded device batches.

Caches are keyed by ``(model_generation, start_iteration, num_iteration)``;
any in-place mutation of the booster bumps its generation
(``GBDT.invalidate_predict_cache``), so a stale compiled forest can never
be served.

Numerics: a bucket dispatch runs the exact device ops of the one-shot
``GBDT.predict_raw`` device branch (same stacked blocks, same scan, same
elementwise transform), and rows are independent under ``vmap``, so padded
batches return bit-identical outputs to a direct ``Booster.predict`` that
takes the device path.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.predict import build_forest_blocks, forest_to_arrays, predict_forest
from ..ops.predict_tensor import build_tree_tiles, predict_forest_tensor
from ..utils import log

# powers chosen so the jump between buckets wastes at most ~8x padding on
# pathological sizes while keeping the compiled-executable set tiny
DEFAULT_BUCKETS = (1, 8, 64, 512, 4096)


def _plan(buckets, n: int):
    """Greedy (rows, bucket) decomposition shared by the per-model cache
    and the cross-model pack: full buckets dispatch unpadded, a padded
    dispatch is only taken when its bucket is at most 2x the remaining
    rows (or nothing smaller fits)."""
    out = []
    rem = n
    while rem > 0:
        b_pad = next((b for b in buckets if b >= rem), None)
        b_full = next((b for b in reversed(buckets) if b <= rem), None)
        if b_pad is not None and (b_full is None or b_pad <= 2 * rem):
            out.append((rem, b_pad))
            rem = 0
        else:
            out.append((b_full, b_full))
            rem -= b_full
    return out


class CompiledForestCache:
    """One booster generation, compiled for serving.

    Parameters
    ----------
    gbdt: models.gbdt.GBDT — the loaded booster.
    buckets: padded batch sizes to pre-compile (sorted, deduped).
    start_iteration / num_iteration: forest slice, as in ``predict``.
    generation: serving generation id stamped on every response.
    stats: optional ``ServeStats`` for cache accounting.
    artifact_store: optional ``infer.ArtifactStore`` — under
        ``predict_engine=compiled`` the build consults it by source key
        before paying a local forest compile (a fleet peer may have
        shipped the artifact already) and publishes local compiles into
        it; admissions vs local compiles are counted in ``ServeStats``.
    """

    def __init__(self, gbdt, buckets: Optional[Sequence[int]] = None,
                 start_iteration: int = 0, num_iteration: int = -1,
                 generation: int = 0, stats=None,
                 tree_block: Optional[int] = None,
                 artifact_store=None) -> None:
        self.gbdt = gbdt
        self.generation = int(generation)
        self.start_iteration = int(start_iteration)
        self.num_iteration = int(num_iteration)
        self.stats = stats
        bl = tuple(sorted({int(b) for b in (buckets or DEFAULT_BUCKETS)
                           if int(b) > 0}))
        if not bl:
            raise ValueError("serve needs at least one positive bucket size")
        self.buckets = bl
        self.key = (getattr(gbdt, "generation", 0),
                    self.start_iteration, self.num_iteration)

        idx = gbdt._model_slice(start_iteration, num_iteration)
        gbdt._materialize_lazy(idx)
        trees = [gbdt._tree(i) for i in idx]
        # linear forests compile like constant ones: the padded per-leaf
        # coefficient tables ride the stacked TreeArrays and the traversal
        # carry accumulates each leaf's dot product on device
        # (docs/linear-trees.md), so every bucket/registry/router/frontend
        # path serves linear models bit-identically to device predict
        self.has_linear = any(getattr(t, "is_linear", False) for t in trees)
        self.idx = idx
        self.num_class = gbdt.num_tree_per_iteration
        # matrix width the compiled executables expect: 1 + max split
        # feature. Wider request rows are truncated (trailing columns can
        # never be gathered by any node), narrower ones are padded by the
        # server under predict_disable_shape_check.
        self.width = max(1, 1 + max(
            (max(t.split_feature[:t.num_internal], default=0)
             for t in trees), default=0)) if trees else 1
        if tree_block is None:
            tree_block = int(os.environ.get("LAMBDAGAP_PREDICT_TREE_BLOCK",
                                            64))
        self._tree_block = tree_block
        # traversal engine: the tensorized [rows x trees] engine is the
        # serving default (predict_engine=tensor); the sequential scan
        # stays selectable for differential testing. Both are bit-identical
        # (ops/predict_tensor.py contract), so the serve-vs-predict parity
        # guarantee above holds under either engine.
        self.engine = gbdt.config.predict_engine
        self._tree_tile = int(gbdt.config.predict_tree_tile)
        if idx and self.engine != "compiled":
            forest, depth = forest_to_arrays(trees, use_inner_feature=False)
            tree_class = jnp.asarray([i % self.num_class for i in idx],
                                     jnp.int32)
            self._forest = jax.device_put(forest)
            self._depth = depth
            self._tree_class = tree_class
            if self.engine == "tensor":
                self._blocks = build_tree_tiles(self._forest, tree_class,
                                                self._tree_tile)
            else:
                self._blocks = build_forest_blocks(self._forest, tree_class,
                                                   tree_block)
        else:
            self._forest = None
            self._depth = 8
            self._tree_class = jnp.zeros(0, jnp.int32)
            self._blocks = None
        cfg = gbdt.config
        obj = gbdt.objective
        # margin-based prediction early stop, same gating as predict_raw
        self._es_freq = (cfg.pred_early_stop_freq * self.num_class
                         if cfg.pred_early_stop and obj is not None
                         and obj.name in ("binary", "multiclass",
                                          "multiclassova") else 0)
        self._es_margin = float(cfg.pred_early_stop_margin)
        self._n_iters = max(1, len(idx) // max(self.num_class, 1))
        # compiled engine: serve the infer/ artifact instead of the
        # training-shaped tables. The artifact is content-addressed, so a
        # replica whose store already holds this model's compile (shipped
        # over the wire by a peer) skips the lowering entirely — that
        # admission-vs-local split is the compile_shared_total metric.
        self.artifact = None
        self.artifact_hash = None
        self._compiled = None
        if idx and self.engine == "compiled":
            from ..infer import CompiledForest, compile_forest, source_key_of
            art = None
            if artifact_store is not None:
                art = artifact_store.get(
                    source_key_of(gbdt, start_iteration, num_iteration))
            if art is not None:
                if stats is not None:
                    stats.record_compile_shared()
            else:
                art = compile_forest(gbdt, start_iteration, num_iteration)
                if artifact_store is not None:
                    artifact_store.put(art)
                if stats is not None:
                    stats.record_compile_local()
            self.artifact = art
            self.artifact_hash = art.hash
            self._compiled = CompiledForest(
                art, early_stop_freq=self._es_freq,
                early_stop_margin=self._es_margin,
                row_block=int(cfg.infer_row_block))
        self._warm: set = set()
        self._warm_lock = threading.Lock()
        self.build_time_s = 0.0
        if stats is not None:
            stats.record_forest_build()

    @property
    def hbm_bytes(self) -> int:
        """Resident device bytes of this compiled forest: the stacked node
        tables plus the engine's tile/block slices. The registry charges
        this against ``serve_hbm_budget_mb`` for LRU eviction; executable
        code size is not counted (XLA does not expose it), so the budget
        governs the dominant term — the forest arrays themselves."""
        total = 0
        for obj in (self._forest, self._blocks, self._tree_class):
            for leaf in jax.tree_util.tree_leaves(obj):
                total += getattr(leaf, "nbytes", 0)
        if self._compiled is not None:
            total += self._compiled.nbytes
        return int(total)

    # ------------------------------------------------------------------
    def bucket_of(self, n: int) -> int:
        """Smallest pre-compiled bucket holding ``n`` rows (requests larger
        than the top bucket are chunked by the caller)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def plan(self, n: int):
        """Greedy decomposition of ``n`` rows into (rows, bucket) dispatches.

        Full buckets dispatch unpadded; a padded dispatch is only taken
        when its bucket is at most 2x the remaining rows (or nothing
        smaller fits), so padding waste per batch stays under 2x instead
        of the up-to-8x a naive round-up to the next bucket can cost
        between sparse bucket sizes."""
        return _plan(self.buckets, n)

    def _dispatch(self, xb: np.ndarray, raw_score: bool) -> jax.Array:
        """One padded bucket through the compiled forest: [num_class, B]."""
        if self._compiled is not None:
            out = self._compiled.predict(jnp.asarray(xb))
        elif self.engine == "tensor":
            out = predict_forest_tensor(
                jnp.asarray(xb), self._forest, self._tree_class,
                self.num_class, self._depth, binned=False,
                early_stop_freq=self._es_freq,
                early_stop_margin=self._es_margin,
                tree_tile=self._tree_tile, tiles=self._blocks,
                has_linear=self.has_linear)
        else:
            out = predict_forest(
                jnp.asarray(xb), self._forest, self._tree_class,
                self.num_class, self._depth, binned=False,
                early_stop_freq=self._es_freq,
                early_stop_margin=self._es_margin,
                tree_block=self._tree_block, blocks=self._blocks,
                has_linear=self.has_linear)
        if self.gbdt.average_output:
            out = out / self._n_iters
        obj = self.gbdt.objective
        if not raw_score and obj is not None:
            out = obj.convert_output(out)
        return out

    def predict(self, X: np.ndarray, raw_score: bool = False,
                record: bool = True) -> np.ndarray:
        """Predict [N, width] float32 rows; returns [N] (one class) or
        [N, K], matching ``Booster.predict`` semantics bit-for-bit on the
        device path. N is chunked by the largest bucket, each chunk padded
        up to its bucket with zero rows that are sliced off after."""
        X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
        if X.ndim != 2:
            raise ValueError(f"serve predict expects 2-D rows, got {X.shape}")
        N = X.shape[0]
        K = self.num_class
        if (self._forest is None and self._compiled is None) or N == 0:
            res = np.zeros((K, N), dtype=np.float32)
            return res[0] if K == 1 else res.T
        from ..obs import costplane
        parts = []
        lo = 0
        t_dispatch = time.perf_counter()
        for n, b in self.plan(N):
            chunk = X[lo:lo + n]
            lo += n
            if n < b:
                chunk = np.concatenate(
                    [chunk, np.zeros((b - n, X.shape[1]), np.float32)])
            with self._warm_lock:        # parallel batch workers share this
                hit = b in self._warm
                if not hit:
                    self._warm.add(b)
            if record and self.stats is not None:
                self.stats.record_cache(hit, bucket=b)
            if not hit and self.stats is not None:
                self.stats.record_bucket_compile(b)
            out = self._dispatch(chunk, raw_score)
            # graftlint: disable=R1 — the terminal D2H of the response is
            # inherent to serving: results must reach the client as numpy
            parts.append(np.asarray(jax.device_get(out))[:, :n])
        # every chunk ended in a device_get, so this wall is device-
        # complete — the serve-side join the cost plane's roofline uses
        costplane.PLANE.note_wall("serve_dispatch",
                                  time.perf_counter() - t_dispatch,
                                  calls=len(parts))
        res = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=1)
        return res[0] if K == 1 else res.T

    def warm(self) -> float:
        """Compile + execute every bucket once on zero rows so the first
        real request of any size hits a warm executable. Returns the time
        spent (also kept as ``build_time_s``); warm dispatches do not count
        toward hit/miss stats."""
        t0 = time.perf_counter()
        for b in self.buckets:
            self.predict(np.zeros((b, self.width), np.float32), record=False)
        self.build_time_s = time.perf_counter() - t0
        log.info("serve: warmed %d padding buckets %s in %.2fs "
                 "(generation %d, %d trees, %s engine)", len(self.buckets),
                 list(self.buckets), self.build_time_s, self.generation,
                 len(self.idx), self.engine)
        return self.build_time_s


class ModelPack:
    """Padding buckets extended ACROSS models (serve_pack_models).

    The per-model cache pads a request batch up to a bucket so it hits a
    warm executable; at millions-of-tenants scale the dispatch COUNT is
    the bottleneck — a mixed FairQueue batch touching M tiny per-tenant
    models still costs M dispatches. A ModelPack fuses the resident
    compiled models into ONE :class:`infer.engine.PackedForests`
    executable: the mixed batch concatenates into one padded bucket with a
    per-row model id, the O(trees) traversal + accumulation dispatches
    ONCE, and only the per-model averaging/objective conversion (cheap
    elementwise on the [K, n_i] score slices) runs per member afterwards.

    Bit-identity: each row's raw scores out of the packed dispatch are
    value-identical to its member cache serving the row alone (masked
    foreign trees contribute exact ``+0.0``; see PackedForests), and the
    averaging/conversion here reuses the member's own ``_dispatch`` tail
    ops — ``tests/test_infer.py`` asserts equality across the pack.

    Members must be compiled-engine caches without prediction early stop;
    the registry rebuilds packs whenever membership or any member's
    generation changes (the pack key is the (name, generation) tuple set).
    """

    def __init__(self, members, buckets: Optional[Sequence[int]] = None,
                 stats=None) -> None:
        from ..infer import PackedForests
        if not members:
            raise ValueError("ModelPack needs at least one member cache")
        for name, c in members.items():
            if c._compiled is None:
                raise ValueError(
                    f"model {name!r} has no compiled forest (pack members "
                    "need predict_engine=compiled and a nonempty tree slice)")
            if c._es_freq:
                raise ValueError(
                    f"model {name!r} uses prediction early stop; packs "
                    "cannot replay a per-model tree-count stop")
        self.members = dict(members)
        self.stats = stats
        self.packed = PackedForests(
            {n: c._compiled for n, c in self.members.items()})
        self.width = self.packed.width
        bl = tuple(sorted({int(b) for b in (buckets or DEFAULT_BUCKETS)
                           if int(b) > 0}))
        self.buckets = bl or DEFAULT_BUCKETS
        self.key = frozenset((n, c.key) for n, c in self.members.items())
        self._warm: set = set()
        self._warm_lock = threading.Lock()

    @property
    def hbm_bytes(self) -> int:
        return int(self.packed.nbytes)

    def predict_mixed(self, parts, record: bool = True):
        """parts: list of ``(model_name, X [n_i, >=width_i], raw_score)``.
        Returns one output per part, each matching what the member cache's
        ``predict`` would have returned — but the whole mixed batch pays
        ONE traversal dispatch per padded bucket instead of one per model.
        """
        Xs, rms, ns = [], [], []
        for name, X, _raw in parts:
            X = np.ascontiguousarray(np.asarray(X, dtype=np.float32))
            if X.ndim != 2:
                raise ValueError(
                    f"serve predict expects 2-D rows, got {X.shape}")
            if X.shape[1] > self.width:
                X = X[:, :self.width]
            elif X.shape[1] < self.width:
                # a member model never gathers past its own width, so the
                # pad value is unreachable for this row's trees
                X = np.concatenate(
                    [X, np.full((X.shape[0], self.width - X.shape[1]),
                                np.nan, np.float32)], axis=1)
            Xs.append(X)
            rms.append(np.full(X.shape[0],
                               self.packed.model_index[name], np.int32))
            ns.append(X.shape[0])
        X = np.concatenate(Xs)
        rm = np.concatenate(rms)
        N = X.shape[0]
        outs = []
        lo = 0
        for n, b in _plan(self.buckets, N):
            xb, rb = X[lo:lo + n], rm[lo:lo + n]
            lo += n
            if n < b:
                xb = np.concatenate(
                    [xb, np.zeros((b - n, self.width), np.float32)])
                rb = np.concatenate([rb, np.zeros(b - n, np.int32)])
            with self._warm_lock:
                hit = b in self._warm
                if not hit:
                    self._warm.add(b)
            if record and self.stats is not None:
                self.stats.record_cache(hit, bucket=b)
            if not hit and self.stats is not None:
                self.stats.record_bucket_compile(b)
            outs.append(self.packed.predict(xb, rb)[:, :n])
        raw = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=1)
        res = []
        lo = 0
        for (name, _X, raw_score), n in zip(parts, ns):
            c = self.members[name]
            K = c.num_class
            out = raw[:K, lo:lo + n]
            lo += n
            # the member cache's _dispatch tail, op for op (bit-identity)
            if c.gbdt.average_output:
                out = out / c._n_iters
            obj = c.gbdt.objective
            if not raw_score and obj is not None:
                out = obj.convert_output(out)
            # graftlint: disable=R1 — the terminal D2H of the response is
            # inherent to serving: results must reach the client as numpy
            part = np.asarray(jax.device_get(out))
            res.append(part[0] if K == 1 else part.T)
        return res

    def warm(self) -> float:
        """Pre-compile every pack bucket (zero rows, model 0)."""
        name = next(iter(self.members))
        t0 = time.perf_counter()
        for b in self.buckets:
            self.predict_mixed(
                [(name, np.zeros((b, self.width), np.float32), True)],
                record=False)
        return time.perf_counter() - t0
