"""Delta hot-swap: ship only appended trees over the wire.

A continuously trained booster grows by appending trees; the rest of the
model text — every already-deployed tree block — is byte-identical
between generations (the resume/replay contract: ``tree_to_string`` is a
stable round-trip, and continued training never rewrites a finished
tree). A fleet rollout that re-ships the whole model text therefore
moves O(total trees) bytes per replica to communicate O(new trees) of
information; at the million-user shape (large forests, frequent refresh,
many replicas) the full-text swap frame IS the rollout cost.

The model text is line-oriented and tree-bucketed (``Tree=N`` blocks
between the header and the ``end of trees`` marker — models/model_text),
so a delta is a pure text splice:

- :func:`make_delta` compares base and new text and returns a wire-safe
  dict — the new header (its ``tree_sizes`` row changed), the APPENDED
  tree blocks only, the new tail, and a hash of the base's tree region
  so a stale replica can never splice onto the wrong foundation. Returns
  ``None`` when the new model does not extend the base (caller falls
  back to a full swap — a delta is an optimization, not a contract).
- :func:`apply_delta` reconstructs the full new model text on the
  replica from its OWN resident base text + the delta, verifying tree
  count and hash first (:class:`DeltaMismatch` on any disagreement).

The reconstructed text then takes the NORMAL swap path — load, compile,
pre-warm, generation flip, circuit breaker on failure — so delta swaps
inherit every rollback guarantee the full swap already proves
(docs/serving.md "Delta hot-swap"). Only the wire frame shrinks.
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

DELTA_FORMAT = 1
_END = "end of trees"


class DeltaMismatch(ValueError):
    """The delta's base does not match the replica's resident model."""


def split_model_text(text: str) -> Tuple[str, List[str], str]:
    """``(header, tree_blocks, tail)`` such that
    ``header + "".join(tree_blocks) + "end of trees" + tail`` equals
    ``text`` byte-for-byte. Each block keeps its ``Tree=N`` prefix."""
    if _END not in text:
        raise ValueError("model text has no 'end of trees' marker")
    head, tail = text.split(_END, 1)
    parts = head.split("Tree=")
    header = parts[0]
    blocks = [f"Tree={p}" for p in parts[1:]]
    return header, blocks, tail


def _tree_hash(blocks: List[str], n: Optional[int] = None) -> str:
    region = "".join(blocks if n is None else blocks[:n])
    return hashlib.sha256(region.encode("utf-8")).hexdigest()


def make_delta(base_text: str, new_text: str) -> Optional[Dict]:
    """The wire delta from ``base_text`` to ``new_text``, or None when
    the new model is not a pure tree-append extension of the base (tree
    count shrank, or any shared tree block changed bytes)."""
    base_header, base_blocks, base_tail = split_model_text(base_text)
    new_header, new_blocks, new_tail = split_model_text(new_text)
    n = len(base_blocks)
    if len(new_blocks) < n or new_blocks[:n] != base_blocks:
        return None
    return {
        "format": DELTA_FORMAT,
        "base_trees": n,
        "base_hash": _tree_hash(base_blocks),
        "append": "".join(new_blocks[n:]),
        "header": new_header,
        "tail": new_tail,
    }


def apply_delta(base_text: str, delta: Dict) -> str:
    """Reconstruct the full new model text from the replica's resident
    base text + a :func:`make_delta` frame. Raises :class:`DeltaMismatch`
    when the replica's base is not the delta's base — the caller
    (registry ``swap_delta``) converts that into the breaker-fed
    ``SwapFailed`` rollback path."""
    if not isinstance(delta, dict) or delta.get("format") != DELTA_FORMAT:
        raise DeltaMismatch(
            f"unknown delta format {delta.get('format') if isinstance(delta, dict) else type(delta).__name__!r}")
    for key in ("base_trees", "base_hash", "append", "header", "tail"):
        if key not in delta:
            raise DeltaMismatch(f"delta frame missing {key!r}")
    _header, blocks, _tail = split_model_text(base_text)
    n = int(delta["base_trees"])
    if len(blocks) != n:
        raise DeltaMismatch(
            f"delta expects a {n}-tree base but the resident model has "
            f"{len(blocks)} trees (a swap landed since the delta was "
            "computed); re-sync with a full swap")
    got = _tree_hash(blocks)
    if got != delta["base_hash"]:
        raise DeltaMismatch(
            "delta base hash mismatch: the resident trees are not the "
            "base this delta was computed against; re-sync with a full "
            "swap")
    return (str(delta["header"]) + "".join(blocks) + str(delta["append"])
            + _END + str(delta["tail"]))


def delta_bytes(delta: Dict) -> int:
    """Wire payload size of a delta frame (the number the bench/gate
    compares against the full model text)."""
    return sum(len(str(delta.get(k, "")).encode("utf-8"))
               for k in ("append", "header", "tail"))


def model_text_of(gbdt) -> str:
    """The full model text of a loaded booster — the base a controller
    diffs rollouts against (same serializer as ``GBDT.save_model``)."""
    from ..models.model_text import save_model_to_string
    return save_model_to_string(gbdt)
